# Test tiers (see pytest.ini): the default tier must stay green on every
# commit; the slow tier (multihost subprocess tests, MXU interpret-mode
# kernel matrix, reference-consistency differential tests) must pass
# before a round is declared done. Both run on CPU via tests/conftest.py
# (virtual 8-device mesh); bench.py is the only thing that touches the
# real accelerator.

PY ?= python

.PHONY: test test-slow test-all faults chaos postmortem distributed observe lint lint-sarif lint-ci pipeline kernels perf stream bench serve-chaos serve-bench loop loop-chaos elastic install

test:
	$(PY) -m pytest tests/ -x -q

# tpulint: AST invariant checker (jit hygiene, lock discipline, registry
# consistency — docs/StaticAnalysis.md); exits non-zero on any
# unsuppressed finding, plus the rule-engine's own fixture tests
lint:
	$(PY) -m lightgbm_tpu.analysis lightgbm_tpu --format=json
	$(PY) -m pytest tests/test_static_analysis.py -x -q -m lint

# same run, SARIF 2.1.0 on stdout — for CI diff annotators
lint-sarif:
	$(PY) -m lightgbm_tpu.analysis lightgbm_tpu --format=sarif

# hermetic CI gate: cache disabled (every trace rebuilt from scratch),
# human-readable text on stdout plus tpulint.sarif for annotators
lint-ci:
	$(PY) -m lightgbm_tpu.analysis lightgbm_tpu --no-cache
	$(PY) -m lightgbm_tpu.analysis lightgbm_tpu --no-cache --format=sarif > tpulint.sarif
	$(PY) -m pytest tests/test_static_analysis.py -x -q -m lint

# the pipelined-executor tier: byte-parity vs the serial block loop,
# device-eval fidelity, adaptive scheduler (tests/test_pipeline.py,
# docs/Performance.md) — fast subset by default; `-m pipeline` without
# the `not slow` filter adds the interpret-mode matrix
pipeline:
	$(PY) -m pytest tests/ -x -q -m "pipeline and not slow"
	$(PY) -m pytest tests/ -x -q -m "pipeline and slow"

# the histogram-kernel tier: scatter/mxu/oracle parity (incl.
# adversarial bin distributions and the quantized bit-exactness
# contract), hist_backend resolution + autotune (tests/
# test_hist_backends.py, docs/Performance.md) — the fast subset is
# tier-1; `-m "kernels and slow"` adds tree/model byte-parity
kernels:
	$(PY) -m pytest tests/ -x -q -m "kernels and not slow"
	$(PY) -m pytest tests/ -x -q -m "kernels and slow"

# the round-6 perf tier: microbench-shaped structural assertions for
# the scan partition and the level-pipelined grower — stage/fixup
# dispatch counts, speculative-overlap accounting, counts reuse,
# sort-free jaxprs (tests/test_partition_scan.py,
# tests/test_level_pipeline.py, docs/Performance.md "Level
# pipelining"). Count-based, never wall-clock: green means the
# structure that produced the BENCH_r06 numbers is intact
perf:
	$(PY) -m pytest tests/ -x -q -m "perf and not slow"

# the out-of-core streaming tier: sketch/bin parity, adversarial chunk
# layouts, model.txt byte-parity vs in-memory, mid-stream checkpoint
# resume (tests/test_streaming.py, docs/Streaming.md) — fast subset is
# tier-1; `-m "streaming and slow"` adds the 10M-row bounded-memory smoke
stream:
	$(PY) -m pytest tests/ -x -q -m "streaming and not slow"

# the fault-injection tier: every registered reliability site fired and
# recovered (tests/test_reliability.py, docs/Reliability.md)
faults:
	$(PY) -m pytest tests/ -x -q -m faults

# the rank-death chaos tier: 2-rank run loses a rank mid-collective,
# survivor aborts with a named diagnostic, resume is byte-identical
# (tests/test_chaos.py, docs/Reliability.md "Distributed fault model");
# the trailing -m overrides pytest.ini's `not slow`
chaos:
	$(PY) -m pytest tests/test_chaos.py -x -q -m chaos

# the flight-recorder acceptance scenario: the 2-rank kill run must
# leave a postmortem_<rank>.json on BOTH ranks naming the hung
# collective site (tests/test_chaos.py::test_postmortem_bundles,
# docs/Observability.md "Post-mortem workflow")
postmortem:
	$(PY) -m pytest tests/test_chaos.py -x -q -m chaos -k postmortem

# the distributed-learner tier: crossbar byte-parity oracles (serial vs
# data-parallel reduce-scatter, bit-for-bit), hist_agg/binning units,
# fault-site + provision-latch checks (tests/test_distributed_learner.py,
# docs/Distributed.md) — fast subset is tier-1; the second invocation
# adds full-task parity, fused determinism, and the 8-device rank-death
# chaos scenario
distributed:
	$(PY) -m pytest tests/test_distributed_learner.py -x -q -m "distributed and not slow"
	$(PY) -m pytest tests/test_distributed_learner.py -x -q -m "distributed and slow"

# the elastic world-resize tier (docs/Distributed.md "Elasticity"):
# the fast subset (tier-1, no subprocesses) covers epoch agreement,
# the reshard loader's W->W'->W byte-identity, stale-epoch rejection
# and the shrink-vote state machine; the slow invocation runs the
# shrink-and-finish reincarnation scenario — kill a rank at 2x4
# devices, survivors vote a new epoch, re-shard, finish with zero
# aborts, byte-identical to a fixed-world resume
elastic:
	$(PY) -m pytest tests/test_elastic.py -x -q -m "elastic and not slow"
	$(PY) -m pytest tests/test_elastic.py -x -q -m "elastic and slow"

# the serving chaos tier: concurrent load while the fault registry
# kills replica dispatches, breakers trip/heal, and the model is
# hot-swapped mid-run — zero drops, bit-identical answers, breaker
# lifecycle visible in metrics (tests/test_serve_chaos.py,
# docs/Serving.md "Degradation ladder") — fast subset is tier-1; the
# second invocation adds the slow open-loop QPS ramp
serve-chaos:
	$(PY) -m pytest tests/test_serve_chaos.py -x -q -m "serve_chaos and not slow"
	$(PY) -m pytest tests/test_serve_chaos.py -x -q -m "serve_chaos and slow"

# the continuous-loop tier (docs/Continuous.md): `loop` is the fast
# state-machine/unit tier (tier-1); `loop-chaos` runs the slow
# kill-matrix — one kill per fault site on the cycle path under live
# traffic, plus poison quarantine and the freshness SLO alarm, with
# byte-identity against an unkilled reference run
# (tests/test_loop_chaos.py)
loop:
	$(PY) -m pytest tests/test_continuous.py -x -q -m "loop and not slow"

loop-chaos:
	$(PY) -m pytest tests/test_continuous.py -x -q -m "loop and not slow"
	$(PY) -m pytest tests/test_loop_chaos.py -x -q -m "loop and slow"

# the serving load bench: open-loop QPS ramp + chaos stage, emits
# SERVE_r<N>.json (sustained QPS at p99<10ms) into the same
# regression-sentinel trajectory as BENCH_r*
serve-bench:
	$(PY) bench_serve.py
	$(PY) bench.py --compare --strict

# the observability tier: spans, training telemetry, MFU accounting,
# Prometheus /metrics (tests/test_observability.py, docs/Observability.md)
observe:
	$(PY) -m pytest tests/test_observability.py -x -q

# batched: the whole slow tier in ONE pytest process hard-crashed the
# interpreter twice (not OOM; see TESTS.md round 4) — per-batch runs
# are 100% green and are the supported invocation
test-slow:
	$(PY) -m pytest tests/test_mxu_kernels.py tests/test_mxu_smoke.py \
	  tests/test_mxu_forced_cegb.py -x -q -m slow
	$(PY) -m pytest tests/test_efb.py tests/test_efb_mxu.py \
	  tests/test_packed_bins.py tests/test_fused.py \
	  tests/test_bench_robustness.py tests/test_dask_stub.py -x -q -m slow
	$(PY) -m pytest tests/test_multihost.py tests/test_distributed.py \
	  tests/test_cli.py -x -q -m slow
	$(PY) -m pytest tests/ -x -q -m slow --ignore=tests/test_mxu_kernels.py \
	  --ignore=tests/test_mxu_smoke.py --ignore=tests/test_mxu_forced_cegb.py \
	  --ignore=tests/test_efb.py --ignore=tests/test_efb_mxu.py \
	  --ignore=tests/test_packed_bins.py --ignore=tests/test_fused.py \
	  --ignore=tests/test_bench_robustness.py --ignore=tests/test_dask_stub.py \
	  --ignore=tests/test_multihost.py --ignore=tests/test_distributed.py \
	  --ignore=tests/test_cli.py

test-all: test test-slow

# the bench run, followed by the regression sentinel: the fresh record
# is compared against the BENCH_r*/MULTICHIP_r* trajectory and a >10%
# drop vs best-so-far fails the target (observability/regress.py)
bench:
	$(PY) bench.py
	$(PY) bench.py --compare --strict

install:
	pip install -e . --no-build-isolation --no-deps
