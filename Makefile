# Test tiers (see pytest.ini): the default tier must stay green on every
# commit; the slow tier (multihost subprocess tests, MXU interpret-mode
# kernel matrix, reference-consistency differential tests) must pass
# before a round is declared done. Both run on CPU via tests/conftest.py
# (virtual 8-device mesh); bench.py is the only thing that touches the
# real accelerator.

PY ?= python

.PHONY: test test-slow test-all bench install

test:
	$(PY) -m pytest tests/ -x -q

test-slow:
	$(PY) -m pytest tests/ -x -q -m slow

test-all: test test-slow

bench:
	$(PY) bench.py

install:
	pip install -e . --no-build-isolation --no-deps
