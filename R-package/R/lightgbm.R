# R surface of lightgbm_tpu via reticulate.
#
# Mirrors the reference R package's exported API (R-package/NAMESPACE):
# lgb.Dataset, lgb.Dataset.construct/create.valid/save/set.categorical,
# lgb.train, lgb.cv, lgb.load, lgb.save, lgb.dump, predict.lgb.Booster,
# lgb.importance, lgb.get.eval.result, lightgbm(). The reference binds
# its C API from R (lightgbm_R.cpp); this package bridges to the Python
# core instead — parameters, model files and semantics are identical.

.lgb_env <- new.env(parent = emptyenv())

.lgb_py <- function() {
  if (is.null(.lgb_env$mod)) {
    .lgb_env$mod <- reticulate::import("lightgbm_tpu", delay_load = FALSE)
  }
  .lgb_env$mod
}

# reticulate converts an unnamed empty R list to a Python list; the core
# expects a dict of parameters
.lgb_params <- function(params) {
  if (length(params) == 0L) reticulate::dict() else params
}

#' Construct a Dataset (reference lgb.Dataset, R-package/R/lgb.Dataset.R)
#' @export
lgb.Dataset <- function(data, params = list(), reference = NULL,
                        label = NULL, weight = NULL, group = NULL,
                        init_score = NULL, colnames = NULL,
                        categorical_feature = NULL, free_raw_data = FALSE) {
  py <- .lgb_py()
  ds <- py$Dataset(
    data = data, label = label, weight = weight, group = group,
    init_score = init_score, params = .lgb_params(params),
    feature_name = if (is.null(colnames)) "auto" else as.list(colnames),
    categorical_feature = if (is.null(categorical_feature)) "auto"
                          else as.list(categorical_feature),
    reference = reference, free_raw_data = free_raw_data)
  class(ds) <- c("lgb.Dataset", class(ds))
  ds
}

#' @export
lgb.Dataset.construct <- function(dataset) {
  dataset$construct()
  invisible(dataset)
}

#' @export
lgb.Dataset.create.valid <- function(dataset, data, label = NULL, ...) {
  v <- dataset$create_valid(data = data, label = label, ...)
  class(v) <- c("lgb.Dataset", class(v))
  v
}

#' @export
lgb.Dataset.save <- function(dataset, fname) {
  dataset$save_binary(fname)
  invisible(dataset)
}

#' @export
lgb.Dataset.set.categorical <- function(dataset, categorical_feature) {
  dataset$set_categorical_feature(as.list(categorical_feature))
  invisible(dataset)
}

#' @export
slice <- function(dataset, idxset, ...) UseMethod("slice")

#' @export
slice.lgb.Dataset <- function(dataset, idxset, ...) {
  # Python subset() takes 0-based indices
  s <- dataset$subset(as.integer(idxset - 1L))
  class(s) <- c("lgb.Dataset", class(s))
  s
}

#' @export
get_field <- function(dataset, field_name) UseMethod("get_field")

#' @export
get_field.lgb.Dataset <- function(dataset, field_name) {
  dataset$get_field(field_name)
}

#' @export
set_field <- function(dataset, field_name, data) UseMethod("set_field")

#' @export
set_field.lgb.Dataset <- function(dataset, field_name, data) {
  dataset$set_field(field_name, data)
  invisible(dataset)
}

#' Train a model (reference lgb.train, R-package/R/lgb.train.R)
#' @export
lgb.train <- function(params = list(), data, nrounds = 100L,
                      valids = list(), obj = NULL, eval = NULL,
                      verbose = 1L, record = TRUE,
                      eval_freq = 1L, init_model = NULL,
                      early_stopping_rounds = NULL, callbacks = list(),
                      ...) {
  py <- .lgb_py()
  if (!is.null(early_stopping_rounds)) {
    params$early_stopping_round <- early_stopping_rounds
  }
  if (is.null(params$verbosity)) {
    params$verbosity <- as.integer(verbose)
  }
  cbs <- callbacks
  evals_result <- reticulate::dict()
  if (isTRUE(record)) {
    cbs <- c(list(py$record_evaluation(evals_result)), cbs)
  }
  if (length(valids) && verbose > 0L && eval_freq > 0L) {
    cbs <- c(list(py$log_evaluation(period = as.integer(eval_freq))), cbs)
  }
  bst <- py$train(
    params = .lgb_params(params), train_set = data,
    num_boost_round = as.integer(nrounds),
    valid_sets = unname(valids),
    valid_names = if (length(valids)) as.list(names(valids)) else NULL,
    fobj = obj, feval = eval, init_model = init_model,
    callbacks = cbs)
  attr(bst, "evals_result") <- evals_result
  class(bst) <- c("lgb.Booster", class(bst))
  bst
}

#' Cross validation (reference lgb.cv, R-package/R/lgb.cv.R)
#' @export
lgb.cv <- function(params = list(), data, nrounds = 100L, nfold = 3L,
                   obj = NULL, eval = NULL, stratified = TRUE,
                   early_stopping_rounds = NULL, ...) {
  py <- .lgb_py()
  if (!is.null(early_stopping_rounds)) {
    params$early_stopping_round <- early_stopping_rounds
  }
  py$cv(params = .lgb_params(params), train_set = data,
        num_boost_round = as.integer(nrounds), nfold = as.integer(nfold),
        stratified = stratified, fobj = obj, feval = eval)
}

#' @export
predict.lgb.Booster <- function(object, newdata, rawscore = FALSE,
                                predleaf = FALSE, predcontrib = FALSE,
                                num_iteration = NULL, ...) {
  object$predict(newdata, raw_score = rawscore, pred_leaf = predleaf,
                 pred_contrib = predcontrib,
                 num_iteration = num_iteration)
}

#' @export
print.lgb.Booster <- function(x, ...) {
  cat("<lgb.Booster>\n")
  cat(sprintf("  trees: %d\n", x$num_trees()))
  invisible(x)
}

#' @export
summary.lgb.Booster <- function(object, ...) print(object, ...)

#' Load a model from file (reference lgb.load)
#' @export
lgb.load <- function(filename = NULL, model_str = NULL) {
  py <- .lgb_py()
  bst <- if (!is.null(filename)) py$Booster(model_file = filename)
         else py$Booster(model_str = model_str)
  class(bst) <- c("lgb.Booster", class(bst))
  bst
}

#' Save a model to file (reference lgb.save)
#' @export
lgb.save <- function(booster, filename, num_iteration = NULL) {
  booster$save_model(filename, num_iteration = num_iteration)
  invisible(booster)
}

#' Dump model to JSON (reference lgb.dump)
#' @export
lgb.dump <- function(booster, num_iteration = NULL) {
  booster$dump_model(num_iteration = num_iteration)
}

#' Feature importance (reference lgb.importance)
#' @export
lgb.importance <- function(model, percentage = TRUE) {
  gain <- model$feature_importance(importance_type = "gain")
  splits <- model$feature_importance(importance_type = "split")
  nm <- unlist(model$feature_name())
  out <- data.frame(Feature = nm, Gain = as.numeric(gain),
                    Cover = NA_real_, Frequency = as.numeric(splits))
  out <- out[order(-out$Gain), ]
  if (percentage && sum(out$Gain) > 0) {
    out$Gain <- out$Gain / sum(out$Gain)
    out$Frequency <- out$Frequency / sum(out$Frequency)
  }
  out
}

#' @export
lgb.get.eval.result <- function(booster, data_name, eval_name,
                                iters = NULL, is_err = FALSE) {
  rec <- attr(booster, "evals_result")
  vals <- unlist(rec[[data_name]][[eval_name]])
  if (!is.null(iters)) vals <- vals[iters]
  vals
}

#' High-level fit, mirroring the reference lightgbm() entry point
#' @export
lightgbm <- function(data, label = NULL, weight = NULL, params = list(),
                     nrounds = 100L, verbose = 1L,
                     objective = "regression", ...) {
  params$objective <- params$objective %||% objective
  dtrain <- lgb.Dataset(data, label = label, weight = weight)
  lgb.train(params = params, data = dtrain, nrounds = nrounds,
            verbose = verbose, ...)
}

`%||%` <- function(a, b) if (is.null(a)) b else a
