# Run with:  Rscript R-package/tests/testthat.R
# (needs R + reticulate pointed at a python with lightgbm_tpu).
library(testthat)

`%||%` <- function(a, b) if (is.null(a)) b else a

args <- commandArgs(trailingOnly = FALSE)
file_arg <- sub("^--file=", "", grep("^--file=", args, value = TRUE))
if (length(file_arg) == 0L) {
  stop("run via Rscript R-package/tests/testthat.R")
}
repo_root <- normalizePath(file.path(dirname(file_arg), "..", ".."))
source(file.path(repo_root, "R-package", "R", "lightgbm.R"))
test_dir(file.path(repo_root, "R-package", "tests", "testthat"))
