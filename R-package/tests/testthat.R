# Reference R-package/tests/testthat.R analog: run with
#   Rscript R-package/tests/testthat.R
# (needs R + reticulate pointed at a python with lightgbm_tpu).
library(testthat)
source(file.path(dirname(dirname(sys.frame(1)$ofile %||% "R-package/tests")),
                 "R", "lightgbm.R"))
`%||%` <- function(a, b) if (is.null(a)) b else a
test_dir(file.path(dirname(sys.frame(1)$ofile %||% "R-package/tests"),
                   "testthat"))
