# Smoke tests for the reticulate bridge (reference R-package/tests/
# testthat/test_basic.R, condensed): Dataset/train/predict/save/load/
# importance/eval-results on a toy binary problem.

test_that("train, predict, save and reload round-trip", {
  set.seed(1)
  n <- 800L
  X <- matrix(rnorm(n * 5L), ncol = 5L)
  y <- as.numeric(X[, 1L] + 0.5 * X[, 2L] > 0)
  dtrain <- lgb.Dataset(X, label = y)
  bst <- lgb.train(params = list(objective = "binary", verbosity = -1L,
                                 num_leaves = 15L),
                   data = dtrain, nrounds = 10L, verbose = 0L)
  p <- predict.lgb.Booster(bst, X)
  expect_equal(length(p), n)
  expect_gt(mean((p > 0.5) == y), 0.9)

  f <- tempfile(fileext = ".txt")
  lgb.save(bst, f)
  bst2 <- lgb.load(filename = f)
  p2 <- predict.lgb.Booster(bst2, X)
  expect_equal(p, p2, tolerance = 1e-7)
})

test_that("empty params list works (dict conversion)", {
  set.seed(2)
  X <- matrix(rnorm(600L), ncol = 3L)
  y <- rnorm(200L)
  dtrain <- lgb.Dataset(X, label = y)
  expect_silent({
    bst <- lgb.train(data = dtrain, nrounds = 3L, verbose = 0L)
  })
})

test_that("valids + record produce eval results", {
  set.seed(3)
  X <- matrix(rnorm(2000L), ncol = 4L)
  y <- as.numeric(X[, 1L] > 0)
  dtrain <- lgb.Dataset(X, label = y,
                        params = list(objective = "binary"))
  dvalid <- lgb.Dataset.create.valid(dtrain, X[1:100L, ], label = y[1:100L])
  bst <- lgb.train(params = list(objective = "binary", verbosity = -1L,
                                 metric = "binary_logloss"),
                   data = dtrain, nrounds = 5L,
                   valids = list(valid = dvalid), verbose = 0L)
  r <- lgb.get.eval.result(bst, "valid", "binary_logloss")
  expect_equal(length(r), 5L)
  expect_true(all(diff(r) <= 1e-6))
})

test_that("importance and cv run", {
  set.seed(4)
  X <- matrix(rnorm(1500L), ncol = 5L)
  y <- as.numeric(X[, 1L] > 0)
  dtrain <- lgb.Dataset(X, label = y)
  bst <- lgb.train(params = list(objective = "binary", verbosity = -1L),
                   data = dtrain, nrounds = 5L, verbose = 0L)
  imp <- lgb.importance(bst)
  expect_true(is.data.frame(imp))
  expect_equal(nrow(imp), 5L)
  cvres <- lgb.cv(params = list(objective = "binary", verbosity = -1L),
                  data = lgb.Dataset(X, label = y), nrounds = 3L,
                  nfold = 2L)
  expect_true(length(cvres) > 0L)
})
