"""Benchmark: Higgs-style 1M x 28 binary classification, 255 leaves.

Mirrors the reference's headline benchmark (docs/Experiments.rst:111-123:
Higgs 500 trees, num_leaves=255, 28-core Xeon -> 130.094 s total,
i.e. 3.843 trees/sec). No dataset download is possible here, so a synthetic
Higgs-shaped problem (1M rows x 28 continuous features, balanced binary
labels from a nonlinear rule) stands in; the metric is trees/sec of the
steady-state training loop on the visible accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
N_FEATURES = 28
NUM_LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_MAX_BIN", 255))
WARMUP_TREES = 5
BENCH_TREES = int(os.environ.get("BENCH_TREES", 100))
BLOCK_TREES = int(os.environ.get("BENCH_BLOCK_TREES", 25))  # r4 A/B:
# 20-tree dispatches halve the host drains (median 2.87 vs 2.78-2.82);
# r5 same-hour A/B: 25-tree blocks measure 3.04/3.04 vs 2.95/2.96 at
# 20 — one fewer drain and block boundaries that straddle the
# deterministic fast/slow tree bands (docs/PerfNotes.md round 5)
BASELINE_TREES_PER_SEC = 500.0 / 130.094  # reference CPU Higgs headline
# like-for-like anchor (VERDICT r4 weak #8): the reference binary on
# THIS synthetic 1M x 28 set, single core, idle host — re-measured each
# round by helpers/recert_auc_parity.py. Band so far: 2.96 (loaded, r1)
# / 3.43 (idle, r4) / 4.33 (idle, r5 build). The denominator uses the
# LATEST idle measurement — the strictest honest anchor.
SINGLE_CORE_TREES_PER_SEC = 4.33


def make_higgs_like(n, f, seed=17):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    # nonlinear separation rule on a few "physics" features + noise dims
    logit = (1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.6 * X[:, 2] * X[:, 3] +
             0.5 * np.abs(X[:, 4]) - 0.4 * X[:, 5] ** 2 +
             0.3 * X[:, 6] * X[:, 0] + 0.35 * rng.randn(n))
    y = (logit > np.median(logit)).astype(np.float32)
    return X, y


def _probe_backend(timeout_s: int = 180) -> str:
    """Probe the accelerator in a subprocess: a wedged remote tunnel
    hangs forever inside XLA calls, which no in-process timeout can
    interrupt — the probe process is killable. Returns "" when healthy,
    else a one-line diagnosis. Output goes to a temp file, not pipes:
    a forked transport helper inheriting pipe ends would make the
    post-kill pipe drain hang the parent — the exact failure mode the
    probe exists to avoid."""
    import subprocess
    import tempfile
    with tempfile.TemporaryFile() as errf:
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp, numpy as np;"
                 "x = jnp.ones((8, 8)) @ jnp.ones((8, 8));"
                 "print(float(np.asarray(x)[0, 0]))"],
                timeout=timeout_s, stdout=subprocess.DEVNULL,
                stderr=errf, start_new_session=True)
        except subprocess.TimeoutExpired:
            return ("device probe timed out after %ds (wedged "
                    "accelerator tunnel?)" % timeout_s)
        if proc.returncode == 0:
            return ""
        errf.seek(0)
        tail = errf.read().decode(errors="replace").strip()
        return "device probe failed (rc=%d): %s" % (
            proc.returncode, tail.splitlines()[-1] if tail else "no stderr")


def _probe_with_retry() -> str:
    """Probe; on failure keep retrying with a fixed interval inside a
    bounded window (default: every 10 min for 1 h) so a transient tunnel
    outage at bench time doesn't zero the round's official record.
    Returns "" when healthy, else the last failure diagnosis."""
    window_s = int(os.environ.get("BENCH_RETRY_WINDOW", 3600))
    interval_s = int(os.environ.get("BENCH_RETRY_INTERVAL", 600))
    deadline = time.time() + window_s
    problem = _probe_backend()
    while problem and time.time() + interval_s < deadline:
        print(f"# accelerator probe failed ({problem}); retrying in "
              f"{interval_s}s (window closes in "
              f"{int(deadline - time.time())}s)", file=sys.stderr)
        time.sleep(interval_s)
        problem = _probe_backend()
    return problem


PARAMS = {"objective": "binary", "num_leaves": NUM_LEAVES,
          "learning_rate": 0.1, "max_bin": MAX_BIN, "verbosity": -1,
          "min_data_in_leaf": 20, "use_quantized_grad": True,
          "growth_overshoot": float(os.environ.get("BENCH_OVERSHOOT",
                                                   1.75)),
          "growth_bridge_gate": 0.93,
          # histogram kernel: "auto" autotunes mxu vs the Pallas
          # scatter kernel on device and pins the winner (byte-neutral
          # in the quantized posture). Pin explicitly to measure one
          # backend, e.g. LGBM_TPU_HIST_BACKEND=mxu for the pre-kernel
          # attribution point (docs/Performance.md r06 protocol).
          "hist_backend": os.environ.get("LGBM_TPU_HIST_BACKEND",
                                         "auto"),
          # row partition for the slot-grouped scatter kernels: "auto"
          # resolves to the blocked-prefix-sum scan (byte-identical to
          # the argsort oracle). Pin LGBM_TPU_PARTITION_IMPL=argsort
          # for the pre-scan attribution point of the r06 two-point
          # protocol (docs/PerfNotes.md round 6).
          "partition_impl": os.environ.get("LGBM_TPU_PARTITION_IMPL",
                                           "auto")}
if int(os.environ.get("BENCH_LEVEL_PIPELINE", "0")):
    # staged level-pipelined grower (serial MXU path only; the fused
    # multi-tree scan — the headline dispatch shape — ignores it).
    # Opt-in so the default posture's parameter echo is unchanged.
    PARAMS["level_pipeline"] = True
# Bench posture vs library defaults (both A/B'd, docs/PerfNotes.md):
# - use_quantized_grad: stochastically-rounded integer gradients with
#   exact leaf refit. Round-3 A/B: 2.31 vs 1.74 trees/s, AUC@95
#   0.98119 (quant) vs 0.98092 (exact) — ~2.4e-4, an order below
#   growth-order noise.
# - growth_overshoot 1.75 (default 2.0): round-4 A/B at 105 trees:
#   1.75 -> 2.83-3.4 t/s AUC 0.98098; 2.0 -> 2.68 t/s AUC 0.98129
#   (~3e-4, same order as quantization). 1.5 costs 1.1e-3 — rejected.
# - growth_bridge_gate 0.93 (default 0 = full chase): skips the
#   s_max-wide bridge sweep for trees already within 7% of the
#   overshoot target; A/B at 115 trees: median 3.03 AUC 0.98143 vs
#   2.85 AUC 0.98167 (~2.4e-4).
# The held-out AUC is printed below either way; the 200-tree
# differential vs the reference binary re-certifies the cumulative
# posture cost (helpers/recert_auc_parity.py).


def _drain(booster):
    """Force a device->host pull. block_until_ready is not reliable
    through remoted-accelerator tunnels; a host transfer cannot complete
    before the device queue does."""
    float(np.asarray(booster.gbdt.train_score[:1])[0])


class _Bench:
    """Fault-tolerant measurement driver. Every device interaction goes
    through train_block(); on a runtime/compile failure it re-probes the
    backend (with the bounded retry window), rebuilds the booster if the
    old one's device state died with the fault, and keeps measuring.
    Partial results beat rc=1 — main() always emits the JSON line from
    whatever blocks were captured (VERDICT r3 item 1)."""

    def __init__(self, lgb, X, y):
        self.lgb = lgb
        self.X, self.y = X, y
        self.bin_time = 0.0
        self.booster = None
        self.dead = False  # backend declared unreachable

    def rebuild(self):
        from lightgbm_tpu.utils.timer import global_timer
        before = dict(global_timer.totals())
        t0 = time.time()
        dtrain = self.lgb.Dataset(self.X, label=self.y,
                                  params={"max_bin": MAX_BIN})
        dtrain.construct()
        self.bin_time = time.time() - t0
        # decomposition of the recorded binning time (VERDICT r4 item 6:
        # the driver-captured 2.5 s vs the measured 1.5 s of halves):
        # sample+transpose / native bounds / native quantize / remainder
        after = global_timer.totals()
        parts = {k.replace("dataset_", ""): after.get(k, 0.0)
                 - before.get(k, 0.0)
                 for k in ("dataset_sample", "dataset_bounds",
                           "dataset_quantize")}
        parts["other"] = self.bin_time - sum(parts.values())
        self.bin_parts = parts
        self.booster = self.lgb.Booster(params=PARAMS, train_set=dtrain)

    def train_block(self, n_trees):
        """Train n_trees (one fused dispatch when eligible; train_many
        itself falls back to per-iteration on a fused fault). Returns
        (wall seconds of the SUCCESSFUL attempt, clean) — probe
        retries, rebuild/re-binning, the failed attempt, and a
        post-rebuild recompile warmup stay out of the timing; clean is
        False when train_many degraded to per-iteration mid-block (the
        time is real but not representative — callers should keep the
        trees and drop the sample). (None, False) = backend dead."""
        if self.dead:
            return None, False
        for attempt in (0, 1):
            try:
                # test hook: injects a fault ABOVE train_many's own
                # fallback, exercising this probe/rebuild/retry path
                from lightgbm_tpu.boosting.gbdt import \
                    _maybe_inject_fused_fault
                _maybe_inject_fused_fault("BENCH_INJECT_BLOCK_FAULT")
                if self.booster is None:
                    self.rebuild()
                    # un-timed warmup: the fresh booster's fused scan
                    # re-traces/recompiles on first dispatch — that cost
                    # must not land in a measured block
                    self.booster.update_batch(1)
                    _drain(self.booster)
                ff0 = getattr(self.booster.gbdt, "_fused_failures", 0)
                t1 = time.time()
                self.booster.update_batch(n_trees)
                _drain(self.booster)
                dt = time.time() - t1
                gb = self.booster.gbdt
                clean = (getattr(gb, "_fused_failures", 0) <= ff0 and
                         not getattr(gb, "_fused_disabled", False))
                return dt, clean
            except Exception as exc:
                print(f"# block failed (attempt {attempt}): "
                      f"{type(exc).__name__}: {exc}", file=sys.stderr)
                problem = _probe_with_retry()
                if problem:
                    print(f"# accelerator unreachable after retry window:"
                          f" {problem}", file=sys.stderr)
                    self.dead = True
                    return None, False
                # backend is healthy again, but the old booster's device
                # buffers may have died with the fault — rebuild
                self.booster = None
        self.dead = True
        return None, False


def _pipeline_bench(bench, result):
    """Pipelined-executor record (pipeline/executor.py): train extra
    trees on the already-compiled bench booster through run_pipelined
    (no valid sets — the overlap under measurement is stacked-tree
    unpacking against the next block's device compute) and merge the
    overlap fraction plus per-block host/device wall columns into the
    JSON record. Keys MERGE like _serve_bench; best-effort: a pipeline
    fault leaves the zeroed schema keys in place. BENCH_PIPELINE_TREES=0
    skips (the training headline is unaffected)."""
    n_trees = int(os.environ.get("BENCH_PIPELINE_TREES", 2 * BLOCK_TREES))
    if n_trees <= 0 or bench is None or bench.booster is None or bench.dead:
        return
    try:
        from lightgbm_tpu.pipeline import run_pipelined
        bst = bench.booster
        start = int(bst.current_iteration())
        run_pipelined(bst, start_iter=start,
                      num_boost_round=start + n_trees,
                      base_block=min(BLOCK_TREES, n_trees),
                      run_callbacks=lambda i, ev: None, has_valid=False)
        _drain(bst)
        st = getattr(bst.gbdt, "_pipeline_stats", None)
        if st is None or not st.blocks:
            return
        d = st.as_dict()
        result["pipeline_overlap_frac"] = d["overlap_frac"]
        result["pipeline_blocks"] = d["blocks"]
        result["pipeline_block_host_ms"] = d["host_ms"]
        result["pipeline_block_device_ms"] = d["device_ms"]
        print(f"# pipeline detail: {d['blocks']} blocks / "
              f"{d['iterations']} trees, sizes {d['block_sizes']}, "
              f"host ms {d['host_ms']}, device ms {d['device_ms']}, "
              f"overlap {100.0 * d['overlap_frac']:.1f}%",
              file=sys.stderr)
    except Exception as exc:
        print(f"# pipeline bench failed: {type(exc).__name__}: {exc}",
              file=sys.stderr)


def _serve_bench(bench, result):
    """Serve-path record: a mixed-size request stream (1..1000 rows)
    through serving.Server on the just-trained booster. Keys MERGE into
    the single JSON record — never a second JSON line (the round
    tooling parses exactly one). Best-effort: a serving fault leaves
    the zeroed schema keys in place, it cannot retract the training
    record."""
    n_req = int(os.environ.get("BENCH_SERVE_REQUESTS", 48))
    if n_req <= 0 or bench is None or bench.booster is None or bench.dead:
        return
    try:
        from lightgbm_tpu.serving import Server
        rng = np.random.RandomState(5)
        Xq, _ = make_higgs_like(4096, N_FEATURES, seed=23)
        sizes = [int(rng.choice([1, 4, 16, 64, 256, 1000]))
                 for _ in range(n_req)]
        with Server(min_bucket=16, max_bucket=1024,
                    max_wait_ms=0.5) as srv:
            srv.load_model("bench", booster=bench.booster)
            for s in sizes:
                lo = int(rng.randint(0, 4096 - s)) if s < 4096 else 0
                srv.predict("bench", Xq[lo:lo + s])
            snap = srv.metrics_snapshot("bench")["models"]["bench"]
        for src, dst in (("qps", "serve_qps"),
                         ("rows_per_sec", "serve_rows_per_sec"),
                         ("p50_ms", "serve_p50_ms"),
                         ("p95_ms", "serve_p95_ms"),
                         ("p99_ms", "serve_p99_ms"),
                         ("buckets_compiled", "serve_buckets_compiled"),
                         ("bucket_cache_hits", "serve_bucket_hits")):
            result[dst] = snap[src]
        print(f"# serve detail: {snap['requests']} requests "
              f"({snap['rows']} rows), {snap['buckets_compiled']} "
              f"buckets compiled (bound {snap['max_compilations']}), "
              f"p50/p95/p99 {snap['p50_ms']}/{snap['p95_ms']}/"
              f"{snap['p99_ms']} ms, {snap['qps']} req/s",
              file=sys.stderr)
    except Exception as exc:
        print(f"# serve bench failed: {type(exc).__name__}: {exc}",
              file=sys.stderr)


def _task_bench(result):
    """Task-matrix rows (VERDICT r4 item 2, promoted into the official
    record): regression / multiclass / lambdarank through
    helpers/bench_tasks.py at the bench posture, one dict per task
    appended to result["tasks"] — {"task", "value" (trees/sec),
    "unit", "metric", "metric_value", "vs_single_core"}. Keys MERGE
    into the single JSON record, like _serve_bench. Best-effort: a
    task fault leaves the rows gathered so far. BENCH_TASKS="" skips
    (robustness tests; the binary headline is unaffected),
    BENCH_TASK_TREES scales depth."""
    spec = os.environ.get("BENCH_TASKS",
                          "regression,multiclass,lambdarank")
    names = [t.strip() for t in spec.split(",") if t.strip()]
    if not names:
        return
    n_trees = int(os.environ.get("BENCH_TASK_TREES", 60))
    try:
        from helpers.bench_tasks import (SINGLE_CORE_RATES, TASKS,
                                         run_ours)
    except Exception as exc:
        print(f"# task bench unavailable: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return
    for name in names:
        if name not in TASKS:
            print(f"# task bench: unknown task {name!r}; skipped",
                  file=sys.stderr)
            continue
        try:
            rate, metric_value = run_ours(name, n_trees)
        except Exception as exc:
            print(f"# task bench [{name}] failed: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
            continue
        anchor = SINGLE_CORE_RATES.get(name, 0.0)
        result["tasks"].append({
            "task": name, "value": round(float(rate), 3),
            "unit": "trees/sec", "metric": TASKS[name]["metric"],
            "metric_value": round(float(metric_value), 6),
            "vs_single_core": round(float(rate) / anchor, 3)
            if anchor else 0.0})


def _parse_synth_argv(argv=None):
    """`--synth rows=10000000,cols=28[,chunk=262144][,seed=17]` (or the
    `--synth=...` form) -> spec dict, None when the flag is absent.
    Malformed specs raise SystemExit with a usage line rather than
    silently benching the wrong shape."""
    argv = sys.argv[1:] if argv is None else argv
    raw = None
    for i, a in enumerate(argv):
        if a == "--synth":
            if i + 1 >= len(argv):
                raise SystemExit("--synth needs rows=...,cols=...")
            raw = argv[i + 1]
            break
        if a.startswith("--synth="):
            raw = a[len("--synth="):]
            break
    if raw is None:
        return None
    spec = {"rows": 0, "cols": 0, "chunk": 262144, "seed": 17}
    for part in raw.split(","):
        k, _, v = part.partition("=")
        if k not in spec or not v:
            raise SystemExit(f"--synth: bad field {part!r} "
                             "(want rows=...,cols=...[,chunk=...][,seed=...])")
        spec[k] = int(v)
    if spec["rows"] < 1 or spec["cols"] < 1:
        raise SystemExit("--synth: rows and cols must be >= 1")
    return spec


def _stream_bench(result, spec):
    """Out-of-core ingest bench: stream `spec` rows of synthetic data
    (helpers/synth.py — generated chunk-by-chunk, never materialized)
    through the two-pass sketch+bin loader, then train a short booster
    on the binned result. Records stream_* keys — chunk count, parse/
    bin overlap fraction, end-to-end ingest rows/sec — in the same
    JSON record. Best-effort like _serve_bench: a fault leaves zeros
    and a stderr line. Runs only when --synth is given; the 1M-row
    in-memory headline above is untouched."""
    if spec is None:
        return
    try:
        import lightgbm_tpu as lgb
        from helpers.synth import SynthSource
        src = SynthSource(rows=spec["rows"], cols=spec["cols"],
                          chunk_rows=spec["chunk"], seed=spec["seed"])
        t0 = time.perf_counter()
        ds = lgb.Dataset(src, params={"max_bin": MAX_BIN}).construct()
        ingest_s = time.perf_counter() - t0
        st = ds._binned.stream_stats
        result["stream_chunks"] = st.chunks
        result["stream_rows"] = st.rows
        result["stream_overlap_frac"] = round(st.overlap_frac, 4)
        result["stream_rows_per_sec"] = round(st.rows_per_sec, 1)
        result["stream_sample_rows"] = st.sample_rows
        result["stream_exact"] = int(st.exact)
        result["stream_ingest_s"] = round(ingest_s, 3)
        n_trees = int(os.environ.get("BENCH_STREAM_TREES", 20))
        t0 = time.perf_counter()
        lgb.train(dict(PARAMS, objective="binary"), ds,
                  num_boost_round=n_trees)
        train_s = time.perf_counter() - t0
        if train_s > 0:
            result["stream_trees_per_sec"] = round(n_trees / train_s, 3)
        print(f"# stream bench: {st.rows} rows / {st.chunks} chunks in "
              f"{ingest_s:.1f}s ({st.rows_per_sec:.0f} rows/s, "
              f"{st.overlap_frac:.0%} parse/bin overlap), "
              f"{n_trees} trees in {train_s:.1f}s", file=sys.stderr)
    except Exception as exc:
        print(f"# stream bench failed: {type(exc).__name__}: {exc}",
              file=sys.stderr)


def _multichip_worker_main(argv):
    """``bench.py --multichip-worker`` (spawned by --multichip with the
    device count forced in XLA_FLAGS): stream the --synth dataset
    through the two-pass loader, train tree_learner=data through the
    fused+pipelined executor over every visible device, and print ONE
    JSON line with the measured steady-state trees/sec."""
    import jax
    import lightgbm_tpu as lgb
    from helpers.synth import SynthSource
    from lightgbm_tpu.observability import registry as _obs

    spec = _parse_synth_argv(argv) or \
        {"rows": 1_000_000, "cols": 28, "chunk": 262144, "seed": 17}
    n_leaves = int(os.environ.get("BENCH_MC_LEAVES", 63))
    n_trees = int(os.environ.get("BENCH_MC_TREES", 30))
    warmup = int(os.environ.get("BENCH_MC_WARMUP", 12))
    ndev = len(jax.devices())
    src = SynthSource(rows=spec["rows"], cols=spec["cols"],
                      chunk_rows=spec["chunk"], seed=spec["seed"])
    t0 = time.perf_counter()
    ds = lgb.Dataset(src, params={"max_bin": MAX_BIN}).construct()
    ingest_s = time.perf_counter() - t0
    params = dict(PARAMS, num_leaves=n_leaves, tree_learner="data",
                  pipeline=True, use_quantized_grad=False)
    _obs.enable()
    # warmup compiles every dispatch shape (iteration-0 per-iteration
    # path + the fused sharded block); the timed run below re-hits the
    # process-global jit cache, so it measures steady-state training
    lgb.train(params, ds, num_boost_round=warmup)
    t0 = time.perf_counter()
    lgb.train(params, ds, num_boost_round=n_trees)
    train_s = time.perf_counter() - t0
    dist = _obs.distributed_snapshot()
    rate = n_trees / train_s if train_s > 0 else 0.0
    rec = {
        "n_devices": ndev, "tree_learner": "data",
        "trees_per_sec": round(rate, 3),
        "vs_baseline": round(rate / BASELINE_TREES_PER_SEC, 3),
        "num_leaves": n_leaves, "trees": n_trees,
        "rows": spec["rows"], "cols": spec["cols"],
        "ingest_s": round(ingest_s, 3),
        "train_s": round(train_s, 3),
        "world": dist["world"],
        "feature_shard_width": dist["feature_shard_width"]}
    # elasticity cost (docs/Distributed.md "Elasticity"): when this
    # round resized mid-run, the sentinel tracks the post-resize
    # throughput and reshard wall alongside the main series
    mem = _obs.membership_snapshot()
    if mem.get("resizes", 0):
        rec["chaos_resize"] = {
            "resizes": int(mem["resizes"]),
            "reshard_wall_s": float(mem["reshard_wall_s"]),
            "post_resize_trees_per_sec": round(rate, 3)}
    print(json.dumps(rec))
    sys.stdout.flush()
    return 0


def _multichip_main(argv):
    """``bench.py --multichip [--devices N] [--out PATH] [--synth ...]``:
    real multi-device training benchmark. Spawns a worker process with
    ``--xla_force_host_platform_device_count=N`` appended to XLA_FLAGS
    (visible-only on the host platform: real chips are untouched, CPU
    CI gets N virtual devices) and wraps the worker's JSON line into
    the MULTICHIP_r*.json record shape the regression sentinel tracks
    (observability/regress.py): n_devices/rc/ok/skipped/tail plus the
    measured trees_per_sec, vs_baseline and tree_learner."""
    import subprocess
    ndev, out = 8, "MULTICHIP_r06.json"
    for i, a in enumerate(argv):
        if a == "--devices" and i + 1 < len(argv):
            ndev = int(argv[i + 1])
        elif a.startswith("--devices="):
            ndev = int(a[len("--devices="):])
        elif a == "--out" and i + 1 < len(argv):
            out = argv[i + 1]
        elif a.startswith("--out="):
            out = a[len("--out="):]
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        f" --xla_force_host_platform_device_count={ndev}")
    spec = _parse_synth_argv(argv) or \
        {"rows": 1_000_000, "cols": 28, "chunk": 262144, "seed": 17}
    cmd = [sys.executable, os.path.abspath(__file__),
           "--multichip-worker",
           "--synth=" + ",".join(f"{k}={v}" for k, v in spec.items())]
    timeout_s = int(os.environ.get("BENCH_MC_TIMEOUT", 3600))
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=timeout_s)
        rc, out_txt, err_txt = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as exc:
        rc = -1
        out_txt = (exc.stdout or b"").decode() \
            if isinstance(exc.stdout, bytes) else (exc.stdout or "")
        err_txt = f"worker timed out after {timeout_s}s"
    parsed = None
    for line in reversed(out_txt.strip().splitlines()):
        try:
            parsed = json.loads(line)
            break
        except ValueError:
            continue
    record = {"n_devices": ndev, "rc": rc,
              "ok": bool(rc == 0 and parsed
                         and parsed.get("trees_per_sec", 0) > 0),
              "skipped": False,
              "tail": (err_txt or "")[-2000:] + (out_txt or "")[-500:]}
    if parsed:
        record.update(parsed)
    with open(out, "w") as fh:
        json.dump(record, fh, indent=2)
        fh.write("\n")
    print(json.dumps(record))
    sys.stdout.flush()
    return 0 if record["ok"] else 1


def _compare_main(argv):
    """``bench.py --compare [--strict] [--trajectory-dir D]``: the bench
    regression sentinel (lightgbm_tpu/observability/regress.py) — check
    the BENCH_r*/MULTICHIP_r* trajectory for per-metric drops beyond
    the threshold. Pure record reading: no dataset, no accelerator, no
    probe — safe to run anywhere, including the `make bench` tail.
    With --strict, regressions exit nonzero."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from lightgbm_tpu.observability import regress
    root = None
    for i, a in enumerate(argv):
        if a == "--trajectory-dir":
            if i + 1 >= len(argv):
                raise SystemExit("--trajectory-dir needs a path")
            root = argv[i + 1]
        elif a.startswith("--trajectory-dir="):
            root = a[len("--trajectory-dir="):]
    result = regress.compare(root)
    print(json.dumps({"bench_regressions": result}))
    sys.stdout.flush()
    print(regress.render_compare(result), file=sys.stderr)
    return 1 if ("--strict" in argv and result["regressions"]) else 0


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    result = {"metric": "higgs1m_trees_per_sec", "value": 0.0,
              "unit": "trees/sec", "vs_baseline": 0.0,
              "vs_single_core": 0.0,
              # serve-path schema (filled by _serve_bench; zeros when
              # the serve bench is skipped or faults)
              "serve_qps": 0.0, "serve_rows_per_sec": 0.0,
              "serve_p50_ms": 0.0, "serve_p95_ms": 0.0,
              "serve_p99_ms": 0.0, "serve_buckets_compiled": 0,
              "serve_bucket_hits": 0,
              # pipelined-executor schema (filled by _pipeline_bench;
              # zeros when the pipeline bench is skipped or faults)
              "pipeline_overlap_frac": 0.0, "pipeline_blocks": 0,
              "pipeline_block_host_ms": [],
              "pipeline_block_device_ms": [],
              # reliability-counter schema (overwritten from the live
              # counters at the end of the run)
              "device_retries": 0, "fallbacks": 0, "guard_trips": 0,
              "checkpoint_saves": 0, "checkpoint_failures": 0,
              # device-utilization schema (observability/mfu.py):
              # achieved TFLOP/s from the analytic per-tree histogram
              # MAC count x the measured trees/sec; mfu_per_tree = that
              # over the device's bf16 peak (0.0 when the peak is
              # unknown, e.g. CPU or interpret mode)
              "achieved_tflops": 0.0, "mfu_per_tree": 0.0,
              "device_peak_tflops": 0.0,
              # round-6 attribution side channels (never sentinel
              # metrics): which partition impl ran, the staged-grower
              # dispatch accounting, and — under BENCH_PROFILE_SPANS=1
              # — per-span wall totals from the observability trace
              "partition_impl": "", "level_pipeline": {},
              "profile_spans": {},
              # per-task rows (regression/multiclass/lambdarank) from
              # helpers/bench_tasks.py, filled by _task_bench
              "tasks": [],
              # out-of-core ingest schema (filled by _stream_bench when
              # --synth rows=...,cols=... is given; zeros otherwise)
              "stream_chunks": 0, "stream_rows": 0,
              "stream_overlap_frac": 0.0, "stream_rows_per_sec": 0.0,
              "stream_sample_rows": 0, "stream_exact": 0,
              "stream_ingest_s": 0.0, "stream_trees_per_sec": 0.0}
    block_times = []
    block_trees = min(BLOCK_TREES, BENCH_TREES)
    bench = None
    try:
        problem = _probe_with_retry()
        if problem:
            print(f"# accelerator unreachable: {problem}; no measurement "
                  "possible", file=sys.stderr)
            return result, block_times, block_trees, None
        import lightgbm_tpu as lgb
        from lightgbm_tpu import cext
        cext.available()  # lazy g++ build happens here, not in bin_time
        if int(os.environ.get("BENCH_PROFILE_SPANS", "0")):
            # span capture for the r06 attribution protocol: totals per
            # span name ride the record. Opt-in — the ring appends cost
            # real wall in the measured blocks, so headline runs leave
            # it off (docs/Performance.md "BENCH_r06 attribution
            # protocol")
            from lightgbm_tpu.observability import registry as _obs0
            _obs0.enable(ring=65536)
        X, y = make_higgs_like(N_ROWS, N_FEATURES)
        bench = _Bench(lgb, X, y)
        bench.rebuild()
        # warmup: compile all jitted phases (incl. the fused multi-tree
        # scan, boosting/fused.py — one device dispatch per block)
        bench.train_block(max(1, WARMUP_TREES - 1))
        bench.train_block(block_trees)  # compile the bench-block shape

        # the remoted-accelerator tunnel has run-to-run variance of
        # +-50% (occasionally 3x, docs/PerfNotes.md); time several
        # blocks, report the MEDIAN (best in the detail line).
        n_blocks = max(1, round(BENCH_TREES / block_trees))
        degraded = []
        for _ in range(n_blocks):
            dt, clean = bench.train_block(block_trees)
            if dt is None:
                break
            if clean:
                block_times.append(dt)
            else:
                degraded.append(dt)
                print(f"# block degraded mid-measurement ({dt:.2f}s); "
                      "sample dropped from the record", file=sys.stderr)
        if not block_times and degraded:
            # an honest degraded number beats an honest zero
            block_times = degraded
    except Exception as exc:  # belt and braces: never lose the record
        print(f"# bench aborted: {type(exc).__name__}: {exc}",
              file=sys.stderr)
    if block_times:
        rates = sorted(block_trees / b for b in block_times)
        median_rate = rates[len(rates) // 2] if len(rates) % 2 else \
            0.5 * (rates[len(rates) // 2 - 1] + rates[len(rates) // 2])
        result["value"] = round(median_rate, 3)
        result["vs_baseline"] = round(
            median_rate / BASELINE_TREES_PER_SEC, 3)
        result["vs_single_core"] = round(
            median_rate / SINGLE_CORE_TREES_PER_SEC, 3)
        try:
            # device utilization: analytic MACs of one tree at the
            # bench posture (quantized grads -> 3 histogram channels;
            # binary log-loss has non-constant hessians, so the
            # const-hessian channel drop never applies) x measured rate
            from lightgbm_tpu.observability import mfu as _mfu
            from lightgbm_tpu.observability import registry as _obs
            if _obs.hist_backend_snapshot()["choice"] not in ("", "mxu"):
                # the analytic MAC form models the one-hot matmul
                # kernel only; the scatter kernels are partition-
                # shaped, so MFU honestly reads unavailable
                raise RuntimeError("no MAC model for the scatter "
                                   "histogram backend")
            tmacs = _mfu.tree_macs(
                num_leaves=NUM_LEAVES, num_rows=N_ROWS,
                num_features=N_FEATURES, bmax=MAX_BIN,
                quantized=True, const_hess=False,
                hist_subtraction=True,
                overshoot=PARAMS["growth_overshoot"],
                bridge_gate=PARAMS["growth_bridge_gate"])
            tflops = _mfu.achieved_tflops(tmacs * median_rate)
            peak = _mfu.device_peak_tflops()
            result["achieved_tflops"] = round(tflops, 4)
            result["device_peak_tflops"] = peak
            if peak:
                result["mfu_per_tree"] = round(tflops / peak, 6)
        except Exception as exc:
            print(f"# device-utilization accounting failed: {exc}",
                  file=sys.stderr)
    try:
        # which histogram backend actually ran (+ autotune timings) —
        # pinned once per process by GBDT._resolved_hist_backend and
        # recorded regardless of the observability enable flag
        from lightgbm_tpu.observability import registry as _obs
        result["hist_backend"] = _obs.hist_backend_snapshot()
        result["partition_impl"] = str(PARAMS.get("partition_impl",
                                                  "auto"))
        result["level_pipeline"] = _obs.level_pipeline_snapshot()
        if int(os.environ.get("BENCH_PROFILE_SPANS", "0")):
            agg = {}
            for sp in _obs.trace.spans():
                a = agg.setdefault(sp["name"], [0, 0.0])
                a[0] += 1
                a[1] += sp["dur"]
            result["profile_spans"] = {
                name: {"count": c, "total_s": round(t, 4)}
                for name, (c, t) in sorted(
                    agg.items(), key=lambda kv: -kv[1][1])[:16]}
    except Exception as exc:
        print(f"# hist-backend record unavailable: {exc}",
              file=sys.stderr)
    _pipeline_bench(bench, result)
    _serve_bench(bench, result)
    _task_bench(result)
    _stream_bench(result, _parse_synth_argv())
    try:
        # reliability counters (lightgbm_tpu/reliability/): how degraded
        # this record is — retries, fused->per-iter / device->host
        # fallbacks, guard trips — rides in the same JSON line
        from lightgbm_tpu.reliability import counters
        result.update(counters.snapshot())
    except Exception as exc:
        print(f"# reliability counters unavailable: {exc}",
              file=sys.stderr)
    return result, block_times, block_trees, bench


def _report(result, block_times, block_trees, bench):
    """Detail lines; every step is best-effort so a late fault cannot
    retract the already-printed JSON record."""
    try:
        import jax
        rates = sorted(block_trees / b for b in block_times)
        blocks = ", ".join(f"{block_trees / b:.2f}" for b in block_times)
        parts = getattr(bench, "bin_parts", None)
        decomp = ("" if not parts else " (" + " + ".join(
            f"{k} {v:.2f}" for k, v in parts.items()) + ")")
        print(f"# bench detail: {len(block_times)} blocks x "
              f"{block_trees} trees, median {result['value']:.2f} best "
              f"{rates[-1]:.2f} trees/sec, per block: [{blocks}], "
              f"binning {bench.bin_time:.1f}s{decomp}, "
              f"device={jax.devices()[0].device_kind}", file=sys.stderr)
        Xva, yva = make_higgs_like(40_000, N_FEATURES, seed=99)
        sc = bench.booster.predict(Xva, raw_score=True)
        from lightgbm_tpu.metrics import AUCMetric  # tie-corrected
        auc = AUCMetric._auc_fast(sc, yva > 0, np.ones_like(yva))
        print(f"# held-out AUC after "
              f"{bench.booster.current_iteration()} trees: {auc:.5f}",
              file=sys.stderr)
        if result.get("achieved_tflops"):
            peak = result.get("device_peak_tflops", 0.0)
            mfu_s = (f"MFU {result['mfu_per_tree']:.4f} of "
                     f"{peak:.0f} TFLOP/s bf16 peak") if peak else \
                "MFU n/a (unknown device peak; set LGBM_TPU_PEAK_TFLOPS)"
            print(f"# device utilization: "
                  f"{result['achieved_tflops']:.3f} achieved TFLOP/s "
                  f"from analytic histogram MACs "
                  f"(observability/mfu.py, slight lower bound), {mfu_s}",
                  file=sys.stderr)
        hb = result.get("hist_backend") or {}
        if hb.get("choice"):
            tim = ", ".join(f"{k[:-3]} {v:.2f}ms"
                            for k, v in sorted(hb.items())
                            if k.endswith("_ms"))
            print(f"# histogram backend: {hb['choice']} "
                  f"({'autotuned: ' + tim if hb.get('autotuned') else 'pinned'})",
                  file=sys.stderr)
        for row in result.get("tasks", []):
            print(f"# task {row['task']}: {row['value']:.2f} trees/sec "
                  f"({row['vs_single_core']:.2f}x single-core ref), "
                  f"{row['metric']} = {row['metric_value']:.5f}",
                  file=sys.stderr)
        print("# note: vs_baseline uses the reference's published "
              "10.5M-row 28-core Higgs rate; vs_single_core uses the "
              "same-host single-core reference on THIS synthetic "
              "1M-row set (band 2.96-4.33 trees/sec loaded/idle, "
              "latest idle 4.33 — docs/PerfNotes.md round 5)",
              file=sys.stderr)
    except Exception as exc:
        print(f"# detail reporting failed: {type(exc).__name__}: {exc}",
              file=sys.stderr)


if __name__ == "__main__":
    if "--compare" in sys.argv[1:]:
        sys.exit(_compare_main(sys.argv[1:]))
    if "--multichip-worker" in sys.argv[1:]:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        sys.exit(_multichip_worker_main(sys.argv[1:]))
    if "--multichip" in sys.argv[1:]:
        sys.exit(_multichip_main(sys.argv[1:]))
    _result, _blocks, _bt, _bench = main()
    print(json.dumps(_result))
    sys.stdout.flush()
    if _blocks and _bench is not None and _bench.booster is not None:
        _report(_result, _blocks, _bt, _bench)
