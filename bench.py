"""Benchmark: Higgs-style 1M x 28 binary classification, 255 leaves.

Mirrors the reference's headline benchmark (docs/Experiments.rst:111-123:
Higgs 500 trees, num_leaves=255, 28-core Xeon -> 130.094 s total,
i.e. 3.843 trees/sec). No dataset download is possible here, so a synthetic
Higgs-shaped problem (1M rows x 28 continuous features, balanced binary
labels from a nonlinear rule) stands in; the metric is trees/sec of the
steady-state training loop on the visible accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
N_FEATURES = 28
NUM_LEAVES = 255
MAX_BIN = 255
WARMUP_TREES = 5
BENCH_TREES = int(os.environ.get("BENCH_TREES", 100))
BLOCK_TREES = int(os.environ.get("BENCH_BLOCK_TREES", 10))
BASELINE_TREES_PER_SEC = 500.0 / 130.094  # reference CPU Higgs headline


def make_higgs_like(n, f, seed=17):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    # nonlinear separation rule on a few "physics" features + noise dims
    logit = (1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.6 * X[:, 2] * X[:, 3] +
             0.5 * np.abs(X[:, 4]) - 0.4 * X[:, 5] ** 2 +
             0.3 * X[:, 6] * X[:, 0] + 0.35 * rng.randn(n))
    y = (logit > np.median(logit)).astype(np.float32)
    return X, y


def _probe_backend(timeout_s: int = 180) -> str:
    """Probe the accelerator in a subprocess: a wedged remote tunnel
    hangs forever inside XLA calls, which no in-process timeout can
    interrupt — the probe process is killable. Returns "" when healthy,
    else a one-line diagnosis. Output goes to a temp file, not pipes:
    a forked transport helper inheriting pipe ends would make the
    post-kill pipe drain hang the parent — the exact failure mode the
    probe exists to avoid."""
    import subprocess
    import tempfile
    with tempfile.TemporaryFile() as errf:
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp, numpy as np;"
                 "x = jnp.ones((8, 8)) @ jnp.ones((8, 8));"
                 "print(float(np.asarray(x)[0, 0]))"],
                timeout=timeout_s, stdout=subprocess.DEVNULL,
                stderr=errf, start_new_session=True)
        except subprocess.TimeoutExpired:
            return ("device probe timed out after %ds (wedged "
                    "accelerator tunnel?)" % timeout_s)
        if proc.returncode == 0:
            return ""
        errf.seek(0)
        tail = errf.read().decode(errors="replace").strip()
        return "device probe failed (rc=%d): %s" % (
            proc.returncode, tail.splitlines()[-1] if tail else "no stderr")


def _probe_with_retry() -> str:
    """Probe; on failure keep retrying with a fixed interval inside a
    bounded window (default: every 10 min for 1 h) so a transient tunnel
    outage at bench time doesn't zero the round's official record.
    Returns "" when healthy, else the last failure diagnosis."""
    window_s = int(os.environ.get("BENCH_RETRY_WINDOW", 3600))
    interval_s = int(os.environ.get("BENCH_RETRY_INTERVAL", 600))
    deadline = time.time() + window_s
    problem = _probe_backend()
    while problem and time.time() + interval_s < deadline:
        print(f"# accelerator probe failed ({problem}); retrying in "
              f"{interval_s}s (window closes in "
              f"{int(deadline - time.time())}s)", file=sys.stderr)
        time.sleep(interval_s)
        problem = _probe_backend()
    return problem


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    problem = _probe_with_retry()
    if problem:
        # emit a parseable, honest record instead of hanging the driver
        print(json.dumps({
            "metric": "higgs1m_trees_per_sec", "value": 0.0,
            "unit": "trees/sec", "vs_baseline": 0.0}))
        print(f"# accelerator unreachable: {problem}; no measurement "
              "possible", file=sys.stderr)
        return
    import lightgbm_tpu as lgb

    X, y = make_higgs_like(N_ROWS, N_FEATURES)
    t0 = time.time()
    dtrain = lgb.Dataset(X, label=y, params={"max_bin": MAX_BIN})
    dtrain.construct()
    bin_time = time.time() - t0

    # use_quantized_grad: stochastically-rounded integer gradients with
    # exact leaf refit. A/B at this config (docs/PerfNotes.md round 3):
    # 2.31 vs 1.74 trees/s, AUC@95 0.98119 (quant) vs 0.98092 (exact) —
    # the quantization effect (~2.4e-4) is an order of magnitude below
    # growth-order noise, and the held-out AUC is printed below either way
    params = {"objective": "binary", "num_leaves": NUM_LEAVES,
              "learning_rate": 0.1, "max_bin": MAX_BIN, "verbosity": -1,
              "min_data_in_leaf": 20, "use_quantized_grad": True}
    booster = lgb.Booster(params=params, train_set=dtrain)

    # warmup: compile all jitted phases (incl. the fused multi-tree scan,
    # boosting/fused.py — one device dispatch per block). Drain via an
    # actual host transfer (block_until_ready is not reliable through
    # remoted-accelerator tunnels; a device->host pull cannot complete
    # before the queue does)
    block_trees = min(BLOCK_TREES, BENCH_TREES)
    booster.update_batch(max(1, WARMUP_TREES - 1))
    booster.update_batch(block_trees)  # compile the bench-block shape
    float(np.asarray(booster.gbdt.train_score[:1])[0])

    # the remoted-accelerator tunnel has run-to-run variance of +-50%
    # (occasionally 3x, docs/PerfNotes.md); time several blocks and take
    # the best, the documented measurement methodology for this backend.
    # BENCH_TREES rounds to whole blocks (at least one).
    n_blocks = max(1, round(BENCH_TREES / block_trees))
    block_times = []
    for _ in range(n_blocks):
        t1 = time.time()
        booster.update_batch(block_trees)
        float(np.asarray(booster.gbdt.train_score[:1])[0])
        block_times.append(time.time() - t1)
    rates = sorted(block_trees / b for b in block_times)
    best_rate = rates[-1]
    median_rate = rates[len(rates) // 2] if len(rates) % 2 else \
        0.5 * (rates[len(rates) // 2 - 1] + rates[len(rates) // 2])

    # the tunnel-oscillation rationale for best-block stands (docs/
    # PerfNotes.md), but the headline reports the MEDIAN so steady-state
    # is not overstated; best is in the detail line
    trees_per_sec = median_rate
    result = {
        "metric": "higgs1m_trees_per_sec",
        "value": round(trees_per_sec, 3),
        "unit": "trees/sec",
        "vs_baseline": round(trees_per_sec / BASELINE_TREES_PER_SEC, 3),
    }
    import jax
    print(json.dumps(result))
    blocks = ", ".join(f"{block_trees / b:.2f}" for b in block_times)
    print(f"# bench detail: {n_blocks} blocks x {block_trees} trees, "
          f"median {median_rate:.2f} best {best_rate:.2f} trees/sec, "
          f"per block: [{blocks}], binning {bin_time:.1f}s, "
          f"device={jax.devices()[0].device_kind}", file=sys.stderr)
    Xva, yva = make_higgs_like(40_000, N_FEATURES, seed=99)
    sc = booster.predict(Xva, raw_score=True)
    from lightgbm_tpu.metrics import AUCMetric  # tie-corrected, no scipy
    auc = AUCMetric._auc_fast(sc, yva > 0, np.ones_like(yva))
    print(f"# held-out AUC after {booster.current_iteration()} "
          f"trees: {auc:.5f}", file=sys.stderr)
    print("# note: vs_baseline uses the reference's published 10.5M-row "
          "28-core Higgs rate; same-host single-core reference on THIS "
          "synthetic 1M-row set measured 2.96 trees/sec "
          "(docs/PerfNotes.md)", file=sys.stderr)


if __name__ == "__main__":
    main()
