#!/usr/bin/env python
"""Serving load bench: sustained QPS at p99 < 10ms, as a guarded record.

Open-loop QPS ramp (testing.chaos_serve.run_open_loop — arrivals on a
fixed schedule, so the server can't hide slowness by back-pressuring
the generator) with heavy-tailed request sizes against a replicated
`serving.Server`, one stage per target QPS. A mid-ramp chaos stage
injects replica-dispatch faults so the record carries the cost of the
degradation ladder, not just the sunny path. The headline value is the
highest achieved QPS among stages that held p99 < 10ms; shed /
fallback / failover / deadline-miss counts ride as side channels.

Output contract (mirrors bench.py):
- one single-line JSON metric record on stdout:
  {"metric": "serve_sustained_qps_p99lt10ms", "value": ..., "unit":
   "qps", "p99_ms": ..., "shed": ..., "fallback": ..., "failovers":
   ..., "deadline_misses": ...}
- `# serve detail:` lines on stderr;
- a wrapped SERVE_r<N>.json bench record in the repo root (N from
  SERVE_ROUND or the next free round) that
  `bench.py --compare [--strict]` parses and the regression sentinel
  tracks exactly like BENCH_r*.

A second, multi-model stage (PR 15) loads N small models + 1 large one
— the tenant mix where per-model dispatch serializes — twice: unpacked
(one DeviceForest + one queue each) and packed (`Server.load_pack`, one
fused ForestPack dispatch + one continuous-batching queue). The same
heavy-tailed open-loop schedule hits both; the record carries
mm_packed_qps / mm_unpacked_qps / mm_packed_speedup and the matching
p99s so the regression sentinel tracks the packed win per round.

Env knobs: SERVE_BENCH_STAGES="qps:sec,qps:sec,..." (default ramp),
SERVE_BENCH_REPLICAS (default 2), SERVE_BENCH_TREES /
SERVE_BENCH_ROWS (model/pool size), SERVE_ROUND (record number),
SERVE_BENCH_CHAOS=0 to disable fault injection,
SERVE_MM_STAGES / SERVE_MM_SMALL (multi-model stage ramp / small-model
count), SERVE_MM=0 to skip the multi-model stage.
"""

import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

P99_SLO_MS = 10.0


def _parse_stages(spec):
    stages = []
    for part in spec.split(","):
        qps, _, dur = part.strip().partition(":")
        stages.append((float(qps), float(dur or "2.0")))
    return stages


def _next_round():
    env = os.environ.get("SERVE_ROUND", "")
    if env:
        return int(env)
    rounds = [int(m.group(1)) for p in glob.glob(
        os.path.join(REPO, "SERVE_r*.json"))
        if (m := re.search(r"_r(\d+)\.json$", p))]
    return max(rounds, default=0) + 1


def run_bench():
    from lightgbm_tpu.reliability import faults
    from lightgbm_tpu.serving import Server
    from lightgbm_tpu.testing.chaos_serve import (dyadic_booster,
                                                  run_open_loop)

    trees = int(os.environ.get("SERVE_BENCH_TREES", 48))
    rows = int(os.environ.get("SERVE_BENCH_ROWS", 8192))
    replicas = int(os.environ.get("SERVE_BENCH_REPLICAS", 2))
    chaos = os.environ.get("SERVE_BENCH_CHAOS", "1") != "0"
    stages = _parse_stages(os.environ.get(
        "SERVE_BENCH_STAGES", "100:2,200:2,400:2,800:2"))

    bst, X = dyadic_booster(n=rows, f=16, trees=trees, num_leaves=31,
                            seed=7)
    per_stage = []
    with Server(min_bucket=16, max_bucket=1024, max_wait_ms=0.5,
                max_queue=4096, n_replicas=replicas, retry_attempts=2,
                breaker_threshold=3, breaker_cooldown_ms=100.0) as srv:
        srv.load_model("bench", booster=bst)
        # warm the bucket cache so stage 1 doesn't pay compile time
        for s in (1, 4, 16, 64):
            srv.predict("bench", X[:s], raw_score=True)

        def _mid(stage):
            # chaos stage: a burst of replica-dispatch faults mid-ramp
            if chaos and stage == max(len(stages) - 2, 1):
                faults.schedule("serving_replica_predict", fail=3)
                print(f"# serve chaos: armed 3 replica faults at stage "
                      f"{stage}", file=sys.stderr)

        for si, (qps, dur) in enumerate(stages):
            if si:
                _mid(si)
            res = run_open_loop(srv, "bench", X, stages=[(qps, dur)],
                                max_rows=64, raw_score=True,
                                timeout_s=60.0, seed=100 + si)
            pct = res.latency_percentiles()
            per_stage.append({
                "target_qps": qps, "achieved_qps": round(res.qps(), 3),
                "issued": res.issued, "dropped": res.dropped,
                **pct, **res.by_outcome()})
            print(f"# serve detail: stage {si} target {qps:g} qps -> "
                  f"achieved {res.qps():.1f} qps, p50/p95/p99 "
                  f"{pct['p50_ms']}/{pct['p95_ms']}/{pct['p99_ms']} ms,"
                  f" outcomes {res.by_outcome()}", file=sys.stderr)

        snap = srv.metrics_snapshot("bench")["models"]["bench"]
        faults.clear()

    within = [s for s in per_stage if s["p99_ms"] < P99_SLO_MS
              and s["dropped"] == 0]
    if within:
        best = max(within, key=lambda s: s["achieved_qps"])
    else:   # nothing held the SLO: report the least-bad stage honestly
        best = min(per_stage, key=lambda s: s["p99_ms"])
    record = {
        "metric": "serve_sustained_qps_p99lt10ms",
        "value": best["achieved_qps"], "unit": "qps",
        "p99_ms": best["p99_ms"], "p50_ms": best["p50_ms"],
        "slo_held": bool(within),
        "replicas": replicas, "trees": trees,
        "shed": snap["shed_count"],
        "fallback": snap["fallback_count"],
        "failovers": snap["failovers"],
        "deadline_misses": snap["deadline_misses"],
        "device_retries": snap["device_retries"],
        "swap_drains": snap["swap_drains"],
        "stages": per_stage,
    }
    total_dropped = sum(s["dropped"] for s in per_stage)
    if total_dropped:
        raise RuntimeError(
            f"{total_dropped} requests dropped/hung during the ramp")
    return record


def run_multimodel_bench():
    """Packed vs unpacked serving of N small + 1 large model under one
    heavy-tailed open-loop schedule. Returns the mm_* record fields."""
    from lightgbm_tpu.serving import Server
    from lightgbm_tpu.testing.chaos_serve import (dyadic_booster,
                                                  run_open_loop)

    n_small = int(os.environ.get("SERVE_MM_SMALL", 4))
    replicas = int(os.environ.get("SERVE_BENCH_REPLICAS", 2))
    stages = _parse_stages(os.environ.get(
        "SERVE_MM_STAGES", "300:2,600:2,900:2"))
    models = []
    for i in range(n_small):
        bst, _ = dyadic_booster(n=2048, f=16, trees=12, num_leaves=15,
                                seed=20 + i)
        models.append((f"small{i}", bst))
    big, X = dyadic_booster(n=8192, f=16, trees=48, num_leaves=31,
                            seed=7)
    models.append(("large", big))
    names = [nm for nm, _ in models]

    def _run(packed):
        # max_bucket 256: requests are tiny (the launch-bound tenant
        # mix), so coalesced blocks never need the top of the ladder —
        # and the warm loop below can afford to cover EVERY bucket,
        # keeping compile time out of the measured window
        with Server(min_bucket=16, max_bucket=256, max_wait_ms=0.5,
                    max_queue=4096, n_replicas=replicas,
                    retry_attempts=2, slo_ms=0.0,
                    scheduler="slo") as srv:
            if packed:
                srv.load_pack("bench_pack", models)
            else:
                for nm, bst in models:
                    srv.load_model(nm, booster=bst)
            for s in (16, 32, 64, 128, 256):
                for nm in names:
                    srv.predict(nm, X[:s], raw_score=True)
            per_stage = []
            for si, (qps, dur) in enumerate(stages):
                res = run_open_loop(srv, names[0], X, stages=[(qps, dur)],
                                    max_rows=8, raw_score=True,
                                    timeout_s=60.0, seed=300 + si,
                                    names=names)
                pct = res.latency_percentiles()
                per_stage.append({
                    "target_qps": qps,
                    "achieved_qps": round(res.qps(), 3),
                    "issued": res.issued, "dropped": res.dropped,
                    **pct, **res.by_outcome()})
                print(f"# serve mm detail: {'packed' if packed else 'unpacked'}"
                      f" stage {si} target {qps:g} -> "
                      f"{res.qps():.1f} qps, p99 {pct['p99_ms']} ms",
                      file=sys.stderr)
            extra = {}
            if packed:
                psnap = srv.metrics_snapshot()["packs"].get(
                    "bench_pack", {})
                extra = {k: psnap.get(k) for k in
                         ("fused_dispatches", "occupancy",
                          "avg_slots_active", "interleaves",
                          "compile_count")}
        within = [s for s in per_stage
                  if s["p99_ms"] < P99_SLO_MS and s["dropped"] == 0]
        best = max(within, key=lambda s: s["achieved_qps"]) if within \
            else min(per_stage, key=lambda s: s["p99_ms"])
        return {"best": best, "slo_held": bool(within),
                "stages": per_stage, **extra}

    unpacked = _run(packed=False)
    packed = _run(packed=True)
    speedup = packed["best"]["achieved_qps"] / \
        max(unpacked["best"]["achieved_qps"], 1e-9)
    return {
        "mm_packed_qps": packed["best"]["achieved_qps"],
        "mm_packed_p99_ms": packed["best"]["p99_ms"],
        "mm_unpacked_qps": unpacked["best"]["achieved_qps"],
        "mm_unpacked_p99_ms": unpacked["best"]["p99_ms"],
        "mm_packed_speedup": round(speedup, 3),
        "multimodel": {
            "n_small": n_small, "large_trees": 48,
            "packed": packed, "unpacked": unpacked},
    }


def main():
    rnd = _next_round()
    cmd = "python bench_serve.py"
    try:
        record = run_bench()
        if os.environ.get("SERVE_MM", "1") != "0":
            record.update(run_multimodel_bench())
        rc = 0
        line = json.dumps(record)
        print(line)
    except Exception as exc:        # unusable sample, honest record
        rc = 1
        record = None
        line = f"# serve bench failed: {type(exc).__name__}: {exc}"
        print(line, file=sys.stderr)
    wrapped = {"n": rnd, "cmd": cmd, "rc": rc, "tail": line,
               "parsed": record}
    out = os.path.join(REPO, f"SERVE_r{rnd:02d}.json")
    with open(out, "w") as fh:
        json.dump(wrapped, fh, indent=1)
        fh.write("\n")
    print(f"# serve record -> {out}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
