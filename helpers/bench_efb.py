"""EFB wide-sparse on-chip benchmark (VERDICT r3 item 3 done-criterion).

Same shape as the round-3 measurement (docs/PerfNotes.md): 200k x 1000,
~95% sparse via 20-feature exclusive groups, max_bin=63, 63 leaves.
Compares the portable EFB grower, the MXU path with the segmented
bundle-space scan (round-4 default), and optionally the round-3
expansion fallback.

Usage: python helpers/bench_efb.py [n_trees] [mode ...]
  modes: portable seg expand   (default: portable seg)
"""

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def make_sparse(n=200_000, f=1000, group=20, seed=11, card=0):
    """card=0: continuous sparse values (~63 bins/feature — bundles stay
    bin-heavy, the MXU's unfavorable case). card=k>0: k distinct values
    per feature (the classic EFB target — one-hot/discrete encodings —
    where bundling collapses hundreds of features per column)."""
    rng = np.random.RandomState(seed)
    X = np.zeros((n, f), np.float32)
    logit = np.zeros(n, np.float32)
    for g in range(0, f, group):
        which = rng.randint(g, g + group, size=n)
        if card:
            vals = (rng.randint(1, card + 1, size=n) /
                    np.float32(card) + 0.5).astype(np.float32)
        else:
            vals = rng.rand(n).astype(np.float32) + 0.5
        X[np.arange(n), which] = vals
        if g == 0:
            logit += np.where(which == 0, vals * 2.0, 0.0)
    logit += 0.5 * X[:, 500] + 0.3 * rng.randn(n).astype(np.float32)
    y = (logit > np.median(logit)).astype(np.float32)
    return X, y


def run_mode(X, y, mode, n_trees):
    import lightgbm_tpu as lgb
    params = {"objective": "binary", "num_leaves": 63, "max_bin": 63,
              "learning_rate": 0.1, "verbosity": -1,
              "min_data_in_leaf": 20}
    if mode == "portable":
        params["efb_use_mxu"] = False
    elif mode == "expand":
        params["efb_segmented_scan"] = False
    elif mode == "seg_quant":
        # the flagship bench posture (quantized 3-channel histograms)
        params["use_quantized_grad"] = True
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
    bst = lgb.Booster(params=params, train_set=ds)
    impl = bst.gbdt._hist_impl
    bst.update()  # warmup/compile tree 1
    float(np.asarray(bst.gbdt.train_score[:1])[0])
    t0 = time.time()
    bst.update_batch(n_trees)
    float(np.asarray(bst.gbdt.train_score[:1])[0])
    dt = time.time() - t0
    from lightgbm_tpu.metrics import AUCMetric
    sc = np.asarray(bst.gbdt.train_score)
    auc = AUCMetric._auc_fast(sc, y > 0, np.ones_like(y))
    print(f"{mode:9s} impl={impl:8s} {n_trees} trees in {dt:7.1f}s = "
          f"{n_trees / dt:5.3f} trees/s  train-AUC@{n_trees + 1} {auc:.5f}",
          flush=True)
    return n_trees / dt


def run_reference(X, y, n_trees):
    """Same-host reference binary at this shape, single core (VERDICT r4
    item 3: the EFB story needs an external anchor, not just internal
    A/Bs). Sparse LibSVM input (a dense 200k x 1000 CSV would be
    ~800 MB); the reference's own EFB (enable_bundle) is on by default.
    Trains twice (2 and n+2 iterations) so its loading/binning time
    cancels out of the per-tree rate."""
    import subprocess
    import tempfile
    bin_ = os.environ.get("LGBM_REFERENCE_BIN", "/tmp/lgbbuild/lightgbm")
    if not os.path.exists(bin_):
        print(f"# reference binary absent ({bin_}); skipping ref row")
        return None
    import shutil
    d = tempfile.mkdtemp(prefix="efb_ref_")
    path = os.path.join(d, "train.svm")
    with open(path, "w") as fh:
        for i in range(X.shape[0]):
            nz = np.nonzero(X[i])[0]
            fh.write("%d %s\n" % (
                int(y[i]),
                " ".join("%d:%.6g" % (j, X[i, j]) for j in nz)))
    times = {}
    for iters in (2, n_trees + 2):
        conf = os.path.join(d, f"train_{iters}.conf")
        with open(conf, "w") as fh:
            fh.write(f"task=train\ndata={path}\nobjective=binary\n"
                     f"num_iterations={iters}\nnum_leaves=63\n"
                     "max_bin=63\nlearning_rate=0.1\n"
                     "min_data_in_leaf=20\nnum_threads=1\nverbosity=-1\n"
                     f"output_model={d}/m{iters}.txt\n")
        t0 = time.time()
        res = subprocess.run([bin_, f"config={conf}"],
                             capture_output=True, text=True, timeout=7200)
        assert res.returncode == 0, \
            res.stdout[-2000:] + res.stderr[-2000:]
        times[iters] = time.time() - t0
    shutil.rmtree(d, ignore_errors=True)
    rate = n_trees / max(times[n_trees + 2] - times[2], 1e-9)
    print(f"reference impl=1-core   {n_trees} trees in "
          f"{times[n_trees + 2] - times[2]:7.1f}s = {rate:5.3f} trees/s "
          f"(loading/binning {times[2]:.0f}s excluded)", flush=True)
    return rate


def main():
    n_trees = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    card = int(os.environ.get("EFB_CARD", 0))
    modes = sys.argv[2:] or ["portable", "seg"]
    X, y = make_sparse(card=card)
    rates = {}
    for mode in modes:
        if mode == "ref":
            r = run_reference(X, y, n_trees)
            if r:
                rates[mode] = r
            continue
        rates[mode] = run_mode(X, y, mode, n_trees)
    if "seg" in rates and "portable" in rates:
        print(f"# card={card}: segmented-MXU / portable speedup: "
              f"{rates['seg'] / rates['portable']:.2f}x")
    if "ref" in rates and "portable" in rates:
        print(f"# card={card}: ours-portable / reference-1-core: "
              f"{rates['portable'] / rates['ref']:.2f}x")


if __name__ == "__main__":
    main()
