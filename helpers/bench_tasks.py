"""TPU bench records for the reference's non-binary task matrix
(VERDICT r4 item 2): regression, multiclass, lambdarank — each timed on
the TPU AND run through the same-host reference binary on one core with
identical data, tree shape (255 leaves / 255 bins), learning rate, and
tree count, so every task of BASELINE.json's config list has a
comparable perf row (docs/Experiments.rst:111-155 publishes 5 tasks;
round 4 had TPU numbers for 1). Postures differ deliberately and are
printed with the rows: ours runs the BENCH posture (quantized grads +
overshoot 1.75 + bridge gate — the documented headline posture,
bench.py), the reference runs its own defaults (this fork predates
use_quantized_grad); both sides' task metrics are printed so the
quality cost of the posture is visible next to the speed.

Shapes are device-scaled (1M rows x 28 features, 255 leaves / 255
bins — the headline bench shape) so the rows are comparable with the
Higgs record. Metrics are computed by THIS script's own evaluators on
identical held-out predictions from both sides.

Usage: python helpers/bench_tasks.py [task ...] [--trees N]
  tasks: regression multiclass lambdarank (default: all)
Needs the reference CLI for the comparison half
(helpers/build_reference_cli.sh -> /tmp/lgbbuild/lightgbm); without it,
ours-only rows are printed.
"""

import os
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
_BIN = os.environ.get("LGBM_REFERENCE_BIN", "/tmp/lgbbuild/lightgbm")

ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
F = 28
VROWS = 40_000
POSTURE = {"num_leaves": 255, "max_bin": 255, "learning_rate": 0.1,
           "min_data_in_leaf": 20, "verbosity": -1,
           "use_quantized_grad": True, "growth_overshoot": 1.75,
           "growth_bridge_gate": 0.93}

# same-host single-core reference rates on these exact synthetic sets
# (run_reference on an idle host, docs/PerfNotes.md round 5) — the
# per-task anchors bench.py's task rows normalize against, mirroring
# SINGLE_CORE_TREES_PER_SEC for the binary headline
SINGLE_CORE_RATES = {"regression": 3.76, "multiclass": 2.93,
                     "lambdarank": 2.47}


# ---------------------------------------------------------------- data
def make_regression(n, seed):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, F).astype(np.float32)
    y = (1.5 * X[:, 0] - 0.9 * X[:, 1] + 0.8 * X[:, 2] * X[:, 3] +
         0.6 * np.abs(X[:, 4]) - 0.5 * X[:, 5] ** 2 +
         0.4 * np.sin(2 * X[:, 6]) + 0.3 * rng.randn(n)).astype(np.float32)
    return X, y, None


def make_multiclass(n, seed, k=5):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, F).astype(np.float32)
    # class geometry must be seed-INDEPENDENT so train and held-out
    # splits share one distribution (only the rows/noise vary by seed)
    centers = np.random.RandomState(7).randn(k, 6) * 1.2
    d = ((X[:, None, :6] - centers[None]) ** 2).sum(-1)
    d += 1.5 * rng.gumbel(size=(n, k))
    y = np.argmin(d, axis=1).astype(np.float32)
    return X, y, None


def make_lambdarank(n, seed, qsize=20):
    rng = np.random.RandomState(seed)
    nq = n // qsize
    n = nq * qsize
    X = rng.randn(n, F).astype(np.float32)
    raw = (1.1 * X[:, 0] - 0.7 * X[:, 1] + 0.5 * X[:, 2] * X[:, 3] +
           0.9 * rng.randn(n))
    # 5-level relevance by global quantile (label_gain default covers it)
    qs = np.quantile(raw, [0.5, 0.75, 0.9, 0.97])
    y = np.digitize(raw, qs).astype(np.float32)
    group = np.full(nq, qsize, np.int32)
    return X, y, group


# ------------------------------------------------------------- metrics
def rmse(pred, y):
    return float(np.sqrt(np.mean((pred - y) ** 2)))


def multi_logloss(pred_raw, y, k):
    p = pred_raw.reshape(-1, k)
    p = p - p.max(axis=1, keepdims=True)
    logp = p - np.log(np.exp(p).sum(axis=1, keepdims=True))
    return float(-np.mean(logp[np.arange(len(y)), y.astype(int)]))


def ndcg_at(pred, y, group, at=10):
    out, pos = [], 0
    for g in group:
        s = slice(pos, pos + g)
        pos += g
        order = np.argsort(-pred[s])
        rel = y[s][order][:at]
        dcg = np.sum((2.0 ** rel - 1) / np.log2(np.arange(len(rel)) + 2))
        ideal = np.sort(y[s])[::-1][:at]
        idcg = np.sum((2.0 ** ideal - 1) /
                      np.log2(np.arange(len(ideal)) + 2))
        out.append(dcg / idcg if idcg > 0 else 1.0)
    return float(np.mean(out))


TASKS = {
    "regression": dict(
        make=make_regression, obj="regression", extra={},
        metric="rmse"),
    "multiclass": dict(
        make=make_multiclass, obj="multiclass",
        extra={"num_class": 5}, metric="multi_logloss"),
    "lambdarank": dict(
        make=make_lambdarank, obj="lambdarank", extra={},
        metric="ndcg@10"),
}


def eval_metric(task, pred, y, group):
    if task == "regression":
        return rmse(pred, y)
    if task == "multiclass":
        return multi_logloss(pred, y, 5)
    return ndcg_at(pred, y, group)


def run_ours(task, n_trees):
    import jax.numpy  # noqa: F401  (device init before timing)
    import lightgbm_tpu as lgb
    spec = TASKS[task]
    X, y, group = spec["make"](ROWS, seed=21)
    Xv, yv, gv = spec["make"](VROWS, seed=99)
    t0 = time.time()
    ds = lgb.Dataset(X, label=y, group=group, params={"max_bin": 255})
    ds.construct()
    bin_t = time.time() - t0
    params = {"objective": spec["obj"], **POSTURE, **spec["extra"]}
    bst = lgb.Booster(params=params, train_set=ds)
    kcls = bst.num_trees_per_iteration
    iters = max(1, n_trees // kcls)
    block = max(1, 20 // kcls)
    # warmup: iteration 0 (normal path) + one block compile — clamped so
    # ours never trains more total trees than the reference row.
    # bench._drain slices ON DEVICE before the host pull — a full
    # np.asarray(train_score) would drag the whole [N, k] score through
    # the tunnel per block (20 MB at 1M x 5; it depressed the first
    # multiclass rows by ~25%, docs/PerfNotes.md round 5)
    from bench import _drain
    bst.update_batch(min(1 + block, iters))
    _drain(bst)
    done = min(1 + block, iters)
    rates = []
    while done < iters:
        step = min(block, iters - done)
        t1 = time.time()
        bst.update_batch(step)
        _drain(bst)
        rates.append(step * kcls / (time.time() - t1))
        done += step
    pred = bst.predict(Xv, raw_score=True)
    m = eval_metric(task, np.asarray(pred).ravel(), yv, gv)
    med = float(np.median(rates)) if rates else 0.0
    best = float(np.max(rates)) if rates else 0.0
    print(f"ours[{task}]: {med:.2f} trees/s median (best {best:.2f}, "
          f"{len(rates)} blocks), {spec['metric']}@{done * kcls} trees = "
          f"{m:.5f}, binning {bin_t:.1f}s", flush=True)
    return med, m


def run_reference(task, n_trees):
    if not os.path.exists(_BIN):
        print(f"# reference binary absent ({_BIN}); ours-only record")
        return None, None
    spec = TASKS[task]
    X, y, group = spec["make"](ROWS, seed=21)
    Xv, yv, gv = spec["make"](VROWS, seed=99)
    d = tempfile.mkdtemp(prefix=f"bt_{task}_")
    np.savetxt(os.path.join(d, "train.csv"),
               np.column_stack([y, X]), delimiter=",", fmt="%.7g")
    np.savetxt(os.path.join(d, "valid.csv"),
               np.column_stack([yv, Xv]), delimiter=",", fmt="%.7g")
    if group is not None:
        np.savetxt(os.path.join(d, "train.csv.query"), group, fmt="%d")
        np.savetxt(os.path.join(d, "valid.csv.query"), gv, fmt="%d")
    extra = "".join(f"{k}={v}\n" for k, v in spec["extra"].items())
    kcls = spec["extra"].get("num_class", 1)
    iters = max(1, n_trees // kcls)
    conf = os.path.join(d, "train.conf")
    with open(conf, "w") as fh:
        fh.write(f"task=train\ndata={d}/train.csv\n"
                 f"objective={spec['obj']}\n{extra}"
                 f"num_iterations={iters}\nnum_leaves=255\nmax_bin=255\n"
                 "learning_rate=0.1\nmin_data_in_leaf=20\n"
                 "header=false\nlabel_column=0\nverbosity=-1\n"
                 "num_threads=1\n"
                 f"output_model={d}/ref_model.txt\n")
    t0 = time.time()
    res = subprocess.run([_BIN, f"config={conf}"], capture_output=True,
                         text=True, timeout=7200)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    t_ref = time.time() - t0
    pconf = os.path.join(d, "pred.conf")
    with open(pconf, "w") as fh:
        fh.write(f"task=predict\ndata={d}/valid.csv\n"
                 f"input_model={d}/ref_model.txt\n"
                 f"output_result={d}/preds.txt\nheader=false\n"
                 "label_column=0\npredict_raw_score=true\n")
    subprocess.run([_BIN, f"config={pconf}"], check=True,
                   capture_output=True, timeout=1200)
    ref = np.loadtxt(os.path.join(d, "preds.txt"))
    m = eval_metric(task, ref.ravel(), yv, gv)
    rate = iters * kcls / t_ref
    print(f"reference[{task}]: {rate:.2f} trees/s 1-core "
          f"({t_ref:.0f}s incl. its own loading/binning), "
          f"{spec['metric']}@{iters * kcls} trees = {m:.5f}", flush=True)
    return rate, m


def main():
    argv = sys.argv[1:]
    n_trees = 100
    if "--trees" in argv:
        i = argv.index("--trees")
        n_trees = int(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    tasks = [a for a in argv if not a.startswith("--")] or list(TASKS)
    for task in tasks:
        run_ours(task, n_trees)
        run_reference(task, n_trees)


if __name__ == "__main__":
    main()
