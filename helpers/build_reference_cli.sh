#!/usr/bin/env bash
# Build the reference LightGBM CLI out-of-tree for differential testing
# (tests/test_reference_consistency.py). The reference checkout has empty
# vendored submodules (no network), so three tiny stand-ins cover the only
# surfaces its core uses: fast_double_parser::parse_number (-> strtod),
# fmt::format_to_n with "{}"/"{:g}"/"{:.17g}" (-> snprintf), and the
# MatrixXd/fullPivLu().inverse() slice of Eigen used by linear trees
# (-> Gauss-Jordan). Its CMake links into the read-only source dir, so the
# final link is done by hand.
#
# Usage: bash helpers/build_reference_cli.sh [REFERENCE_DIR] [BUILD_DIR]
set -euo pipefail
REF=${1:-/root/reference}
BUILD=${2:-/tmp/lgbbuild}
SHIM=$(dirname "$BUILD")/lgbshim

mkdir -p "$SHIM/external_libs/fast_double_parser/include" \
         "$SHIM/external_libs/fmt/include/fmt" \
         "$SHIM/eigen/Eigen" "$SHIM/anchor/a/b"
ln -sfn "$SHIM/external_libs" "$SHIM/anchor/external_libs"

cp "$(dirname "$0")/reference_shims/fast_double_parser.h" \
   "$SHIM/external_libs/fast_double_parser/include/"
cp "$(dirname "$0")/reference_shims/fmt_format.h" \
   "$SHIM/external_libs/fmt/include/fmt/format.h"
cp "$(dirname "$0")/reference_shims/eigen_dense.h" "$SHIM/eigen/Eigen/Dense"

cmake -S "$REF" -B "$BUILD" -DCMAKE_BUILD_TYPE=Release -DUSE_OPENMP=ON \
  -DCMAKE_CXX_FLAGS="-I$SHIM/anchor/a/b -I$SHIM/eigen"
# compile strictly (any failure aborts); only the link into the read-only
# source tree is bypassed, by building the object library and main.cpp
# and linking by hand
cmake --build "$BUILD" -j8 --target lightgbm_objs
for src in main application/application; do
  g++ -std=c++17 -O3 -fopenmp -I"$REF/include" \
    -I"$SHIM/anchor/a/b" -I"$SHIM/eigen" \
    -c "$REF/src/$src.cpp" -o "$BUILD/$(basename "$src").o"
done
g++ -fopenmp -O3 -o "$BUILD/lightgbm" "$BUILD/main.o" \
  "$BUILD/application.o" \
  $(find "$BUILD/CMakeFiles/lightgbm_objs.dir" -name '*.o') -lpthread
echo "built $BUILD/lightgbm"
