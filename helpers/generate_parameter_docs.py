#!/usr/bin/env python
"""Generate docs/Parameters.md from the config registry.

The reference inverts this: docs/Parameters.rst is the source of truth and
helpers/parameter_generator.py emits src/io/config_auto.cpp from it. Here
the typed registry in lightgbm_tpu/config.py is the source of truth and
this script emits the docs, keeping the same single-source guarantee.

Run from the repo root:  python helpers/generate_parameter_docs.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from lightgbm_tpu.config import _PARAMS  # noqa: E402


def main() -> None:
    lines = [
        "# Parameters",
        "",
        "Generated from the typed parameter registry "
        "(`lightgbm_tpu/config.py`) by `helpers/generate_parameter_docs.py`"
        " — do not edit by hand.",
        "",
        "Parameters are accepted as `key=value` pairs on the CLI / in "
        "config files, and as dict entries in the Python API. Aliases "
        "resolve to the canonical name (first match wins, like the "
        "reference alias table `config_auto.cpp:10`).",
        "",
        "| Parameter | Type | Default | Aliases |",
        "|---|---|---|---|",
    ]
    for spec in _PARAMS:
        tname = getattr(spec.type, "__name__", str(spec.type))
        default = repr(spec.default) if spec.default != "" else '""'
        aliases = ", ".join(f"`{a}`" for a in spec.aliases) or "—"
        lines.append(f"| `{spec.name}` | {tname} | {default} | {aliases} |")
    lines.append("")
    lines.append(f"Total: {len(_PARAMS)} parameters.")
    lines.append("")
    out = os.path.join(os.path.dirname(__file__), "..", "docs",
                       "Parameters.md")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as fh:
        fh.write("\n".join(lines))
    print(f"wrote {out} ({len(_PARAMS)} parameters)")


if __name__ == "__main__":
    main()
