"""Round-4 decay instrumentation (VERDICT r3 item 2).

Trains the bench shape with the fused loop in DEBUG mode: every tree
reports (fixup_iters, pre_prune_leaves) from inside the jit, and every
10-tree block is wall-clock timed. If block time correlates with the
block's fixup-pass count, the late-tree decay is fixup-bound; if not,
something else grows.

Usage: python helpers/instrument_decay.py [n_trees] [block]
"""

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax.numpy as jnp  # noqa: E402
import lightgbm_tpu as lgb  # noqa: E402
from bench import make_higgs_like, PARAMS, MAX_BIN, N_FEATURES  # noqa: E402


def main():
    n_trees = int(sys.argv[1]) if len(sys.argv) > 1 else 120
    block = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    X, y = make_higgs_like(rows, N_FEATURES)
    ds = lgb.Dataset(X, label=y, params={"max_bin": MAX_BIN})
    ds.construct()
    bst = lgb.Booster(params=dict(PARAMS), train_set=ds)
    bst.update()  # iteration 0: normal path (init score plumbing)
    g = bst.gbdt
    assert g._fused_eligible(), "bench config must be fused-eligible"
    run = g._build_fused(debug=True)

    rows_out = []
    for b in range(n_trees // block):
        t0 = time.time()
        score, (stacked, dbg) = run(
            g.train_score, jnp.asarray(g.iter_, jnp.int32), k=block)
        g.train_score = score
        fix = np.asarray(dbg[0])
        pre = np.asarray(dbg[1])
        dt = time.time() - t0
        g.iter_ += block
        rec = {"block": b, "time_s": round(dt, 3),
               "trees_per_s": round(block / dt, 3),
               "fixup_iters": fix.tolist(),
               "pre_prune_leaves": pre.tolist(),
               "fixup_sum": int(fix.sum())}
        rows_out.append(rec)
        print(json.dumps(rec), flush=True)

    fs = np.array([r["fixup_sum"] for r in rows_out], float)
    ts = np.array([r["time_s"] for r in rows_out], float)
    if len(rows_out) > 2 and fs.std() > 0:
        b1, b0 = np.polyfit(fs, ts, 1)
        print(f"# fit: block_time = {b0:.2f}s + {b1 * 1000:.1f}ms * "
              f"fixup_pass  (r={np.corrcoef(fs, ts)[0, 1]:.3f})")
    print(f"# rates: first3 {np.mean(block / ts[:3]):.2f} "
          f"last3 {np.mean(block / ts[-3:]):.2f} trees/s")


if __name__ == "__main__":
    main()
