"""Per-component timing of the EFB MXU path at the wide-sparse shape
(docs/PerfNotes.md round 4) — locates the deficit vs the portable
grower without in-jit guesswork."""

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp

from bench_efb import make_sparse  # noqa: E402


def timeit(fn, *args, reps=5, **kw):
    out = fn(*args, **kw)
    jax.tree_util.tree_map(
        lambda a: np.asarray(a).ravel()[:1], out)
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args, **kw)
    jax.tree_util.tree_map(lambda a: np.asarray(a).ravel()[:1], out)
    return (time.time() - t0) / reps


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import lightgbm_tpu as lgb
    from lightgbm_tpu.efb import build_plan, bundle_matrix, \
        make_device_tables
    from lightgbm_tpu.learner.histogram_mxu import (
        fits_v2, fused_route_hist_mxu, pack_route_tables, route_rows_mxu)
    from lightgbm_tpu.learner.split import SplitHyperParams
    from lightgbm_tpu.learner.split_bundled import find_best_splits_bundled

    X, y = make_sparse()
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
    b = ds.binned
    plan = build_plan(np.asarray(b.bins), b.num_bins, b.default_bins,
                      np.asarray(b.is_categorical), max_bundle_bins=256)
    efb = make_device_tables(plan, b.default_bins, num_bins=b.num_bins,
                             missing_is_nan=(b.missing_types == 2),
                             is_cat=np.asarray(b.is_categorical))
    bund = jnp.asarray(bundle_matrix(np.asarray(b.bins), plan))
    n, fb = bund.shape
    bb = efb.bundle_bmax
    f = b.num_features
    print(f"n={n} F={f} Fb={fb} Bb={bb}")
    g = jnp.asarray(np.random.RandomState(0).randn(n), jnp.float32)
    h = jnp.ones(n, jnp.float32)
    cnt = jnp.ones(n, jnp.float32)
    feat_tbl = jnp.stack([jnp.asarray(b.num_bins, jnp.float32),
                          jnp.asarray((b.missing_types == 2),
                                      jnp.float32)], axis=1)
    m_pad = 256
    node0 = jnp.zeros(n, jnp.int32)
    tbl, member = pack_route_tables(
        jnp.zeros(m_pad, bool), jnp.zeros(m_pad, jnp.int32),
        jnp.zeros(m_pad, jnp.int32), jnp.zeros(m_pad, bool),
        jnp.zeros(m_pad, bool), jnp.full(m_pad, 255, jnp.int32),
        jnp.full(m_pad, 255, jnp.int32),
        jnp.full(m_pad, -1, jnp.int32).at[0].set(0),
        jnp.zeros((m_pad, (63 + 31) // 32), jnp.uint32), m_pad, 63,
        efb=efb)

    for sk in (2, 16, 64, 127):
        ok = fits_v2(sk, fb, bb, True, False, route_width=0,
                     row_block=1024)
        if ok:
            dt = timeit(fused_route_hist_mxu, bund, g, h, cnt, node0,
                        tbl, member, feat_tbl, num_slots=sk, bmax=bb,
                        has_cat=False, double_prec=True, quantized=False,
                        efb_range=True, row_block=1024)
        else:
            dt = float("nan")
        print(f"fused sweep sk={sk:4d}: fits_v2={ok} {dt * 1000:8.1f} ms")

    dt = timeit(route_rows_mxu, bund, node0, tbl, member, feat_tbl,
                efb_range=True)
    print(f"route only:            {dt * 1000:8.1f} ms")

    s = 127
    rng = np.random.RandomState(1)
    hist_b = jnp.asarray(rng.rand(s, fb, bb, 3).astype(np.float32))
    pg = jnp.asarray(rng.randn(s).astype(np.float32))
    ph = jnp.ones(s, jnp.float32) * 100
    pc = jnp.ones(s, jnp.float32) * 1000
    po = jnp.zeros(s, jnp.float32)
    hp = SplitHyperParams(min_data_in_leaf=20)
    dt = timeit(find_best_splits_bundled, hist_b, pg, ph, pc, po,
                jnp.asarray(b.num_bins),
                jnp.asarray(b.missing_types == 2),
                jnp.asarray(b.is_categorical),
                jnp.ones(f, jnp.float32), hp, efb)
    print(f"bundled scan S={s}:    {dt * 1000:8.1f} ms")


if __name__ == "__main__":
    main()
