"""Per-pass floor microbench at the MAIN bench shape (1M x 28 x 255).

Round-4 closed with per-tree time ~= dots(123ms) + per-pass floors
(~15ms x ~10) + recon(36ms) + glue(30ms); the floors are now the
largest line item (docs/PerfNotes.md).  This times the fused
route+hist sweep (the whole per-pass kernel cost) across kernel-slot
counts and row blocks to separate:
  - MXU row-padding waste (C*sk < 128 on early passes),
  - per-grid-step overhead (489 steps at row_block=2048),
  - the dot's true slot-proportional cost,
and times the sibling-reconstruction dot at f32-HIGHEST vs an exact
split-bf16 2-pass formulation.

Usage: python helpers/microbench_pass.py [sweep|recon|all]
"""

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

N = 1_000_000
F = 28
BMAX = 256
M_PAD = 896          # round_up(2*447-1+1, 128) at overshoot 1.75


def timeit(fn, *args, reps=10, **kw):
    out = fn(*args, **kw)
    jax.tree_util.tree_map(lambda a: np.asarray(a).ravel()[:1], out)
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args, **kw)
    jax.tree_util.tree_map(lambda a: np.asarray(a).ravel()[:1], out)
    return (time.time() - t0) / reps


def make_pass_state(sk, rng):
    """Tables emulating a mid-tree pass: sk parents split last pass,
    children carry kernel slots, rows sit in the parents."""
    from lightgbm_tpu.learner.histogram_mxu import pack_route_tables
    m1 = M_PAD
    ids = np.arange(m1)
    split = ids < sk
    feat = ids % F
    thr = np.full(m1, 128)
    child_l = np.where(split, sk + 2 * ids, -1)
    child_r = np.where(split, sk + 2 * ids + 1, -1)
    slot = np.full(m1, -1)
    child_ids = ids - sk
    is_child = (ids >= sk) & (ids < 3 * sk)
    slot[is_child] = child_ids[is_child] % sk
    tbl, member = pack_route_tables(
        jnp.asarray(split), jnp.asarray(feat, jnp.int32),
        jnp.asarray(thr, jnp.int32), jnp.zeros(m1, bool),
        jnp.zeros(m1, bool), jnp.asarray(child_l, jnp.int32),
        jnp.asarray(child_r, jnp.int32), jnp.asarray(slot, jnp.int32),
        jnp.zeros((m1, (BMAX + 31) // 32), jnp.uint32), M_PAD, BMAX)
    row_node = jnp.asarray(rng.randint(0, max(sk, 1), N), jnp.int32)
    return tbl, member, row_node


def bench_sweep():
    from lightgbm_tpu.learner.histogram_mxu import fused_route_hist_mxu
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, BMAX, (N, F)), jnp.uint8)
    g = jnp.asarray(rng.randint(-127, 128, N), jnp.float32)
    h = jnp.asarray(rng.randint(0, 128, N), jnp.float32)
    cnt = jnp.ones(N, jnp.float32)
    feat_tbl = jnp.stack([jnp.full(F, 255.0), jnp.zeros(F)], axis=1)

    print("# fused_route_hist_mxu, quantized (3ch), m table rows below")
    print("sk\trb\tm_cap\tms")
    for sk in (2, 9, 16, 24, 40, 72, 136, 232):
        tbl, member, row_node = make_pass_state(sk, rng)
        for rb in (2048, 4096, 8192, 16384):
            for m_cap in ({128, M_PAD} if sk <= 24 else {M_PAD}):
                if 3 * sk > m_cap:
                    continue
                t = tbl[:m_cap]
                mem = member[:m_cap]
                try:
                    dt = timeit(
                        fused_route_hist_mxu, bins, g, h, cnt, row_node,
                        t, mem, feat_tbl, num_slots=sk, bmax=BMAX,
                        has_cat=False, double_prec=True, quantized=True,
                        row_block=rb)
                except Exception as e:
                    print(f"{sk}\t{rb}\t{m_cap}\tFAIL {type(e).__name__}")
                    continue
                print(f"{sk}\t{rb}\t{m_cap}\t{dt * 1e3:.2f}", flush=True)


def bench_recon():
    s, sk, p_all = 448, 232, 226
    fb3 = F * BMAX * 3
    rng = np.random.RandomState(1)
    kern2 = jnp.asarray(rng.rand(sk, fb3), jnp.float32)
    parent = jnp.asarray(rng.rand(p_all, fb3), jnp.float32)
    mk = jnp.asarray(rng.randint(-1, 2, (s, sk)), jnp.float32)
    mp = jnp.asarray((rng.rand(s, p_all) < 0.01), jnp.float32)

    @jax.jit
    def recon_highest(mk, mp, kern2, parent):
        return jax.lax.dot_general(
            jnp.concatenate([mk, mp], axis=1),
            jnp.concatenate([kern2, parent], axis=0),
            dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)

    @jax.jit
    def recon_split(mk, mp, kern2, parent):
        lhs = jnp.concatenate([mk, mp], axis=1).astype(jnp.bfloat16)
        rhs = jnp.concatenate([kern2, parent], axis=0)
        hi = jax.lax.reduce_precision(rhs, exponent_bits=8,
                                      mantissa_bits=7)
        lo = rhs - hi
        d = lambda r: jax.lax.dot_general(
            lhs, r.astype(jnp.bfloat16),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return d(hi) + d(lo)

    a = timeit(recon_highest, mk, mp, kern2, parent)
    b = timeit(recon_split, mk, mp, kern2, parent)
    ra = np.asarray(recon_highest(mk, mp, kern2, parent))
    rb = np.asarray(recon_split(mk, mp, kern2, parent))
    rel = np.abs(ra - rb).max() / max(np.abs(ra).max(), 1e-30)
    print(f"# recon dot [s={s}, {sk}+{p_all}] x [{fb3}]")
    print(f"highest\t{a * 1e3:.2f} ms")
    print(f"split2\t{b * 1e3:.2f} ms\tmax rel diff {rel:.2e}")

    # the parent-carry dot (sel_p), same shapes transposed
    selp = jnp.asarray((rng.rand(p_all, s) < 0.004), jnp.float32)
    hist = jnp.asarray(rng.rand(s, fb3), jnp.float32)

    @jax.jit
    def carry_highest(selp, hist):
        return jax.lax.dot_general(
            selp, hist, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)

    @jax.jit
    def carry_split(selp, hist):
        hi = jax.lax.reduce_precision(hist, exponent_bits=8,
                                      mantissa_bits=7)
        sl = selp.astype(jnp.bfloat16)
        d = lambda r: jax.lax.dot_general(
            sl, r.astype(jnp.bfloat16),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return d(hi) + d(hist - hi)

    a = timeit(carry_highest, selp, hist)
    b = timeit(carry_split, selp, hist)
    print(f"carry_highest\t{a * 1e3:.2f} ms")
    print(f"carry_split\t{b * 1e3:.2f} ms")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("sweep", "all"):
        bench_sweep()
    if which in ("recon", "all"):
        bench_recon()
