"""Per-pass floor microbench at the MAIN bench shape (1M x 28 x 255).

Round-4 closed with per-tree time ~= dots(123ms) + per-pass floors
(~15ms x ~10) + recon(36ms) + glue(30ms); the floors are now the
largest line item (docs/PerfNotes.md).  This times the fused
route+hist sweep (the whole per-pass kernel cost) across kernel-slot
counts and row blocks to separate:
  - MXU row-padding waste (C*sk < 128 on early passes),
  - per-grid-step overhead (489 steps at row_block=2048),
  - the dot's true slot-proportional cost,
and times the sibling-reconstruction dot at f32-HIGHEST vs an exact
split-bf16 2-pass formulation.

All timings are CHAINED IN-JIT (k dependency-chained iterations per
dispatch, long-minus-short differencing) — per-dispatch tunnel latency
through the remoted accelerator is tens of ms and would swamp
single-call numbers.

Usage: python helpers/microbench_pass.py [sweep|recon|tree|all]
"""

import sys
import time

import numpy as np

import os
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

N = 1_000_000
F = 28
BMAX = 256
M_PAD = 896          # round_up(2*447-1+1, 128) at overshoot 1.75


def timeit_chained(body, carry0, reps=16):
    """Per-iteration seconds of `body` (carry -> carry), timed as one
    jitted fori_loop dispatch of 2+reps iterations minus one of 2."""

    @jax.jit
    def chain(c0, k):
        return jax.lax.fori_loop(0, k, lambda i, c: body(c), c0)

    def run(k):
        out = chain(carry0, jnp.asarray(k, jnp.int32))
        jax.tree_util.tree_map(lambda a: np.asarray(a).ravel()[:1], out)

    run(2)  # compile + warm
    best = np.inf
    for _ in range(2):
        t0 = time.time()
        run(2 + reps)
        dt_long = time.time() - t0
        t0 = time.time()
        run(2)
        dt_short = time.time() - t0
        best = min(best, (dt_long - dt_short) / reps)
    return best


def make_pass_state(sk, rng):
    """Ping-pong tables: sk parents split into children that split
    straight back, so EVERY chained iteration routes through a split
    node (full decision math + slot pickup) and builds sk slots —
    the steady-pass cost, not the settled-rows shortcut."""
    from lightgbm_tpu.learner.histogram_mxu import pack_route_tables
    m1 = M_PAD
    ids = np.arange(m1)
    is_parent = ids < sk
    is_child = (ids >= sk) & (ids < 3 * sk)
    split = is_parent | is_child
    feat = ids % F
    thr = np.full(m1, 128)
    child_l = np.where(is_parent, sk + 2 * ids,
                       np.where(is_child, (ids - sk) // 2, -1))
    child_r = np.where(is_parent, sk + 2 * ids + 1,
                       np.where(is_child, (ids - sk) // 2, -1))
    slot = np.where(split, ids % sk, -1)
    tbl, member = pack_route_tables(
        jnp.asarray(split), jnp.asarray(feat, jnp.int32),
        jnp.asarray(thr, jnp.int32), jnp.zeros(m1, bool),
        jnp.zeros(m1, bool), jnp.asarray(child_l, jnp.int32),
        jnp.asarray(child_r, jnp.int32), jnp.asarray(slot, jnp.int32),
        jnp.zeros((m1, (BMAX + 31) // 32), jnp.uint32), M_PAD, BMAX)
    row_node = jnp.asarray(rng.randint(0, max(sk, 1), N), jnp.int32)
    return tbl, member, row_node


def bench_sweep():
    from lightgbm_tpu.learner.histogram_mxu import fused_route_hist_mxu
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, BMAX, (N, F)), jnp.uint8)
    g = jnp.asarray(rng.randint(-127, 128, N), jnp.float32)
    h = jnp.asarray(rng.randint(0, 128, N), jnp.float32)
    cnt = jnp.ones(N, jnp.float32)
    feat_tbl = jnp.stack([jnp.full(F, 255.0), jnp.zeros(F)], axis=1)

    def _r128(x):
        return min(M_PAD, ((x + 127) // 128) * 128)

    print("# fused_route_hist_mxu per pass, quantized (3ch), chained")
    print("sk\trb\tm_cap\tms")
    # m_cap mirrors the grower's per-pass slice (round_up to lanes of
    # the live node-id range); the sk=72 full-width row quantifies the
    # table-width cost at mid frontier
    for sk in (16, 72, 136, 232):
        tbl, member, row_node = make_pass_state(sk, rng)
        for rb in (2048, 4096, 8192):
            for m_cap in ({_r128(3 * sk), M_PAD} if sk == 72 and
                          rb == 2048 else {_r128(3 * sk)}):
                t = tbl[:m_cap]
                mem = member[:m_cap]

                def body(rn):
                    _h, rn2 = fused_route_hist_mxu(
                        bins, g, h, cnt, rn, t, mem, feat_tbl,
                        num_slots=sk, bmax=BMAX, has_cat=False,
                        double_prec=True, quantized=True, row_block=rb)
                    return rn2

                try:
                    dt = timeit_chained(body, row_node)
                except Exception as e:
                    print(f"{sk}\t{rb}\t{m_cap}\tFAIL {type(e).__name__}")
                    continue
                print(f"{sk}\t{rb}\t{m_cap}\t{dt * 1e3:.2f}", flush=True)


def bench_recon():
    s, sk, p_all = 448, 232, 226
    fb3 = F * BMAX * 3
    rng = np.random.RandomState(1)
    kern2 = jnp.asarray(rng.rand(sk, fb3), jnp.float32)
    parent = jnp.asarray(rng.rand(p_all, fb3), jnp.float32)
    mk = jnp.asarray(rng.randint(-1, 2, (s, sk)), jnp.float32)
    mp = jnp.asarray((rng.rand(s, p_all) < 0.01), jnp.float32)

    def recon_highest(kern2):
        return jax.lax.dot_general(
            jnp.concatenate([mk, mp], axis=1),
            jnp.concatenate([kern2, parent], axis=0),
            dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)

    def recon_split(kern2):
        lhs = jnp.concatenate([mk, mp], axis=1).astype(jnp.bfloat16)
        rhs = jnp.concatenate([kern2, parent], axis=0)
        hi = jax.lax.reduce_precision(rhs, exponent_bits=8,
                                      mantissa_bits=7)
        lo = rhs - hi
        d = lambda r: jax.lax.dot_general(
            lhs, r.astype(jnp.bfloat16),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return d(hi) + d(lo)

    a = timeit_chained(lambda k2: recon_highest(k2)[:sk], kern2,
                       reps=300)
    b = timeit_chained(lambda k2: recon_split(k2)[:sk], kern2,
                       reps=300)
    ra = np.asarray(recon_highest(kern2))
    rb = np.asarray(recon_split(kern2))
    rel = np.abs(ra - rb).max() / max(np.abs(ra).max(), 1e-30)
    print(f"# recon dot [s={s}, {sk}+{p_all}] x [{fb3}], chained")
    print(f"highest\t{a * 1e3:.2f} ms")
    print(f"split2\t{b * 1e3:.2f} ms\tmax rel diff {rel:.2e}")

    # the parent-carry dot (sel_p): [P, s] x [s, F*B*3]
    selp = jnp.asarray((rng.rand(p_all, s) < 0.004), jnp.float32)
    hist = jnp.asarray(rng.rand(s, fb3), jnp.float32)

    def carry_highest(hist):
        return jax.lax.dot_general(
            selp, hist, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)

    def carry_split(hist):
        hi = jax.lax.reduce_precision(hist, exponent_bits=8,
                                      mantissa_bits=7)
        sl = selp.astype(jnp.bfloat16)
        d = lambda r: jax.lax.dot_general(
            sl, r.astype(jnp.bfloat16),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return d(hi) + d(hist - hi)

    pad = jnp.zeros((s - p_all, fb3), jnp.float32)
    a = timeit_chained(
        lambda h_: jnp.concatenate([carry_highest(h_), pad]), hist,
        reps=300)
    b = timeit_chained(
        lambda h_: jnp.concatenate([carry_split(h_), pad]), hist,
        reps=300)
    print(f"carry_highest\t{a * 1e3:.2f} ms")
    print(f"carry_split\t{b * 1e3:.2f} ms")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("sweep", "all"):
        bench_sweep()
    if which in ("recon", "all"):
        bench_recon()


def bench_tree():
    """Chained whole-tree growth on the REAL bench data/config —
    separates the grower's cost from the boosting ring's (grad/quantize/
    score/stacking glue): ring = fused-block per-tree minus this."""
    sys.path.insert(0, REPO)
    from bench import make_higgs_like, PARAMS, MAX_BIN, N_FEATURES
    import lightgbm_tpu as lgb
    from lightgbm_tpu.learner.grower_mxu import grow_tree_mxu

    X, y = make_higgs_like(N, N_FEATURES)
    ds = lgb.Dataset(X, label=y, params={"max_bin": MAX_BIN})
    bst = lgb.Booster(params=dict(PARAMS), train_set=ds)
    g = bst.gbdt
    kw = g._mxu_grow_kwargs()
    print("# grower kwargs:", {k: v for k, v in kw.items()
                               if not hasattr(v, "shape")})
    yd = jnp.asarray(y)
    p = jnp.float32(0.5)
    grad0 = p - yd
    hess0 = jnp.full(N, 0.25, jnp.float32)
    cnt = jnp.ones(N, jnp.float32)
    fmask = jnp.ones(N_FEATURES, jnp.float32)
    key = jax.random.PRNGKey(3)

    def body(rn):
        # dependency chain without changing the data: 0*rn is not
        # foldable for floats per IEEE (rn is int -> cast first)
        g_in = grad0 + 0.0 * rn.astype(jnp.float32)
        tree, rn2 = grow_tree_mxu(
            g.bins, g_in, hess0, cnt, fmask, g.num_bins_d,
            g.missing_is_nan_d, g.is_cat_d, rng_key=key, **kw)
        return rn2

    dt = timeit_chained(body, jnp.zeros(N, jnp.int32), reps=10)
    print(f"whole-tree growth (chained): {dt * 1e3:.1f} ms/tree")


if __name__ == "__main__" and "tree" in sys.argv[1:]:
    bench_tree()
