"""Re-certify 200-tree AUC parity vs the reference binary at TODAY'S
defaults (VERDICT r3 item 8): the recorded 0.98388-vs-0.98394 number
predates quantized gradients, packed bins, EFB-default-on, the
segmented scan, and the fused loop.

Trains both on the identical Higgs-shaped 1M x 28 synthetic set with
255 leaves / 255 bins / 200 trees and compares held-out AUC.

Usage: python helpers/recert_auc_parity.py [n_trees] [rows]
Needs the reference CLI (helpers/build_reference_cli.sh ->
/tmp/lgbbuild/lightgbm).
"""

import os
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
_BIN = os.environ.get("LGBM_REFERENCE_BIN", "/tmp/lgbbuild/lightgbm")


def main():
    n_trees = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    rows = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000_000
    from bench import make_higgs_like, N_FEATURES
    from lightgbm_tpu.metrics import AUCMetric
    X, y = make_higgs_like(rows, N_FEATURES)
    Xva, yva = make_higgs_like(40_000, N_FEATURES, seed=99)
    wva = np.ones_like(yva)

    # ---- ours, today's library DEFAULTS (exact grads) + bench posture
    import lightgbm_tpu as lgb
    out = {}
    for name, extra in [("default", {}),
                        ("bench", {"use_quantized_grad": True})]:
        ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
        bst = lgb.Booster(params={
            "objective": "binary", "num_leaves": 255, "max_bin": 255,
            "learning_rate": 0.1, "min_data_in_leaf": 20,
            "verbosity": -1, **extra}, train_set=ds)
        t0 = time.time()
        # 20-tree dispatches: one giant fused scan of 200 trees crashed
        # the remoted TPU worker twice (long-dispatch tunnel limit)
        done = 0
        while done < n_trees:
            step = min(20, n_trees - done)
            bst.update_batch(step)
            float(np.asarray(bst.gbdt.train_score[:1])[0])
            done += step
        sc = bst.predict(Xva, raw_score=True)
        out[name] = AUCMetric._auc_fast(sc, yva > 0, wva)
        print(f"ours[{name}]: AUC@{bst.current_iteration()} = "
              f"{out[name]:.5f}  ({time.time() - t0:.0f}s)", flush=True)

    # ---- reference binary, same data/params
    if not os.path.exists(_BIN):
        print("# reference binary absent; ours-only record")
        return
    d = tempfile.mkdtemp(prefix="recert_")
    np.savetxt(os.path.join(d, "train.csv"),
               np.column_stack([y, X]), delimiter=",", fmt="%.7g")
    np.savetxt(os.path.join(d, "valid.csv"),
               np.column_stack([yva, Xva]), delimiter=",", fmt="%.7g")
    conf = os.path.join(d, "train.conf")
    with open(conf, "w") as fh:
        fh.write(f"task=train\ndata={d}/train.csv\nobjective=binary\n"
                 f"num_iterations={n_trees}\nnum_leaves=255\nmax_bin=255\n"
                 "learning_rate=0.1\nmin_data_in_leaf=20\n"
                 "header=false\nlabel_column=0\nverbosity=-1\n"
                 f"output_model={d}/ref_model.txt\n")
    t0 = time.time()
    res = subprocess.run([_BIN, f"config={conf}"], capture_output=True,
                         text=True, timeout=3600)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    t_ref = time.time() - t0
    pconf = os.path.join(d, "pred.conf")
    with open(pconf, "w") as fh:
        fh.write(f"task=predict\ndata={d}/valid.csv\n"
                 f"input_model={d}/ref_model.txt\n"
                 f"output_result={d}/preds.txt\nheader=false\n"
                 "label_column=0\npredict_raw_score=true\n")
    subprocess.run([_BIN, f"config={pconf}"], check=True,
                   capture_output=True, timeout=600)
    ref_sc = np.loadtxt(os.path.join(d, "preds.txt"))
    ref_auc = AUCMetric._auc_fast(ref_sc, yva > 0, wva)
    print(f"reference: AUC@{n_trees} = {ref_auc:.5f}  "
          f"({t_ref:.0f}s train = {n_trees / t_ref:.2f} trees/s 1-core)")
    for name, auc in out.items():
        print(f"# gap[{name} - reference] = {auc - ref_auc:+.5f}")


if __name__ == "__main__":
    main()
