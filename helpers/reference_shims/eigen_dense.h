// Mini stand-in for Eigen (vendored submodule absent): exactly the surface
// linear_tree_learner.cpp touches — dynamic double matrices, (i,j)/(i)
// access, product, unary minus, fullPivLu().inverse() via Gauss-Jordan
// with partial pivoting (singular matrices yield inf/nan like Eigen).
#pragma once
#include <cmath>
#include <cstddef>
#include <utility>
#include <vector>
namespace Eigen {
class MatrixXd;
struct FullPivLU_shim {
  const MatrixXd* m;
  inline MatrixXd inverse() const;
};
class MatrixXd {
 public:
  MatrixXd() : r_(0), c_(0) {}
  MatrixXd(std::ptrdiff_t r, std::ptrdiff_t c)
      : r_(r), c_(c), d_(r * c, 0.0) {}
  double& operator()(std::ptrdiff_t i, std::ptrdiff_t j) {
    return d_[i * c_ + j];
  }
  double operator()(std::ptrdiff_t i, std::ptrdiff_t j) const {
    return d_[i * c_ + j];
  }
  double& operator()(std::ptrdiff_t i) { return d_[i]; }
  double operator()(std::ptrdiff_t i) const { return d_[i]; }
  std::ptrdiff_t rows() const { return r_; }
  std::ptrdiff_t cols() const { return c_; }

  MatrixXd operator*(const MatrixXd& o) const {
    MatrixXd out(r_, o.c_);
    for (std::ptrdiff_t i = 0; i < r_; ++i)
      for (std::ptrdiff_t k = 0; k < c_; ++k) {
        const double v = (*this)(i, k);
        for (std::ptrdiff_t j = 0; j < o.c_; ++j)
          out(i, j) += v * o(k, j);
      }
    return out;
  }
  MatrixXd operator-() const {
    MatrixXd out(r_, c_);
    for (size_t i = 0; i < d_.size(); ++i) out.d_[i] = -d_[i];
    return out;
  }
  FullPivLU_shim fullPivLu() const { return FullPivLU_shim{this}; }

 private:
  std::ptrdiff_t r_, c_;
  std::vector<double> d_;
};

inline MatrixXd FullPivLU_shim::inverse() const {
  const std::ptrdiff_t n = m->rows();
  MatrixXd a = *m;
  MatrixXd inv(n, n);
  for (std::ptrdiff_t i = 0; i < n; ++i) inv(i, i) = 1.0;
  for (std::ptrdiff_t col = 0; col < n; ++col) {
    std::ptrdiff_t piv = col;
    for (std::ptrdiff_t i = col + 1; i < n; ++i)
      if (std::fabs(a(i, col)) > std::fabs(a(piv, col))) piv = i;
    if (piv != col)
      for (std::ptrdiff_t j = 0; j < n; ++j) {
        std::swap(a(col, j), a(piv, j));
        std::swap(inv(col, j), inv(piv, j));
      }
    const double p = a(col, col);
    for (std::ptrdiff_t j = 0; j < n; ++j) {
      a(col, j) /= p;
      inv(col, j) /= p;
    }
    for (std::ptrdiff_t i = 0; i < n; ++i) {
      if (i == col) continue;
      const double f = a(i, col);
      if (f == 0.0) continue;
      for (std::ptrdiff_t j = 0; j < n; ++j) {
        a(i, j) -= f * a(col, j);
        inv(i, j) -= f * inv(col, j);
      }
    }
  }
  return inv;
}
}  // namespace Eigen
