// stand-in for the vendored fast_double_parser (submodule not checked out;
// no network in this environment). strtod has the same accept-grammar for
// the inputs LightGBM feeds it and runs under the C locale here.
#pragma once
#include <cstdlib>
namespace fast_double_parser {
inline const char* parse_number(const char* p, double* out) {
  char* end = nullptr;
  double v = std::strtod(p, &end);
  if (end == p) return nullptr;
  *out = v;
  return end;
}
}  // namespace fast_double_parser
