// stand-in for vendored {fmt}: LightGBM only calls
// fmt::format_to_n(buf, n, fmt, value) with "{}", "{:g}", "{:.17g}".
#pragma once
#include <cstdio>
#include <cstring>
#include <type_traits>
namespace fmt {
struct format_to_n_result_shim { char* out; size_t size; };
template <typename T>
inline format_to_n_result_shim format_to_n(char* buf, size_t n,
                                           const char* format, T value) {
  int written;
  if constexpr (std::is_floating_point<T>::value) {
    const char* pf = "%.17g";
    if (std::strcmp(format, "{:g}") == 0) pf = "%g";
    written = std::snprintf(buf, n, pf, static_cast<double>(value));
  } else if constexpr (std::is_signed<T>::value) {
    written = std::snprintf(buf, n, "%lld",
                            static_cast<long long>(value));
  } else {
    written = std::snprintf(buf, n, "%llu",
                            static_cast<unsigned long long>(value));
  }
  return {buf + (written < (int)n ? written : (int)n), (size_t)written};
}
}  // namespace fmt
