"""Deterministic chunked synthetic data: 100M-row runs from one seed.

Counter-based generation (Philox) with a FIXED number of 64-bit draws
per row, so chunk k's values depend only on (seed, absolute row index)
— never on chunk size or iteration order. `synth_chunk(row0, n)` jumps
the Philox counter straight to `row0 * draws_per_row` and draws exactly
`n * draws_per_row` uniforms; any chunking of [0, N) therefore yields
the byte-identical dataset (tests/test_streaming.py locks this).

Normals come from Box-Muller on uniform pairs — fixed two draws per
normal. NumPy's `standard_normal` uses ziggurat rejection sampling with
data-dependent draw consumption, which would break the row->counter
alignment; don't substitute it.

The feature/label rule mirrors bench.py's `make_higgs_like` (a few
"physics" features + noise dims, roughly balanced binary labels), so
`bench.py --synth rows=...,cols=...` benches the same problem shape at
out-of-core scale without ever materializing the matrix.
"""

from __future__ import annotations

import numpy as np

from lightgbm_tpu.streaming import ChunkSource

__all__ = ["SynthSource", "synth_chunk", "draws_per_row"]


def draws_per_row(cols: int) -> int:
    """Fixed 64-bit draw budget per row: a Box-Muller pair per feature
    plus one pair for the label-noise normal, padded up to a multiple
    of 4 because Philox `advance(delta)` skips whole counter blocks of
    four 64-bit outputs — a row boundary must land on a block boundary
    for the counter jump to be expressible."""
    need = 2 * int(cols) + 2
    return (need + 3) // 4 * 4


def synth_chunk(row0: int, n: int, cols: int, seed: int = 17):
    """Rows [row0, row0 + n) of the (seed, cols) dataset:
    (X float32 [n, cols], y float32 [n])."""
    dpr = draws_per_row(cols)
    bg = np.random.Philox(key=np.uint64(seed))
    bg.advance(int(row0) * (dpr // 4))
    u = np.random.Generator(bg).random((n, dpr), dtype=np.float64)
    u = u[:, :2 * cols + 2]  # drop block-alignment padding
    # Box-Muller: z_j from the uniform pair (u[2j], u[2j+1])
    u1 = np.maximum(u[:, 0::2], np.finfo(np.float64).tiny)
    u2 = u[:, 1::2]
    z = np.sqrt(-2.0 * np.log(u1)) * np.cos(2.0 * np.pi * u2)
    X = z[:, :cols].astype(np.float32)
    noise = z[:, cols]

    def c(i):
        return X[:, i % cols].astype(np.float64)

    logit = (1.2 * c(0) - 0.8 * c(1) + 0.6 * c(2) * c(3) +
             0.5 * np.abs(c(4)) - 0.4 * c(5) ** 2 +
             0.3 * c(6) * c(0) + 0.35 * noise)
    # E[0.5|z|] - 0.4 E[z^2] ~ 0, so threshold 0 is ~balanced without
    # needing the global median (which a stream cannot know chunk-wise)
    y = (logit > 0.0).astype(np.float32)
    return X, y


class SynthSource(ChunkSource):
    """ChunkSource over the synthetic dataset — nothing materialized
    beyond one chunk; restartable at any chunk by counter jump."""

    has_label = True

    def __init__(self, rows: int, cols: int, chunk_rows: int = 65536,
                 seed: int = 17):
        super().__init__(chunk_rows)
        self.num_rows = int(rows)
        self.num_features = int(cols)
        self.seed = int(seed)

    def chunks(self, start_chunk: int = 0):
        step = self.chunk_rows
        for lo in range(start_chunk * step, self.num_rows, step):
            n = min(step, self.num_rows - lo)
            yield synth_chunk(lo, n, self.num_features, self.seed)

    def describe(self) -> str:
        return (f"synth[{self.num_rows}x{self.num_features} "
                f"seed={self.seed}]")
