"""lightgbm_tpu: TPU-native gradient boosting framework (JAX/XLA/Pallas).

A ground-up redesign of LightGBM's capabilities (reference:
SNSerHello/LightGBM, mounted at /root/reference) for TPU hardware:
histogram GBDT with device-resident binned data, fully-jitted tree growth,
and data-/feature-/voting-parallel training over `jax.sharding` meshes.
"""

__version__ = "0.1.0"

from .config import Config
from .parallel import setup_multihost
from .utils.log import LightGBMError, register_logger

try:  # user-facing API (available once all layers are built)
    from .basic import Booster, Dataset, Sequence
    from .callback import (early_stopping, log_evaluation,
                           record_evaluation, reset_parameter)
    from .engine import cv, train
    from .plotting import plot_importance, plot_metric, plot_tree
    from . import observability
    from . import serving
except ImportError:  # pragma: no cover - during partial builds only
    pass

__all__ = ["Dataset", "Booster", "Sequence", "train", "cv", "Config", "LightGBMError",
           "register_logger", "early_stopping", "log_evaluation",
           "record_evaluation", "reset_parameter", "plot_importance",
           "plot_metric", "plot_tree", "setup_multihost", "observability",
           "serving", "__version__"]
