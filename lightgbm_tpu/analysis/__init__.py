"""tpulint: AST-based invariant checker for this codebase.

Run as ``python -m lightgbm_tpu.analysis [paths...]`` (defaults to the
installed package). Rule catalogue and suppression syntax:
docs/StaticAnalysis.md. Wired into ``make lint`` and enforced at
zero unsuppressed findings by tests/test_static_analysis.py (tier-1).
"""

from .engine import (Analyzer, Finding, ParsedFile, ProjectContext,
                     ProjectRule, Rule, all_rules)

__all__ = [
    "Analyzer", "Finding", "ParsedFile", "ProjectContext", "ProjectRule",
    "Rule", "all_rules",
]
