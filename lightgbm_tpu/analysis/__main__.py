"""CLI entry: ``python -m lightgbm_tpu.analysis [paths...]``.

Exit status 0 iff zero unsuppressed findings — the contract
tests/test_static_analysis.py enforces as a tier-1 test.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .engine import Analyzer, all_rules


def _default_paths() -> List[str]:
    # the package this module ships in
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="tpulint: AST invariant checker (jit hygiene, lock "
                    "discipline, registry consistency). See "
                    "docs/StaticAnalysis.md.")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to scan (default: the "
                             "installed lightgbm_tpu package)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text", help="output format (sarif for "
                        "CI diff annotation)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings (text mode)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    parser.add_argument("--no-interproc", action="store_true",
                        help="disable the cross-function call-graph "
                             "engine (intraprocedural findings only)")
    parser.add_argument("--no-cache", action="store_true",
                        help="bypass the .tpulint_cache/ incremental "
                             "store (CI runs hermetic)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id} [{rule.severity}] {rule.doc}")
        return 0

    paths = args.paths or _default_paths()
    analyzer = Analyzer(interproc=not args.no_interproc,
                        cache=not args.no_cache)
    findings = analyzer.run(paths)
    if args.format == "json":
        print(Analyzer.render_json(findings))
    elif args.format == "sarif":
        print(Analyzer.render_sarif(findings, analyzer.rules))
    else:
        print(Analyzer.render_text(findings,
                                   show_suppressed=args.show_suppressed))
    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
