"""Incremental lint cache: content-hash keyed results under
``.tpulint_cache/``.

Two result classes are cached:

- **per-file findings**: the output of every per-file rule on one
  source file, keyed by the file's content hash *plus* the content
  hashes of every scanned file it imports (callgraph.file_deps) — an
  interprocedural finding in caller.py can appear or vanish when only
  callee.py changes, so dependents invalidate.
- **trace reports**: tracecheck results per manifest entry, keyed by
  the entry name, its contract, and the content hashes of the entry's
  declared source deps. Tracing is the expensive part of a lint run
  (~5s for the fused train program); a warm cache keeps the
  full-package lint inside the tier-1 wall budget.

Every key also folds in a *rules signature* — the content hash of
every module in ``lightgbm_tpu/analysis/`` — plus the jax version, so
editing any rule or bumping jax invalidates everything at once.

The cache only activates for real package scans (the Analyzer enables
it when ``config.py`` is in the scan set) and lives at the repo root;
fixture scans under tests/ never sprinkle cache directories around.
Writes are atomic (temp file + ``os.replace``) so concurrent lint
runs at worst redo work. ``--no-cache`` (or ``Analyzer(cache=False)``)
bypasses it entirely — CI uses that to keep the gate hermetic.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["CACHE_DIR_NAME", "LintCache", "rules_signature"]

CACHE_DIR_NAME = ".tpulint_cache"
_FORMAT_VERSION = "1"


def _sha(*parts: str) -> str:
    h = hashlib.sha256()
    for part in parts:
        h.update(part.encode("utf-8", "replace"))
        h.update(b"\x00")
    return h.hexdigest()


def rules_signature() -> str:
    """Content hash of the analysis package itself (rule edits
    invalidate every cached result) plus the jax version."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    parts: List[str] = [_FORMAT_VERSION]
    try:
        names = sorted(n for n in os.listdir(pkg) if n.endswith(".py"))
    except OSError:
        names = []
    for name in names:
        try:
            with open(os.path.join(pkg, name), "rb") as fh:
                parts.append(hashlib.sha256(fh.read()).hexdigest())
        except OSError:
            parts.append(f"unreadable:{name}")
    try:
        import jax
        parts.append(f"jax:{jax.__version__}")
    except Exception:
        parts.append("jax:none")
    return _sha(*parts)


class LintCache:
    """Content-addressed result store rooted at ``<repo>/.tpulint_cache``."""

    def __init__(self, repo_root: str):
        self.root = os.path.join(repo_root, CACHE_DIR_NAME)
        self.repo_root = repo_root
        self.rules_sig = rules_signature()
        self.hits = 0
        self.misses = 0
        self._content_hashes: Dict[str, str] = {}

    # ------------------------------------------------------------------
    def content_hash(self, path: str) -> str:
        path = os.path.abspath(path)
        cached = self._content_hashes.get(path)
        if cached is not None:
            return cached
        try:
            with open(path, "rb") as fh:
                digest = hashlib.sha256(fh.read()).hexdigest()
        except OSError:
            digest = "unreadable"
        self._content_hashes[path] = digest
        return digest

    def _rel(self, path: str) -> str:
        try:
            return os.path.relpath(os.path.abspath(path), self.repo_root)
        except ValueError:
            return path

    def _dep_fingerprint(self, deps: Sequence[str]) -> str:
        pairs = sorted((self._rel(d), self.content_hash(d))
                       for d in deps)
        return _sha(*[f"{r}={h}" for r, h in pairs])

    # -- keys ----------------------------------------------------------
    def file_key(self, path: str, deps: Sequence[str],
                 interproc: bool) -> str:
        return _sha("file", self.rules_sig, self._rel(path),
                    self.content_hash(path), str(bool(interproc)),
                    self._dep_fingerprint(deps))

    def trace_key(self, entry_name: str, deps: Sequence[str],
                  contract: str) -> str:
        abs_deps = [os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), d) for d in deps]
        return _sha("trace", self.rules_sig, entry_name, contract,
                    self._dep_fingerprint(abs_deps))

    # -- storage -------------------------------------------------------
    def _path_for(self, key: str) -> str:
        return os.path.join(self.root, key[:2], key + ".json")

    def _get(self, key: str):
        try:
            with open(self._path_for(key), "r") as fh:
                payload = json.load(fh)
        except (OSError, ValueError):
            self.misses += 1
            return None
        self.hits += 1
        return payload

    def _put(self, key: str, payload) -> None:
        path = self._path_for(key)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                       suffix=".tmp")
            with os.fdopen(fd, "w") as fh:
                json.dump(payload, fh)
            os.replace(tmp, path)
        except OSError:
            pass                         # cache is best-effort

    # -- typed views ---------------------------------------------------
    def get_file_findings(self, key: str) -> Optional[List[Dict]]:
        payload = self._get(key)
        if isinstance(payload, dict) and \
                isinstance(payload.get("findings"), list):
            return payload["findings"]
        return None

    def put_file_findings(self, key: str,
                          findings: List[Dict]) -> None:
        self._put(key, {"findings": findings})

    def get_trace_report(self, key: str) -> Optional[Dict]:
        payload = self._get(key)
        if isinstance(payload, dict) and "name" in payload:
            return payload
        return None

    def put_trace_report(self, key: str, report: Dict) -> None:
        self._put(key, report)
