"""Interprocedural facts: project call graph + cross-function summaries.

The per-file rules are deliberately intraprocedural — fast, local,
predictable. But the failure modes the ROADMAP calls out (hidden host
syncs, stranded collectives) do not respect function boundaries: the
`float()` that serializes a jitted body usually lives in a helper two
modules away. This module builds the minimum interprocedural machinery
the upgraded rules need, as *facts* handed to the existing rules (the
rules keep their ids and their intraprocedural behavior; facts only add
findings):

- a project-wide call graph over the already-parsed files, with
  file-path-based import resolution (``from .m import f``,
  ``import pkg.mod as m`` + ``m.f()``, bare local calls, and
  ``self.method()`` within a class);
- **host-sync summaries** (for JIT003): per function, which *parameters*
  flow into a host-syncing call (``float()``, ``.item()``, ``np.*`` —
  the same label set as the lexical rule), propagated bottom-up through
  the call graph with a bounded, cycle-safe fixpoint. A jitted body
  passing a traced value into such a parameter is a host sync the
  lexical rule provably cannot see.
- **collective reachability** (for COLL001/002/003): the set of local
  call spellings in each module that transitively perform a collective
  (`dataflow.COLLECTIVE_CALLABLES`), so the taint/CFG rules treat
  ``sync_error_count(x)`` exactly like the ``psum`` hiding inside it.
- **``_locked`` delegation resolution** (for LOCK001): calls to
  ``*_locked``-suffixed functions resolved across modules, so the
  caller-holds-the-lock naming contract is checked at every delegation
  edge, not just inside one class body.

Approximations, documented so rule behavior stays predictable: calls
through arbitrary objects (``obj.m()`` where ``obj`` is not ``self``,
``cls`` or an imported module alias) are unresolved; provenance through
container stores is not tracked; the fixpoint is bounded at
``MAX_DEPTH`` propagation rounds, which caps summary chains without
risking non-termination on call cycles.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .dataflow import COLLECTIVE_CALLABLES, call_name, dotted_name
from .engine import ParsedFile

__all__ = ["FunctionInfo", "InterprocFacts", "MAX_DEPTH"]

#: bounded propagation depth for the bottom-up summary fixpoint — deep
#: enough for any sane helper chain, finite on call cycles
MAX_DEPTH = 6

#: host-sync labels (mirrors rules_jit: builtins that concretize, sync
#: methods, numpy namespace calls)
_HOST_SYNC_FUNCS = ("float", "int", "bool", "complex")
_HOST_SYNC_METHODS = ("item", "tolist", "to_py")
_HOST_MODULES = ("np", "numpy")

#: attribute reads that are static at trace time (mirrors rules_jit)
_STATIC_ATTRS = ("shape", "ndim", "dtype", "size")


def _tainted_sources(e: ast.AST, taint: Dict[str, Set[str]]) -> Set[str]:
    """Union of param sets mentioned by `e`, skipping trace-static
    reads: `x.shape[...]` and `is None` tests never carry a traced
    value into a host sync (same exemptions as the lexical JIT003)."""
    out: Set[str] = set()

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Compare) and node.ops and \
                all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops):
            return
        if isinstance(node, ast.Attribute) and \
                node.attr in _STATIC_ATTRS:
            return
        if isinstance(node, ast.Name):
            out.update(taint.get(node.id, ()))
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(e)
    return out


def _host_call_label(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in _HOST_SYNC_FUNCS:
        return f"{fn.id}()"
    if isinstance(fn, ast.Attribute):
        if fn.attr in _HOST_SYNC_METHODS:
            return f".{fn.attr}()"
        base = dotted_name(fn.value)
        if base in _HOST_MODULES:
            return f"{base}.{fn.attr}()"
    return None


class FunctionInfo:
    """One function or method in the scanned set."""

    __slots__ = ("path", "qualname", "name", "node", "class_name",
                 "params", "host_sync_params", "reaches_collective")

    def __init__(self, path: str, qualname: str, node: ast.FunctionDef,
                 class_name: Optional[str]):
        self.path = path
        self.qualname = qualname
        self.name = node.name
        self.node = node
        self.class_name = class_name
        self.params = [a.arg for a in
                       list(node.args.posonlyargs) + list(node.args.args)
                       + list(node.args.kwonlyargs)]
        #: param name -> (label, path, line) of the host sync it feeds
        self.host_sync_params: Dict[str, Tuple[str, str, int]] = {}
        self.reaches_collective = False

    @property
    def key(self) -> Tuple[str, str]:
        return (self.path, self.qualname)


def _module_file_of(path: str, dots: int, mod_parts: List[str],
                    known: Set[str]) -> Optional[str]:
    """Resolve an import to a scanned file path.

    `dots` is the relative-import level (0 = absolute). Absolute
    imports are matched by path suffix against the scanned set (the
    analyzer has no sys.path; a trailing-components match is exact
    enough inside one repository)."""
    if dots:
        base = os.path.dirname(os.path.abspath(path))
        for _ in range(dots - 1):
            base = os.path.dirname(base)
        cand = os.path.join(base, *mod_parts) + ".py" if mod_parts \
            else None
        if cand is not None and cand in known:
            return cand
        if mod_parts:
            pkg = os.path.join(base, *mod_parts, "__init__.py")
            if pkg in known:
                return pkg
        return None
    if not mod_parts:
        return None
    suffix = os.sep.join(mod_parts) + ".py"
    pkg_suffix = os.sep.join(mod_parts + ["__init__.py"])
    for cand in known:
        if cand.endswith(os.sep + suffix) or cand == suffix or \
                cand.endswith(os.sep + pkg_suffix):
            return cand
    return None


class InterprocFacts:
    """Call graph + summaries over one analyzer run's parsed files."""

    def __init__(self, files: Sequence[ParsedFile]):
        self.files = [f for f in files if f.tree is not None]
        self._paths: Set[str] = {os.path.abspath(f.path)
                                 for f in self.files}
        #: (path, qualname) -> FunctionInfo
        self.functions: Dict[Tuple[str, str], FunctionInfo] = {}
        #: path -> {local top-level function name -> qualname}
        self._top: Dict[str, Dict[str, str]] = {}
        #: path -> {class name -> {method name -> qualname}}
        self._methods: Dict[str, Dict[str, Dict[str, str]]] = {}
        #: path -> {alias -> ("func", target_path, name) |
        #:          ("module", target_path)}
        self._imports: Dict[str, Dict[str, Tuple]] = {}
        for parsed in self.files:
            self._index_file(parsed)
        # the summary fixpoint is the expensive part and is only
        # consulted by rules on a cache miss — computed on first use so
        # a fully-cached scan pays for indexing (file_deps) alone
        self._summaries_done = False

    def _ensure_summaries(self) -> None:
        if not self._summaries_done:
            self._summaries_done = True
            self._resolve_summaries()

    # -- indexing -------------------------------------------------------
    def _index_file(self, parsed: ParsedFile) -> None:
        path = os.path.abspath(parsed.path)
        top: Dict[str, str] = {}
        methods: Dict[str, Dict[str, str]] = {}
        for node in parsed.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = FunctionInfo(path, node.name, node, None)
                self.functions[info.key] = info
                top[node.name] = node.name
            elif isinstance(node, ast.ClassDef):
                meths: Dict[str, str] = {}
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        qn = f"{node.name}.{sub.name}"
                        info = FunctionInfo(path, qn, sub, node.name)
                        self.functions[info.key] = info
                        meths[sub.name] = qn
                methods[node.name] = meths
        self._top[path] = top
        self._methods[path] = methods
        imports: Dict[str, Tuple] = {}
        for node in ast.walk(parsed.tree):
            if isinstance(node, ast.ImportFrom):
                tgt = _module_file_of(path, node.level,
                                      (node.module or "").split(".")
                                      if node.module else [],
                                      self._paths)
                if tgt is None:
                    continue
                for alias in node.names:
                    local = alias.asname or alias.name
                    imports[local] = ("func", tgt, alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    tgt = _module_file_of(path, 0, alias.name.split("."),
                                          self._paths)
                    if tgt is None:
                        continue
                    local = alias.asname or alias.name.split(".")[-1]
                    imports[local] = ("module", tgt)
        self._imports[path] = imports

    # -- call resolution ------------------------------------------------
    def resolve_call(self, path: str, call: ast.Call,
                     class_name: Optional[str] = None
                     ) -> Optional[FunctionInfo]:
        """The FunctionInfo a call resolves to, or None (opaque)."""
        path = os.path.abspath(path)
        name = dotted_name(call.func)
        if not name:
            return None
        parts = name.split(".")
        if len(parts) == 1:
            return self._resolve_name(path, parts[0])
        if parts[0] in ("self", "cls") and len(parts) == 2 and \
                class_name is not None:
            qn = self._methods.get(path, {}).get(class_name, {}) \
                .get(parts[1])
            if qn is not None:
                return self.functions.get((path, qn))
            return None
        # module-alias form: m.f() / pkg.mod.f() via `import ... as m`
        entry = self._imports.get(path, {}).get(parts[0])
        if entry is not None and entry[0] == "module" and len(parts) == 2:
            tgt = entry[1]
            qn = self._top.get(tgt, {}).get(parts[1])
            if qn is not None:
                return self.functions.get((tgt, qn))
        return None

    def _resolve_name(self, path: str,
                      name: str) -> Optional[FunctionInfo]:
        qn = self._top.get(path, {}).get(name)
        if qn is not None:
            return self.functions.get((path, qn))
        entry = self._imports.get(path, {}).get(name)
        if entry is not None and entry[0] == "func":
            _, tgt, fname = entry
            tqn = self._top.get(tgt, {}).get(fname)
            if tqn is not None:
                return self.functions.get((tgt, tqn))
        return None

    # -- summaries ------------------------------------------------------
    def _direct_host_syncs(self, info: FunctionInfo
                           ) -> Dict[str, Tuple[str, str, int]]:
        """Params of `info` that flow into a direct host-sync call.

        Flow-insensitive name taint: a param name, or a local assigned
        from an expression mentioning a tainted name, carries the
        originating param set."""
        taint: Dict[str, Set[str]] = {p: {p} for p in info.params
                                      if p != "self"}

        def expr_sources(e: ast.AST) -> Set[str]:
            return _tainted_sources(e, taint)

        for _ in range(4):
            changed = False
            for node in ast.walk(info.node):
                if isinstance(node, ast.Assign):
                    src = expr_sources(node.value)
                    if not src:
                        continue
                    for tgt in node.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                cur = taint.setdefault(n.id, set())
                                if not src <= cur:
                                    cur |= src
                                    changed = True
            if not changed:
                break
        out: Dict[str, Tuple[str, str, int]] = {}
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            label = _host_call_label(node)
            if label is None:
                continue
            exprs = list(node.args) + [kw.value for kw in node.keywords]
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _HOST_SYNC_METHODS:
                exprs.append(node.func.value)
            for e in exprs:
                for p in expr_sources(e):
                    out.setdefault(p, (label, info.path, node.lineno))
        return out

    def _call_param_map(self, caller: FunctionInfo, call: ast.Call,
                        callee: FunctionInfo
                        ) -> List[Tuple[str, ast.expr]]:
        """(callee param name, argument expression) pairs for a call."""
        params = [p for p in callee.params if p != "self"]
        out: List[Tuple[str, ast.expr]] = []
        for idx, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            if idx < len(params):
                out.append((params[idx], arg))
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in params:
                out.append((kw.arg, kw.value))
        return out

    def _resolve_summaries(self) -> None:
        # seed: direct host syncs and direct collective calls
        direct_sync: Dict[Tuple[str, str],
                          Dict[str, Tuple[str, str, int]]] = {}
        for key, info in self.functions.items():
            direct_sync[key] = self._direct_host_syncs(info)
            info.host_sync_params = dict(direct_sync[key])
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call) and \
                        call_name(node) in COLLECTIVE_CALLABLES:
                    info.reaches_collective = True
                    break
        # bounded bottom-up propagation: caller param -> callee syncing
        # param, and collective reachability through resolved edges.
        # Monotone, so MAX_DEPTH rounds is both the cycle guard and the
        # summary-depth bound.
        for _ in range(MAX_DEPTH):
            changed = False
            for key, info in self.functions.items():
                caller_taint = {p: {p} for p in info.params
                                if p != "self"}
                for node in ast.walk(info.node):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = self.resolve_call(info.path, node,
                                               info.class_name)
                    if callee is None or callee is info:
                        continue
                    if callee.reaches_collective and \
                            not info.reaches_collective:
                        info.reaches_collective = True
                        changed = True
                    if not callee.host_sync_params:
                        continue
                    for pname, arg in self._call_param_map(
                            info, node, callee):
                        hit = callee.host_sync_params.get(pname)
                        if hit is None:
                            continue
                        label, spath, sline = hit
                        for src in _tainted_sources(arg, caller_taint):
                            if src not in info.host_sync_params:
                                info.host_sync_params[src] = (
                                    label, spath, node.lineno)
                                changed = True
            if not changed:
                break

    # -- rule-facing queries --------------------------------------------
    def collective_call_names(self, path: str) -> FrozenSet[str]:
        """Call-site spellings (last dotted segment) in `path` that
        resolve to a function which transitively performs a collective.
        Fed to the SPMD rules as extra collective callables."""
        self._ensure_summaries()
        path = os.path.abspath(path)
        out: Set[str] = set()
        for alias, entry in self._imports.get(path, {}).items():
            if entry[0] == "func":
                _, tgt, fname = entry
                qn = self._top.get(tgt, {}).get(fname)
                if qn is not None:
                    info = self.functions.get((tgt, qn))
                    if info is not None and info.reaches_collective:
                        out.add(alias)
            elif entry[0] == "module":
                for fname, qn in self._top.get(entry[1], {}).items():
                    info = self.functions.get((entry[1], qn))
                    if info is not None and info.reaches_collective:
                        out.add(fname)
        for fname, qn in self._top.get(path, {}).items():
            info = self.functions.get((path, qn))
            if info is not None and info.reaches_collective and \
                    info.name not in COLLECTIVE_CALLABLES:
                out.add(fname)
        return frozenset(out)

    def host_sync_callees(self, path: str, root: ast.AST,
                          class_name: Optional[str] = None
                          ) -> List[Tuple[ast.Call, FunctionInfo,
                                          List[Tuple[str, ast.expr]]]]:
        """Calls under `root` whose resolved callee host-syncs one of
        its parameters: (call, callee, [(syncing param, arg expr)])."""
        self._ensure_summaries()
        out = []
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            callee = self.resolve_call(path, node, class_name)
            if callee is None or not callee.host_sync_params:
                continue
            hits = [(p, arg) for p, arg in
                    self._call_param_map(None, node, callee)
                    if p in callee.host_sync_params]
            if hits:
                out.append((node, callee, hits))
        return out

    def locked_delegate_calls(self, path: str, root: ast.AST,
                              class_name: Optional[str] = None
                              ) -> List[Tuple[ast.Call, FunctionInfo]]:
        """Calls under `root` that resolve to a ``*_locked``-suffixed
        function (the caller-holds-the-lock delegation contract)."""
        self._ensure_summaries()
        out = []
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            if not call_name(node).endswith("_locked"):
                continue
            callee = self.resolve_call(path, node, class_name)
            if callee is not None and callee.name.endswith("_locked"):
                out.append((node, callee))
        return out

    def file_deps(self, path: str) -> List[str]:
        """Scanned files this module's findings may depend on (its
        resolved imports) — the cache invalidation set."""
        path = os.path.abspath(path)
        deps: Set[str] = set()
        for entry in self._imports.get(path, {}).values():
            deps.add(entry[1])
        deps.discard(path)
        return sorted(deps)
