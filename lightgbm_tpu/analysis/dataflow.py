"""Intraprocedural CFG + rank-taint dataflow shared by the rule modules.

The SPMD rules (rules_spmd.py) need more than per-node pattern checks:
whether a `raise` strands peers in a collective is a *reachability*
question, and whether a branch is rank-divergent is a *dataflow*
question. This module provides both as small, dependency-free pieces:

- `CFG`: statement-level control-flow graph over one function body
  (if/for/while/try/with, raise/return/break/continue edges), with a
  `reachable()` query used for "is a collective downstream of this
  statement, avoiding that raise?".
- `RankTaint`: flow-insensitive fixpoint taint over the function's
  namespace. Two lattices:
    * value taint — "this value can differ across ranks". Seeded by
      rank-identity calls (`process_index`, `axis_index`, `host_id`)
      everywhere, and by per-rank data extents (`len(...)`,
      `.shape`/`.size` reads) in *host* code only: inside device
      directories shapes are trace-static and shard-uniform, so a
      `.shape` read there is not a divergence source.
    * shape taint — "this array's shape can differ across ranks":
      seeded by slices with rank-tainted bounds (`x[:n]`) and by
      size-taking constructors (`rng.choice(n, size=k)`), cleared by
      pad-to-static sanitizers (`np.pad`, `np.zeros`, ...). Shape
      taint joins *clean-wins* across a name's assignments so the
      standard conditional-pad idiom (`if n < per: x = np.pad(...)`)
      reads as fixed-wire-shape.
  Collective call results are rank-UNIFORM by construction (every rank
  sees the same gathered value), so collectives *launder* taint: a
  branch on an allgathered error flag is an agreement sync, not a
  divergence — which is exactly the fix COLL002 asks for.

Also hosts the structural helpers (`dotted_name`, `stmt_exprs`,
`child_blocks`, `branch_tests`) the older rule modules grew private
copies of; they now import from here.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

__all__ = [
    "RANK_SOURCES", "COLLECTIVE_CALLABLES", "SHAPE_SANITIZERS",
    "dotted_name", "call_name", "stmt_exprs", "child_blocks",
    "branch_tests", "iter_top_functions", "collective_calls",
    "CFGNode", "CFG", "RankTaint",
]

#: calls whose result is this rank's identity — the root divergence seed
RANK_SOURCES = frozenset({"process_index", "axis_index", "host_id"})

#: collective entry points: every rank must reach these together, and
#: their results are rank-uniform (taint-laundering). Includes the
#: package's own named collective wrappers (basic._allgather_find_mappers,
#: the loader's mapper_sync hook and the watchdog-bracketed
#: parallel.comm.guarded_allgather) so rules see them as collectives.
COLLECTIVE_CALLABLES = frozenset({
    "psum", "psum_scatter", "pmean", "pmax", "pmin", "all_gather",
    "all_to_all", "ppermute", "process_allgather",
    "broadcast_one_to_all", "sync_global_devices",
    "_allgather_find_mappers", "mapper_sync", "guarded_allgather",
})

#: constructors that produce a statically-shaped array regardless of
#: input shape — padding to the fixed wire shape clears shape taint
SHAPE_SANITIZERS = frozenset({
    "pad", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "broadcast_to",
})

#: calls whose *result shape* follows a value argument (rng.choice(n),
#: np.arange(n), ...): value-tainted size -> shape-tainted result
_SIZE_CALLS = frozenset({
    "choice", "permutation", "randint", "arange", "repeat", "tile",
    "linspace",
})

#: calls that always return a scalar — never shape-tainted
_SCALAR_CALLS = frozenset({
    "int", "float", "bool", "len", "min", "max", "sum", "round", "abs",
})

_SHAPE_ATTRS = ("shape", "size", "nbytes")


# ---------------------------------------------------------------------------
# structural helpers (shared with rules_jit / rules_lock)

def dotted_name(node: ast.expr) -> str:
    """``a.b.c`` for Name/Attribute chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(call: ast.Call) -> str:
    """Last dotted segment of a call's callee ('' if not a name chain)."""
    name = dotted_name(call.func)
    if name:
        return name.split(".")[-1]
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return ""


#: expression-valued statement fields (a statement's OWN expressions,
#: excluding its nested blocks)
_STMT_EXPR_FIELDS = ("test", "iter", "value", "exc", "cause", "msg",
                     "target", "targets", "annotation")


def stmt_exprs(stmt: ast.stmt) -> List[ast.expr]:
    """The expressions belonging to `stmt` itself — not to statements
    nested inside its blocks. (`with` items and `return x` values are
    included; an `if` contributes only its test.)"""
    out: List[ast.expr] = []
    for field in _STMT_EXPR_FIELDS:
        val = getattr(stmt, field, None)
        if val is None:
            continue
        if isinstance(val, ast.expr):
            out.append(val)
        elif isinstance(val, list):
            out.extend(v for v in val if isinstance(v, ast.expr))
    for item in getattr(stmt, "items", ()) or ():    # with-statements
        out.append(item.context_expr)
        if item.optional_vars is not None:
            out.append(item.optional_vars)
    return out


def child_blocks(stmt: ast.stmt) -> List[List[ast.stmt]]:
    """Every statement block nested directly under `stmt`."""
    blocks: List[List[ast.stmt]] = []
    for field in ("body", "orelse", "finalbody"):
        val = getattr(stmt, field, None)
        if isinstance(val, list) and val and isinstance(val[0], ast.stmt):
            blocks.append(val)
    for handler in getattr(stmt, "handlers", ()) or ():
        blocks.append(handler.body)
    for case in getattr(stmt, "cases", ()) or ():    # match-statements
        blocks.append(case.body)
    return blocks


def branch_tests(root: ast.AST, include_range_for: bool = True
                 ) -> Iterator[Tuple[ast.AST, List[ast.expr]]]:
    """Yield (node, [condition exprs]) for every Python control-flow
    construct under `root`: if/while/ifexp/assert tests, and the args
    of `for _ in range(...)` loops."""
    for node in ast.walk(root):
        if isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
            yield node, [node.test]
        elif include_range_for and isinstance(node, ast.For) and \
                isinstance(node.iter, ast.Call) and \
                isinstance(node.iter.func, ast.Name) and \
                node.iter.func.id == "range":
            yield node, list(node.iter.args)


def iter_top_functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    """Module-level functions and first-level methods — the analysis
    units for the SPMD rules (nested defs/lambdas are analyzed as part
    of their enclosing top function: closures share the namespace)."""
    for node in getattr(tree, "body", ()):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield sub


def collective_calls(root: ast.AST,
                     extra: frozenset = frozenset()) -> List[ast.Call]:
    """Every call under `root` whose callee name is a collective.
    `extra` adds interprocedurally-resolved names (local spellings that
    transitively perform a collective, callgraph.collective_call_names)."""
    names = COLLECTIVE_CALLABLES | extra if extra else COLLECTIVE_CALLABLES
    return [node for node in ast.walk(root)
            if isinstance(node, ast.Call) and call_name(node) in names]


# ---------------------------------------------------------------------------
# CFG

class CFGNode:
    """One statement in the graph. `kind` tags exits: raise/return."""
    __slots__ = ("stmt", "succs", "kind")

    def __init__(self, stmt: Optional[ast.stmt], kind: str = "stmt"):
        self.stmt = stmt
        self.succs: List["CFGNode"] = []
        self.kind = kind

    def __repr__(self) -> str:        # pragma: no cover - debugging aid
        line = getattr(self.stmt, "lineno", "?")
        return f"<CFGNode {self.kind} line {line}>"


class CFG:
    """Statement-level control-flow graph of one function body.

    Approximations (documented so rule behavior is predictable):
    exceptions raised by any top-level statement of a `try` body may
    reach every handler; loops may execute zero times; `match` takes
    any case or falls through. Nested function/class definitions are
    single opaque nodes (their bodies do not execute here)."""

    def __init__(self, fn: ast.FunctionDef):
        self.exit = CFGNode(None, kind="exit")
        self.nodes: List[CFGNode] = []
        self._of: Dict[int, CFGNode] = {}
        self.entry = self._seq(fn.body, self.exit, None)

    def node(self, stmt: ast.stmt) -> Optional[CFGNode]:
        return self._of.get(id(stmt))

    def reachable(self, start: CFGNode,
                  avoid: Optional[CFGNode] = None) -> Set[CFGNode]:
        """Nodes reachable from `start` (inclusive) without passing
        through `avoid`."""
        seen: Set[CFGNode] = set()
        work = [start]
        while work:
            nd = work.pop()
            if nd in seen or nd is avoid:
                continue
            seen.add(nd)
            work.extend(nd.succs)
        return seen

    # ------------------------------------------------------------------
    def _make(self, stmt: ast.stmt) -> CFGNode:
        n = CFGNode(stmt)
        self.nodes.append(n)
        self._of[id(stmt)] = n
        return n

    def _seq(self, stmts: Sequence[ast.stmt], follow: CFGNode,
             loop: Optional[Tuple[CFGNode, CFGNode]]) -> CFGNode:
        entry = follow
        for stmt in reversed(stmts):
            entry = self._stmt(stmt, entry, loop)
        return entry

    def _stmt(self, stmt: ast.stmt, follow: CFGNode,
              loop: Optional[Tuple[CFGNode, CFGNode]]) -> CFGNode:
        n = self._make(stmt)
        if isinstance(stmt, ast.Return):
            n.kind = "return"
            n.succs = [self.exit]
        elif isinstance(stmt, ast.Raise):
            n.kind = "raise"
            n.succs = [self.exit]
        elif isinstance(stmt, ast.Assert):
            n.kind = "assert"
            n.succs = [follow, self.exit]
        elif isinstance(stmt, ast.If):
            n.succs = [self._seq(stmt.body, follow, loop),
                       self._seq(stmt.orelse, follow, loop)]
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            body = self._seq(stmt.body, n, (n, follow))
            after = self._seq(stmt.orelse, follow, loop)
            n.succs = [body, after]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            n.succs = [self._seq(stmt.body, follow, loop)]
        elif isinstance(stmt, ast.Try) or \
                isinstance(stmt, getattr(ast, "TryStar", ())):
            final_entry = (self._seq(stmt.finalbody, follow, loop)
                           if stmt.finalbody else follow)
            handlers = [self._seq(h.body, final_entry, loop)
                        for h in stmt.handlers]
            after_body = (self._seq(stmt.orelse, final_entry, loop)
                          if stmt.orelse else final_entry)
            body = self._seq(stmt.body, after_body, loop)
            n.succs = [body]
            # any top-level body statement may raise into any handler
            for s in stmt.body:
                bn = self._of.get(id(s))
                if bn is not None:
                    bn.succs = list(bn.succs) + handlers
        elif isinstance(stmt, ast.Break):
            n.succs = [loop[1] if loop else self.exit]
        elif isinstance(stmt, ast.Continue):
            n.succs = [loop[0] if loop else self.exit]
        elif isinstance(stmt, getattr(ast, "Match", ())):
            cases = [self._seq(c.body, follow, loop)
                     for c in stmt.cases]
            n.succs = cases + [follow]
        else:
            # simple statements, plus opaque nested defs/classes
            n.succs = [follow]
        return n


# ---------------------------------------------------------------------------
# taint

class RankTaint:
    """Flow-insensitive rank-divergence taint over one top function.

    `shape_seeds=False` (device code) disables the `.shape`/`len()`
    value seeds; rank-identity calls still seed everywhere."""

    def __init__(self, fn: ast.FunctionDef, shape_seeds: bool = True,
                 extra_collectives: frozenset = frozenset()):
        self.fn = fn
        self.shape_seeds = shape_seeds
        self.collectives = COLLECTIVE_CALLABLES | extra_collectives
        self.value: Set[str] = set()
        self.shape: Set[str] = set()
        # name -> list of ("expr"|"iter", rhs expression) descriptors
        self._assigns: Dict[str, List[Tuple[str, ast.expr]]] = {}
        # (base name, rhs) for container stores x[i] = rhs / x.a = rhs
        self._stores: List[Tuple[str, ast.expr]] = []
        # names bound inside a for/while body: whether such a name was
        # bound at all can depend on rank-local iteration counts, so
        # `x is None` on them IS divergent (see _taints on Compare)
        self.loop_bound: Set[str] = set()
        self._collect()
        self._fix_value()
        self._fix_shape()

    # -- public queries -------------------------------------------------
    def expr_tainted(self, expr: ast.expr) -> bool:
        return self._taints(expr)[0]

    def expr_shape_tainted(self, expr: ast.expr) -> bool:
        return self._taints(expr)[1]

    def stmt_test_tainted(self, stmt: ast.stmt) -> bool:
        """Is the statement's controlling expression rank-divergent?
        (if/while test, for iterable.)"""
        test = getattr(stmt, "test", None)
        if test is not None:
            return self.expr_tainted(test)
        it = getattr(stmt, "iter", None)
        if it is not None:
            return self.expr_tainted(it)
        return False

    # -- assignment collection ------------------------------------------
    def _collect(self) -> None:
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    self._bind(tgt, node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._bind(node.target, node.value)
            elif isinstance(node, ast.AugAssign):
                self._bind(node.target, node.value)
            elif isinstance(node, ast.NamedExpr):
                self._bind(node.target, node.value)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._bind(node.target, node.iter, kind="iter")
            elif isinstance(node, ast.comprehension):
                self._bind(node.target, node.iter, kind="iter")
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        self._bind(item.optional_vars, item.context_expr)
        for node in ast.walk(self.fn):
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                for sub in ast.walk(node):
                    if sub is node:
                        continue
                    if isinstance(sub, ast.Assign):
                        for tgt in sub.targets:
                            self._bound_names(tgt)
                    elif isinstance(sub, (ast.AugAssign, ast.AnnAssign,
                                          ast.NamedExpr)):
                        self._bound_names(sub.target)

    def _bound_names(self, target: ast.expr) -> None:
        if isinstance(target, ast.Name):
            self.loop_bound.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bound_names(elt)
        elif isinstance(target, ast.Starred):
            self._bound_names(target.value)

    def _bind(self, target: ast.expr, rhs: ast.expr,
              kind: str = "expr") -> None:
        if isinstance(target, ast.Name):
            self._assigns.setdefault(target.id, []).append((kind, rhs))
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, rhs, kind="iter" if kind == "iter"
                           else "unpack")
        elif isinstance(target, ast.Starred):
            self._bind(target.value, rhs, kind)
        elif isinstance(target, (ast.Subscript, ast.Attribute)):
            base = target
            while isinstance(base, (ast.Subscript, ast.Attribute)):
                base = base.value
            # attribute stores on self/cls do NOT taint the whole
            # object: `self.label = <tainted>` says nothing about
            # `self.data`, and whole-object taint cascades through
            # every other attribute read in the method
            if isinstance(target, ast.Attribute) and \
                    isinstance(base, ast.Name) and \
                    base.id in ("self", "cls"):
                return
            if isinstance(base, ast.Name):
                self._stores.append((base.id, rhs))

    # -- value fixpoint (monotone) --------------------------------------
    def _fix_value(self) -> None:
        for _ in range(24):
            # shape can feed value (len(x) of shape-tainted x), so the
            # two lattices converge together
            shape_before = set(self.shape)
            self._fix_shape_once()
            changed = self.shape != shape_before
            for name, rhss in self._assigns.items():
                if name in self.value:
                    continue
                for _kind, rhs in rhss:
                    v, s = self._taints(rhs)
                    if v or s:
                        # iterating / unpacking a shape-tainted container
                        # yields rank-divergent element counts too
                        self.value.add(name)
                        changed = True
                        break
            for name, rhs in self._stores:
                if name not in self.value and self._taints(rhs)[0]:
                    self.value.add(name)
                    changed = True
            if not changed:
                break

    # -- shape fixpoint (clean-wins join) -------------------------------
    def _fix_shape_once(self) -> None:
        new: Set[str] = set()
        for name, rhss in self._assigns.items():
            flags = []
            for kind, rhs in rhss:
                if kind in ("iter", "unpack"):
                    # loop elements / unpacked items: scalar-ish
                    flags.append(False)
                else:
                    flags.append(self._taints(rhs)[1])
            if flags and all(flags):
                new.add(name)
        self.shape = new

    def _fix_shape(self) -> None:
        for _ in range(12):
            before = set(self.shape)
            self._fix_shape_once()
            if self.shape == before:
                break

    # -- expression transfer --------------------------------------------
    def _taints(self, e: Optional[ast.expr]) -> Tuple[bool, bool]:
        if e is None:
            return (False, False)
        if isinstance(e, ast.Name):
            return (e.id in self.value, e.id in self.shape)
        if isinstance(e, ast.Constant):
            return (False, False)
        if isinstance(e, ast.Call):
            return self._call_taints(e)
        if isinstance(e, ast.Compare) and len(e.ops) == 1 and \
                isinstance(e.ops[0], (ast.Is, ast.IsNot)):
            # `x is None` is a *structural* test: noneness is
            # rank-uniform (same code path constructed x everywhere) —
            # UNLESS x is bound inside a loop, where a rank-local
            # iteration count decides whether the binding happened at
            # all (the empty-stream `sk is None` shape)
            sides = [e.left, e.comparators[0]]
            if any(isinstance(s, ast.Constant) and s.value is None
                   for s in sides):
                other = next(s for s in sides
                             if not (isinstance(s, ast.Constant)
                                     and s.value is None))
                if isinstance(other, ast.Name):
                    return (other.id in self.loop_bound, False)
                return (False, False)
        if isinstance(e, ast.Attribute):
            bv, bs = self._taints(e.value)
            if e.attr in _SHAPE_ATTRS:
                return (self.shape_seeds or bs, False)
            return (bv, bs)
        if isinstance(e, ast.Subscript):
            bv, bs = self._taints(e.value)
            sv, sliced = self._slice_taints(e.slice)
            return (bv or sv, bs or sliced)
        if isinstance(e, ast.IfExp):
            tv, _ = self._taints(e.test)
            bv, bs = self._taints(e.body)
            ov, os_ = self._taints(e.orelse)
            return (tv or bv or ov, bs or os_)
        if isinstance(e, ast.Lambda):
            return (False, False)
        # generic: OR over child expressions (BinOp, BoolOp, Compare,
        # Tuple, comprehensions, f-strings, ...)
        v = s = False
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                cv, cs = self._taints(child)
                v, s = v or cv, s or cs
            elif isinstance(child, ast.comprehension):
                cv, cs = self._taints(child.iter)
                v, s = v or cv or cs, s
        return (v, s)

    def _slice_taints(self, sl: ast.expr) -> Tuple[bool, bool]:
        """(index value taint, result-shape taint) of a subscript slice."""
        if isinstance(sl, ast.Slice):
            bounds = [sl.lower, sl.upper, sl.step]
            tainted = any(self._taints(b)[0] for b in bounds if b)
            return (tainted, tainted)
        if isinstance(sl, ast.Tuple):
            v = s = False
            for elt in sl.elts:
                ev, es = self._slice_taints(elt)
                v, s = v or ev, s or es
            return (v, s)
        v, s = self._taints(sl)
        # a tainted-shape index array selects a divergent row count
        return (v, s)

    def _call_taints(self, call: ast.Call) -> Tuple[bool, bool]:
        fname = call_name(call)
        args: List[ast.expr] = list(call.args)
        args += [kw.value for kw in call.keywords if kw.value is not None]
        if isinstance(call.func, ast.Attribute):
            args.append(call.func.value)   # method receiver
        av = ash = False
        for a in args:
            if isinstance(a, ast.Starred):
                a = a.value
            v, s = self._taints(a)
            av, ash = av or v, ash or s
        if fname in RANK_SOURCES:
            return (True, False)
        if fname in self.collectives:
            return (False, False)          # rank-uniform result
        if fname in SHAPE_SANITIZERS:
            return (av, False)             # static shape by construction
        if fname == "len":
            return (self.shape_seeds or av or ash, False)
        if fname in _SCALAR_CALLS:
            return (av or ash, False)
        size_kw = any(
            kw.arg in ("size", "shape", "num", "n")
            and kw.value is not None and self._taints(kw.value)[0]
            for kw in call.keywords)
        if fname in _SIZE_CALLS and (av or size_kw):
            return (av, True)
        return (av, ash or size_kw)
