"""tpulint rule engine: file walker, visitor registry, findings.

The codebase's correctness rests on conventions no runtime test can
enforce cheaply: `jax.jit` recompile contracts (`static_argnames`),
`with self._lock` discipline around shared-state classes, and a
registry of ~160 config parameters mirrored in docs and the CLI. The
reference LightGBM leans on C++ sanitizers and compile-time checks for
this class of bug; a JAX port needs its own analyzer, because the
costliest failures on TPU are *silent* — unbounded recompilation and
host syncs in the hot path (PAPERS.md: arxiv 1706.08359 on dispatch
overhead dominating small-batch training, arxiv 2011.02022 on keeping
the per-tree inner loop device-resident).

Architecture:

- `ParsedFile`: one source file — path, source, `ast` tree, per-line
  suppression sets parsed from ``tpulint: disable=<RULE>[,<RULE>...]``
  comments (``disable=all`` silences every rule on that line;
  ``disable-file=`` applies to the whole file). SUP001 flags
  suppressions that name unknown rules or suppress nothing.
- `Rule`: per-file analysis (`check(parsed) -> findings`).
- `ProjectRule`: whole-project analysis (`check_project(files, ctx)`)
  for cross-file invariants — registry consistency, lock-order graphs.
- `Analyzer`: walks the target paths, parses once, runs every rule,
  marks suppressed findings, renders text/JSON.

Exit contract (enforced by tests/test_static_analysis.py as a tier-1
test): `python -m lightgbm_tpu.analysis lightgbm_tpu/` exits 0 iff the
package has zero unsuppressed findings.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = [
    "Finding", "ParsedFile", "Rule", "ProjectRule",
    "StaleSuppressionRule", "Analyzer", "all_rules", "DEVICE_DIRS",
]

#: package subdirectories whose code runs (or stages) device compute;
#: the jit-hygiene and dtype rules only apply here.
DEVICE_DIRS = ("learner", "serving", "parallel", "boosting")

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


@dataclasses.dataclass
class Finding:
    """One analyzer hit, pinned to file:line."""
    rule: str
    severity: str          # "error" | "warning"
    path: str
    line: int
    message: str
    suppressed: bool = False

    def render(self) -> str:
        sup = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}: {self.severity} "
                f"[{self.rule}]{sup} {self.message}")

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


class ParsedFile:
    """One parsed source file plus its suppression comments."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            self.parse_error = str(exc)
        # line number -> set of rule ids disabled on that line
        self.line_suppressions: Dict[int, set] = {}
        self.file_suppressions: set = set()
        # (comment line, "line"|"file", rule id) — kept per-comment so
        # the stale-suppression self-check (SUP001) can point at the
        # exact comment that suppresses nothing
        self.suppression_comments: List[tuple] = []
        for lineno, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
            if m.group(1) == "disable-file":
                self.file_suppressions |= rules
                self.suppression_comments += [
                    (lineno, "file", r) for r in sorted(rules)]
            else:
                self.line_suppressions.setdefault(lineno, set()).update(
                    rules)
                self.suppression_comments += [
                    (lineno, "line", r) for r in sorted(rules)]

    # ------------------------------------------------------------------
    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions or \
                "all" in self.file_suppressions:
            return True
        on_line = self.line_suppressions.get(line, ())
        return rule in on_line or "all" in on_line

    def rel_path(self, root: str) -> str:
        try:
            return os.path.relpath(self.path, root)
        except ValueError:          # different drive (windows)
            return self.path

    def in_device_dir(self) -> bool:
        parts = os.path.normpath(self.path).split(os.sep)
        return any(d in parts for d in DEVICE_DIRS)


class Rule:
    """Per-file rule. Subclasses set `id`/`severity`/`doc` and
    implement `check`."""

    id: str = "RULE000"
    severity: str = "error"
    doc: str = ""

    def check(self, parsed: ParsedFile) -> List[Finding]:
        raise NotImplementedError

    def finding(self, parsed: ParsedFile, line: int,
                message: str) -> Finding:
        return Finding(rule=self.id, severity=self.severity,
                       path=parsed.path, line=line, message=message)


class ProjectRule(Rule):
    """Whole-project rule: sees every parsed file plus the repo layout
    (docs/, tests/) resolved from the package location."""

    def check(self, parsed: ParsedFile) -> List[Finding]:
        return []

    def check_project(self, files: Sequence[ParsedFile],
                      ctx: "ProjectContext") -> List[Finding]:
        raise NotImplementedError


class StaleSuppressionRule(Rule):
    """SUP001 is driven by the Analyzer itself (it needs the final
    finding set to know whether a suppression still suppresses
    anything); the class exists so the rule appears in the catalogue
    and can itself be suppressed/filtered like any other."""

    id = "SUP001"
    doc = ("`# tpulint: disable` comment that names an unknown rule id "
           "or no longer suppresses any finding — dead suppressions "
           "rot silently; delete the comment or fix the rule id")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        return []

    def check_run(self, files: Sequence[ParsedFile],
                  findings: Sequence[Finding],
                  known_ids: Iterable[str]) -> List[Finding]:
        known = set(known_ids) | {"all", "PARSE001"}
        out: List[Finding] = []
        for parsed in files:
            for lineno, kind, rule_id in parsed.suppression_comments:
                if rule_id not in known:
                    out.append(self.finding(
                        parsed, lineno,
                        f"suppression names unknown rule '{rule_id}'"))
                    continue
                if kind == "file":
                    live = any(f.path == parsed.path
                               and (rule_id == "all" or f.rule == rule_id)
                               for f in findings)
                else:
                    live = any(f.path == parsed.path and f.line == lineno
                               and (rule_id == "all" or f.rule == rule_id)
                               for f in findings)
                if not live:
                    out.append(self.finding(
                        parsed, lineno,
                        f"stale suppression: 'disable{'-file' if kind == 'file' else ''}"
                        f"={rule_id}' no longer suppresses any finding"))
        return out


class ProjectContext:
    """Repo layout for cross-file rules: where the package, docs and
    tests live. Resolved from the scanned package directory (the one
    holding config.py), falling back to the installed package."""

    def __init__(self, files: Sequence[ParsedFile]):
        pkg_dir = None
        for f in files:
            if os.path.basename(f.path) == "config.py":
                pkg_dir = os.path.dirname(os.path.abspath(f.path))
                break
        if pkg_dir is None and files:
            pkg_dir = os.path.dirname(os.path.abspath(files[0].path))
        if pkg_dir is None:
            pkg_dir = os.path.dirname(os.path.abspath(__file__))
            pkg_dir = os.path.dirname(pkg_dir)
        self.package_dir = pkg_dir
        self.repo_root = os.path.dirname(pkg_dir)
        self.docs_dir = os.path.join(self.repo_root, "docs")
        self.tests_dir = os.path.join(self.repo_root, "tests")

    def read_doc(self, name: str) -> Optional[str]:
        path = os.path.join(self.docs_dir, name)
        try:
            with open(path, "r") as fh:
                return fh.read()
        except OSError:
            return None

    def read_tests(self) -> str:
        """Concatenated tests/*.py sources (site-name cross checks)."""
        chunks = []
        try:
            names = sorted(os.listdir(self.tests_dir))
        except OSError:
            return ""
        for name in names:
            if not name.endswith(".py"):
                continue
            try:
                with open(os.path.join(self.tests_dir, name)) as fh:
                    chunks.append(fh.read())
            except OSError:
                continue
        return "\n".join(chunks)


def _iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                out.append(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for name in sorted(files):
                if name.endswith(".py"):
                    out.append(os.path.join(root, name))
    return out


def all_rules() -> List[Rule]:
    """The registered rule set, id-ordered."""
    from .rules_faults import FaultCoverageRule
    from .rules_jit import (DtypeF64Rule, DtypePromotionRule,
                            JitDonationReuseRule, JitHostSyncRule,
                            JitPythonControlFlowRule,
                            JitStaticScalarRule)
    from .rules_lock import LockDisciplineRule, LockOrderRule
    from .rules_obs import ObservabilityBracketRule
    from .rules_pallas import PallasKernelRule
    from .rules_perf import PerfHotPathSortRule
    from .rules_registry import (CliTaskRoutingRule, ConfigAttrRule,
                                 FaultSiteRegistryRule, ParamDocsRule,
                                 PrometheusDocsRule)
    from .rules_spmd import (CollectiveBranchRule, CollectiveRaiseRule,
                             CollectiveRegistryRule, CollectiveShapeRule)
    from .rules_trace import (TraceCallbackRule, TraceDonationRule,
                              TraceF64Rule, TraceManifestCoverageRule,
                              TraceRetraceStableRule, TraceSortFreeRule)
    rules: List[Rule] = [
        JitStaticScalarRule(), JitPythonControlFlowRule(),
        JitHostSyncRule(), JitDonationReuseRule(),
        DtypeF64Rule(), DtypePromotionRule(),
        LockDisciplineRule(), LockOrderRule(),
        ObservabilityBracketRule(),
        PallasKernelRule(), PerfHotPathSortRule(),
        ParamDocsRule(), CliTaskRoutingRule(), ConfigAttrRule(),
        FaultSiteRegistryRule(), PrometheusDocsRule(),
        FaultCoverageRule(),
        CollectiveBranchRule(), CollectiveRaiseRule(),
        CollectiveShapeRule(), CollectiveRegistryRule(),
        StaleSuppressionRule(),
        TraceSortFreeRule(), TraceF64Rule(), TraceCallbackRule(),
        TraceDonationRule(), TraceRetraceStableRule(),
        TraceManifestCoverageRule(),
    ]
    return sorted(rules, key=lambda r: r.id)


class Analyzer:
    """Run every rule over the target paths; collect findings.

    `interproc=False` drops the cross-function call-graph facts (the
    per-file rules fall back to their intraprocedural behaviour —
    tests use this to prove which findings only the interprocedural
    engine sees). `cache=False` bypasses the `.tpulint_cache/`
    incremental store; with the default `cache=True` the cache only
    activates when the scan set contains the analyzer's own package
    (its config.py), so fixture scans never touch disk."""

    def __init__(self, rules: Optional[Sequence[Rule]] = None,
                 interproc: bool = True, cache: bool = True):
        self.rules = list(rules) if rules is not None else all_rules()
        self.interproc = interproc
        self.cache = cache

    # ------------------------------------------------------------------
    def parse_paths(self, paths: Iterable[str]) -> List[ParsedFile]:
        files = []
        for path in _iter_py_files(paths):
            try:
                with open(path, "r") as fh:
                    source = fh.read()
            except OSError as exc:
                files.append(ParsedFile(path, ""))
                files[-1].parse_error = str(exc)
                continue
            files.append(ParsedFile(path, source))
        return files

    def run(self, paths: Iterable[str]) -> List[Finding]:
        files = self.parse_paths(paths)
        ctx = ProjectContext(files)
        # interprocedural facts: call graph + cross-function host-sync /
        # collective / lock summaries, shared by JIT003/COLL00x/LOCK001
        facts = None
        if self.interproc:
            from .callgraph import InterprocFacts
            facts = InterprocFacts(files)
        ctx.facts = facts
        for rule in self.rules:
            rule.facts = facts
        # the incremental cache only engages when the scan set contains
        # the analyzer's own package (not a fixture mini-project that
        # happens to ship a config.py) so fixture runs under tests/
        # never create cache directories
        cache = None
        own_pkg = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        if self.cache and any(
                os.path.basename(f.path) == "config.py"
                and os.path.dirname(os.path.abspath(f.path)) == own_pkg
                for f in files):
            from .cache import LintCache
            cache = LintCache(ctx.repo_root)
        ctx.lint_cache = cache
        findings: List[Finding] = []
        by_path = {f.path: f for f in files}
        for parsed in files:
            if parsed.parse_error is not None:
                findings.append(Finding(
                    rule="PARSE001", severity="error", path=parsed.path,
                    line=1,
                    message=f"file does not parse: {parsed.parse_error}"))
                continue
            key = None
            if cache is not None:
                deps = facts.file_deps(parsed.path) if facts else ()
                key = cache.file_key(parsed.path, deps,
                                     self.interproc)
                hit = cache.get_file_findings(key)
                if hit is not None:
                    findings.extend(Finding(**d) for d in hit)
                    continue
            file_findings: List[Finding] = []
            for rule in self.rules:
                file_findings.extend(rule.check(parsed))
            if key is not None:
                # stored pre-suppression-marking: the marking pass below
                # is deterministic in (path, content), so replay is exact
                cache.put_file_findings(
                    key, [f.to_dict() for f in file_findings])
            findings.extend(file_findings)
        for rule in self.rules:
            if isinstance(rule, ProjectRule):
                findings.extend(rule.check_project(files, ctx))
        # stale-suppression self-check: runs over the FINAL finding set
        # (a suppression is live iff it suppresses one of these)
        sup = next((r for r in self.rules
                    if isinstance(r, StaleSuppressionRule)), None)
        if sup is not None:
            findings.extend(sup.check_run(
                files, findings, (r.id for r in self.rules)))
        for f in findings:
            parsed = by_path.get(f.path)
            if parsed is not None and parsed.is_suppressed(f.rule, f.line):
                f.suppressed = True
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return findings

    # ------------------------------------------------------------------
    @staticmethod
    def render_text(findings: Sequence[Finding],
                    show_suppressed: bool = False) -> str:
        shown = [f for f in findings
                 if show_suppressed or not f.suppressed]
        lines = [f.render() for f in shown]
        n_sup = sum(1 for f in findings if f.suppressed)
        lines.append(f"tpulint: {len([f for f in findings if not f.suppressed])} "
                     f"finding(s), {n_sup} suppressed")
        return "\n".join(lines)

    @staticmethod
    def render_json(findings: Sequence[Finding]) -> str:
        active = [f for f in findings if not f.suppressed]
        return json.dumps({
            "findings": [f.to_dict() for f in findings],
            "unsuppressed": len(active),
            "suppressed": len(findings) - len(active),
        }, indent=2)

    @staticmethod
    def render_sarif(findings: Sequence[Finding],
                     rules: Optional[Sequence[Rule]] = None) -> str:
        """SARIF 2.1.0 — the CI-annotation interchange format.

        Suppressed findings are emitted with an ``inSource``
        suppression record rather than dropped, so diff annotators can
        distinguish "fixed" from "silenced"."""
        if rules is None:
            rules = all_rules()
        results = []
        for f in findings:
            result = {
                "ruleId": f.rule,
                "level": "error" if f.severity == "error" else "warning",
                "message": {"text": f.message},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace(os.sep, "/")},
                        "region": {"startLine": max(1, f.line)},
                    },
                }],
            }
            if f.suppressed:
                result["suppressions"] = [{"kind": "inSource"}]
            results.append(result)
        driver = {
            "name": "tpulint",
            "informationUri":
                "https://example.invalid/docs/StaticAnalysis.md",
            "rules": [{
                "id": r.id,
                "defaultConfiguration": {
                    "level": "error" if r.severity == "error"
                    else "warning"},
                "shortDescription": {"text": r.doc or r.id},
            } for r in rules],
        }
        return json.dumps({
            "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                       "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
            "version": "2.1.0",
            "runs": [{"tool": {"driver": driver}, "results": results}],
        }, indent=2)
