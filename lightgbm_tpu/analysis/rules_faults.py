"""Fault-site coverage audit (FAULT001).

Every device dispatch entry point — the boundaries where Python hands a
batch of work to XLA — must be wrapped in a named fault site from
reliability/faults.py, so the fault-injection harness can kill it in
tests and the retry/fallback ladders stay exercised. The manifest below
IS the list of dispatch entry points; growing a new one means adding a
row here and a `faults.inject(...)` (or wrapper) call there.

Injection is recognised either as a site-name string literal inside the
function body (the direct `faults.inject("histogram_build")` form) or a
call to a known wrapper that owns the site (`_maybe_inject_fused_fault`
maps env state onto `fused_dispatch`; `parallel.comm.
check_collective_fault` owns `collective_psum`).
"""

from __future__ import annotations

import ast
import os
from typing import List, Sequence

from .dataflow import call_name
from .engine import Finding, ParsedFile, ProjectContext, ProjectRule

__all__ = ["FaultCoverageRule", "DISPATCH_MANIFEST", "SITE_WRAPPERS"]

#: (file basename, function/method name, required fault site)
DISPATCH_MANIFEST = (
    ("gbdt.py", "train_many_dispatch", "fused_dispatch"),
    ("gbdt.py", "_grow", "histogram_build"),
    ("gbdt.py", "_grow", "collective_psum"),
    ("engine.py", "predict_raw", "serving_device_predict"),
    ("replicas.py", "dispatch", "serving_replica_predict"),
    ("multimodel.py", "dispatch_pack", "serving_pack_predict"),
    ("server.py", "hot_swap", "serving_hot_swap"),
    ("server.py", "hot_swap", "serving_hot_swap_commit"),
    ("checkpoint.py", "save_checkpoint", "checkpoint_io"),
    ("loader.py", "_ingest_chunk_step", "streaming_ingest"),
    ("trainer.py", "_publish", "loop_publish"),
    ("comm.py", "guarded_allgather", "collective_psum"),
    ("hist_agg.py", "build_feature_shards", "distributed_hist_agg"),
    ("elastic.py", "propose_shrink", "elastic_resize"),
)

#: wrapper function -> the site its body injects
SITE_WRAPPERS = {
    "_maybe_inject_fused_fault": "fused_dispatch",
    "check_collective_fault": "collective_psum",
    "_ingest_chunk_step": "streaming_ingest",
    "guarded_allgather": "collective_psum",
    "check_hist_agg_fault": "distributed_hist_agg",
}

#: manifest basenames that are ambiguous in the package (engine.py
#: exists at top level and in serving/) — constrain by parent dir
_DIR_HINTS = {
    ("engine.py", "predict_raw"): "serving",
    ("replicas.py", "dispatch"): "serving",
    ("multimodel.py", "dispatch_pack"): "serving",
    ("server.py", "hot_swap"): "serving",
    ("checkpoint.py", "save_checkpoint"): "reliability",
    ("gbdt.py", "train_many_dispatch"): "boosting",
    ("gbdt.py", "_grow"): "boosting",
    ("loader.py", "_ingest_chunk_step"): "streaming",
    ("trainer.py", "_publish"): "continuous",
    ("comm.py", "guarded_allgather"): "parallel",
    ("hist_agg.py", "build_feature_shards"): "distributed",
    ("elastic.py", "propose_shrink"): "distributed",
}


def _function_covers_site(fn: ast.AST, site: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) and node.value == site:
            return True
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name and SITE_WRAPPERS.get(name) == site:
                return True
    return False


class FaultCoverageRule(ProjectRule):
    id = "FAULT001"
    doc = ("every device dispatch entry point in the manifest "
           "(fused dispatch, histogram build, collective psum, serving "
           "device predict, checkpoint IO) must inject its named fault "
           "site — directly or via a registered wrapper — so the "
           "fault-injection harness can reach it")

    def check_project(self, files: Sequence[ParsedFile],
                      ctx: ProjectContext) -> List[Finding]:
        findings: List[Finding] = []
        for basename, fn_name, site in DISPATCH_MANIFEST:
            hint = _DIR_HINTS.get((basename, fn_name))
            target = None
            for parsed in files:
                if os.path.basename(parsed.path) != basename or \
                        parsed.tree is None:
                    continue
                parts = os.path.normpath(parsed.path).split(os.sep)
                if hint is not None and hint not in parts:
                    continue
                target = parsed
                break
            if target is None:
                continue        # file not in scanned set; nothing to say
            fn = None
            for node in ast.walk(target.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        node.name == fn_name:
                    fn = node
                    break
            if fn is None:
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=target.path, line=1,
                    message=f"dispatch entry point '{fn_name}' (site "
                    f"'{site}') not found in {basename} — update the "
                    f"FAULT001 manifest if it moved"))
                continue
            if not _function_covers_site(fn, site):
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=target.path, line=fn.lineno,
                    message=f"device dispatch entry point '{fn_name}' "
                    f"is not wrapped in fault site '{site}' — add "
                    f"faults.inject('{site}') (or its wrapper) at the "
                    f"dispatch boundary"))
        return findings
