"""jit-hygiene and dtype-discipline rules (device code paths only).

The recompile/host-sync contract these rules enforce: every `jax.jit`
or `pjit` entry point in the device directories (engine.DEVICE_DIRS)
must route Python scalars through `static_argnames`, must not branch
Python control flow on traced values, and must not force a host sync
(`float()`, `bool()`, `.item()`, `np.asarray()` ...) on a traced value
inside the jitted body. Dtype discipline: no float64 (and no implicit
promotion to it) inside jitted bodies — device accumulators are
explicit f32 (config `hist_dtype`, docs/PerfNotes.md).

What does NOT fire, by design:

- `x is None` / `x is not None` branches on traced parameters: a
  None-vs-array change alters the pytree *structure*, which retraces
  anyway — these are structural dispatch, not value-dependent control
  flow.
- anything reached through `.shape` / `.ndim` / `.dtype` / `.size`:
  static at trace time.
- host-side code outside jitted bodies (the serving request path bins
  rows in f64 on the host deliberately — exact threshold semantics).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .dataflow import branch_tests, dotted_name
from .engine import Finding, ParsedFile, Rule

__all__ = ["JitStaticScalarRule", "JitPythonControlFlowRule",
           "JitHostSyncRule", "JitDonationReuseRule", "DtypeF64Rule",
           "DtypePromotionRule", "iter_jitted_functions"]

#: attribute reads that are static at trace time
_STATIC_ATTRS = ("shape", "ndim", "dtype", "size")

#: call names that force a host sync / concretization on a traced value
_HOST_SYNC_FUNCS = ("float", "int", "bool", "complex")
_HOST_SYNC_METHODS = ("item", "tolist", "to_py")
_HOST_MODULES = ("np", "numpy")

_SCALAR_ANNOTATIONS = ("int", "float", "bool", "str")


def _dec_is_jit(expr: ast.expr) -> Tuple[bool, Set[str]]:
    """(is_jit, static_argnames) for one decorator / call expression.

    Recognizes `jax.jit`, `jit`, `pjit`, and
    `functools.partial(jax.jit, static_argnames=(...))` forms.
    """
    name = _dotted_name(expr)
    if name and name.split(".")[-1] in ("jit", "pjit"):
        return True, set()
    if isinstance(expr, ast.Call):
        fn = _dotted_name(expr.func)
        if fn and fn.split(".")[-1] == "partial" and expr.args:
            inner = _dotted_name(expr.args[0])
            if inner and inner.split(".")[-1] in ("jit", "pjit"):
                return True, _static_names_from_call(expr)
        if fn and fn.split(".")[-1] in ("jit", "pjit"):
            return True, _static_names_from_call(expr)
    return False, set()


#: shared with rules_pallas; the canonical implementation lives in
#: dataflow (returns '' — falsy, like the old None — for non-chains)
_dotted_name = dotted_name


def _static_names_from_call(call: ast.Call) -> Set[str]:
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str):
                    names.add(node.value)
    return names


def _donated_names_from_call(call: ast.Call) -> Set[str]:
    """Parameter names listed in a donate_argnames=... keyword
    (mirrors _static_names_from_call; donate_argnums is index-form and
    has no name to resolve here)."""
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnames":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str):
                    names.add(node.value)
    return names


def _donated_from_jit_expr(expr: ast.expr) -> Set[str]:
    """Donated parameter names when `expr` is a jit/pjit wrapping call
    (`jax.jit(fn, donate_argnames=...)` or the
    `functools.partial(jax.jit, donate_argnames=...)` decorator form),
    else empty."""
    if not isinstance(expr, ast.Call):
        return set()
    fn = _dotted_name(expr.func)
    if fn and fn.split(".")[-1] == "partial" and expr.args:
        inner = _dotted_name(expr.args[0])
        if inner and inner.split(".")[-1] in ("jit", "pjit"):
            return _donated_names_from_call(expr)
    if fn and fn.split(".")[-1] in ("jit", "pjit"):
        return _donated_names_from_call(expr)
    return set()


def iter_jitted_functions(tree: ast.AST):
    """Yield (func_def, static_names, via) for every jit entry point:
    decorated functions and `jax.jit(fn)` call forms whose target is a
    function defined in the same enclosing scope."""
    # map scope -> {name: FunctionDef} for call-form resolution
    for scope in ast.walk(tree):
        if not isinstance(scope, (ast.Module, ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        local_defs = {n.name: n for n in ast.iter_child_nodes(scope)
                      if isinstance(n, ast.FunctionDef)}
        for node in ast.iter_child_nodes(scope):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    is_jit, static = _dec_is_jit(dec)
                    if is_jit:
                        yield node, static, "decorator"
                        break
        # call form: jax.jit(fn, ...) anywhere inside this scope's
        # direct statements (return jax.jit(sharded), x = jit(f))
        for node in ast.walk(scope):
            if isinstance(node, ast.Call):
                fn = _dotted_name(node.func)
                if not fn or fn.split(".")[-1] not in ("jit", "pjit"):
                    continue
                if not node.args or not isinstance(node.args[0], ast.Name):
                    continue
                target = local_defs.get(node.args[0].id)
                if target is not None:
                    yield target, _static_names_from_call(node), "call"


def _param_names(func: ast.FunctionDef) -> List[ast.arg]:
    return list(func.args.posonlyargs) + list(func.args.args) + \
        list(func.args.kwonlyargs)


def _offending_names(expr: ast.expr, traced: Set[str]) -> List[ast.Name]:
    """Occurrences of traced names in `expr` that are value-dependent:
    skips `is None` comparisons and `.shape`-like attribute bases."""
    out: List[ast.Name] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Compare) and node.ops and \
                all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops):
            return                      # structural None dispatch
        if isinstance(node, ast.Attribute) and \
                node.attr in _STATIC_ATTRS:
            return                      # static at trace time
        if isinstance(node, ast.Name) and node.id in traced:
            out.append(node)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(expr)
    return out


def _enclosing_classes(parsed: ParsedFile) -> Dict[int, str]:
    """id(method node) -> enclosing class name, for call resolution of
    `self.m()` inside jitted methods."""
    out: Dict[int, str] = {}
    if parsed.tree is None:
        return out
    for node in ast.walk(parsed.tree):
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    out[id(sub)] = node.name
    return out


def _jit_bodies(parsed: ParsedFile):
    """(func, traced_param_names) for each jit entry in a device file."""
    if parsed.tree is None or not parsed.in_device_dir():
        return
    seen = set()
    for func, static, _via in iter_jitted_functions(parsed.tree):
        if id(func) in seen:
            continue
        seen.add(id(func))
        traced = {a.arg for a in _param_names(func)} - static - {"self"}
        yield func, static, traced


class JitStaticScalarRule(Rule):
    id = "JIT001"
    doc = ("jitted function parameter with a Python-scalar default or "
           "int/float/bool/str annotation is not in static_argnames — "
           "each distinct value retraces and recompiles the program")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        findings: List[Finding] = []
        for func, static, _traced in _jit_bodies(parsed):
            params = _param_names(func)
            defaults = list(func.args.defaults)
            kw_defaults = list(func.args.kw_defaults)
            # map param -> default expr (positional defaults right-align)
            pos = list(func.args.posonlyargs) + list(func.args.args)
            default_of: Dict[str, ast.expr] = {}
            for arg, dflt in zip(pos[len(pos) - len(defaults):], defaults):
                default_of[arg.arg] = dflt
            for arg, dflt in zip(func.args.kwonlyargs, kw_defaults):
                if dflt is not None:
                    default_of[arg.arg] = dflt
            for arg in params:
                if arg.arg in static or arg.arg == "self":
                    continue
                scalar = False
                dflt = default_of.get(arg.arg)
                if isinstance(dflt, ast.Constant) and \
                        isinstance(dflt.value, (bool, int, float, str)):
                    scalar = True
                ann = arg.annotation
                if isinstance(ann, ast.Name) and \
                        ann.id in _SCALAR_ANNOTATIONS:
                    scalar = True
                if scalar:
                    findings.append(self.finding(
                        parsed, arg.lineno,
                        f"jitted function '{func.name}': scalar "
                        f"parameter '{arg.arg}' must be listed in "
                        f"static_argnames (traced scalars recompile "
                        f"per value)"))
        return findings


class JitPythonControlFlowRule(Rule):
    id = "JIT002"
    doc = ("Python if/while/for-range control flow on a traced value "
           "inside a jitted body — either a trace error or a silent "
           "per-value recompile; use lax.cond/select or mark the "
           "argument static")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        findings: List[Finding] = []
        for func, _static, traced in _jit_bodies(parsed):
            for node, tests in branch_tests(func):
                for test in tests:
                    for name in _offending_names(test, traced):
                        findings.append(self.finding(
                            parsed, getattr(name, "lineno", node.lineno),
                            f"jitted function '{func.name}': Python "
                            f"control flow on traced value "
                            f"'{name.id}' (host-sync / recompile "
                            f"hazard)"))
        return findings


class JitHostSyncRule(Rule):
    id = "JIT003"
    doc = ("float()/int()/bool()/.item()/np.* applied to a traced value "
           "inside a jitted body — forces a device->host sync at trace "
           "time (or a concretization error); with the interprocedural "
           "engine, also when the sync happens inside a helper the "
           "traced value is passed to")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        findings: List[Finding] = []
        facts = getattr(self, "facts", None)
        class_of = _enclosing_classes(parsed) if facts is not None else {}
        for func, _static, traced in _jit_bodies(parsed):
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                label = self._host_call_label(node)
                if label is None:
                    continue
                args = list(node.args) + \
                    [kw.value for kw in node.keywords]
                hit = None
                for arg in args:
                    names = _offending_names(arg, traced)
                    if names:
                        hit = names[0]
                        break
                # method form: x.item() syncs its receiver
                if hit is None and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _HOST_SYNC_METHODS:
                    names = _offending_names(node.func.value, traced)
                    if names:
                        hit = names[0]
                if hit is not None:
                    findings.append(self.finding(
                        parsed, node.lineno,
                        f"jitted function '{func.name}': host sync "
                        f"'{label}' on traced value '{hit.id}'"))
            if facts is None:
                continue
            # interprocedural: the sync lives in a helper (possibly
            # modules away); flag the call site that feeds a traced
            # value into the helper's syncing parameter
            for call, callee, hits in facts.host_sync_callees(
                    parsed.path, func, class_of.get(id(func))):
                for pname, arg in hits:
                    names = _offending_names(arg, traced)
                    if not names:
                        continue
                    label, spath, sline = callee.host_sync_params[pname]
                    where = os.path.basename(spath)
                    findings.append(self.finding(
                        parsed, call.lineno,
                        f"jitted function '{func.name}': traced value "
                        f"'{names[0].id}' reaches host sync '{label}' "
                        f"through '{callee.name}()' parameter "
                        f"'{pname}' ({where}:{sline})"))
                    break
        return findings

    @staticmethod
    def _host_call_label(node: ast.Call) -> Optional[str]:
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _HOST_SYNC_FUNCS:
            return f"{fn.id}()"
        if isinstance(fn, ast.Attribute):
            if fn.attr in _HOST_SYNC_METHODS:
                return f".{fn.attr}()"
            base = _dotted_name(fn.value)
            if base in _HOST_MODULES:
                return f"{base}.{fn.attr}()"
        return None


class JitDonationReuseRule(Rule):
    id = "JIT004"
    doc = ("a Python name is read again after being passed as a donated "
           "argument (donate_argnames) to a jitted call — the donated "
           "buffer is deleted on non-CPU backends, so any later use of "
           "that name dies at runtime; rebind the name from the call's "
           "result before reading it")

    # Scope, by design: only call sites whose callee resolves IN THE
    # SAME FILE to a jit wrapping that lists donate_argnames (decorated
    # def, or `name = jax.jit(fn, donate_argnames=...)` assignment), and
    # only donated arguments passed as bare names. Attribute-form args
    # (self.train_score) are deliberately not tracked — attribute
    # rebinding is object-ownership territory the name-flow analysis
    # cannot see, and flagging them would drown the rule in noise.
    # Ordering is textual (line order), so a loop back-edge reuse is out
    # of reach; the `name = jitted(name, ...)` rebind idiom is clean.

    def check(self, parsed: ParsedFile) -> List[Finding]:
        if parsed.tree is None or not parsed.in_device_dir():
            return []
        defs = {n.name: n for n in ast.walk(parsed.tree)
                if isinstance(n, ast.FunctionDef)}
        # callable name -> (donated param names, signature def or None)
        registry: Dict[str, Tuple[Set[str],
                                  Optional[ast.FunctionDef]]] = {}
        for node in ast.walk(parsed.tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    donated = _donated_from_jit_expr(dec)
                    if donated:
                        registry[node.name] = (donated, node)
                        break
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                donated = _donated_from_jit_expr(node.value)
                if donated:
                    target = None
                    if node.value.args and \
                            isinstance(node.value.args[0], ast.Name):
                        target = defs.get(node.value.args[0].id)
                    registry[node.targets[0].id] = (donated, target)
        if not registry:
            return []
        findings: List[Finding] = []
        scopes = [parsed.tree] + [n for n in ast.walk(parsed.tree)
                                  if isinstance(n, ast.FunctionDef)]
        for scope in scopes:
            findings.extend(self._check_scope(parsed, scope, registry))
        return findings

    def _check_scope(self, parsed: ParsedFile, scope: ast.AST,
                     registry) -> List[Finding]:
        nodes = self._scope_nodes(scope)
        calls = [n for n in nodes if isinstance(n, ast.Call) and
                 isinstance(n.func, ast.Name) and n.func.id in registry]
        if not calls:
            return []
        names = [n for n in nodes if isinstance(n, ast.Name)]
        stmts = [n for n in nodes
                 if isinstance(n, (ast.Assign, ast.AnnAssign,
                                   ast.AugAssign, ast.NamedExpr))]
        findings: List[Finding] = []
        for call in calls:
            donated, sig = registry[call.func.id]
            exprs = [kw.value for kw in call.keywords
                     if kw.arg in donated]
            if sig is not None:
                params = [a.arg for a in _param_names(sig)]
                for idx, arg in enumerate(call.args):
                    if isinstance(arg, ast.Starred):
                        break
                    if idx < len(params) and params[idx] in donated:
                        exprs.append(arg)
            tracked = {e.id for e in exprs if isinstance(e, ast.Name)}
            end = (getattr(call, "end_lineno", None) or call.lineno,
                   getattr(call, "end_col_offset", None) or 0)
            for var in sorted(tracked):
                if self._rebound_by_call_stmt(stmts, call, var):
                    continue
                events = sorted(
                    (n for n in names if n.id == var and
                     (n.lineno, n.col_offset) > end),
                    key=lambda n: (n.lineno, n.col_offset))
                for n in events:
                    if isinstance(n.ctx, (ast.Store, ast.Del)):
                        break
                    findings.append(self.finding(
                        parsed, n.lineno,
                        f"'{var}' read after being donated to jitted "
                        f"call '{call.func.id}' (buffer deleted on "
                        f"device; rebind from the call's result first)"))
        return findings

    @staticmethod
    def _scope_nodes(scope: ast.AST) -> List[ast.AST]:
        """Nodes belonging to `scope` directly: nested function/class
        bodies form their own scopes and are skipped."""
        out: List[ast.AST] = []
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        return out

    @staticmethod
    def _rebound_by_call_stmt(stmts, call: ast.Call, var: str) -> bool:
        """True when the statement holding `call` assigns `var` itself —
        the `score = advance(score, ...)` rebind idiom."""
        for st in stmts:
            if not any(n is call for n in ast.walk(st)):
                continue
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) and n.id == var:
                        return True
        return False


class DtypeF64Rule(Rule):
    id = "DTYPE001"
    doc = ("float64 reference inside a jitted body — device "
           "accumulators are explicit f32/bf16 (hist_dtype); f64 "
           "either errors (x64 disabled) or silently halves MXU "
           "throughput")

    _F64 = ("float64", "double")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        findings: List[Finding] = []
        for func, _static, _traced in _jit_bodies(parsed):
            for node in ast.walk(func):
                label = None
                if isinstance(node, ast.Attribute) and \
                        node.attr in self._F64:
                    label = f".{node.attr}"
                elif isinstance(node, ast.Constant) and \
                        isinstance(node.value, str) and \
                        node.value in self._F64:
                    label = f"'{node.value}'"
                if label is not None:
                    findings.append(self.finding(
                        parsed, node.lineno,
                        f"jitted function '{func.name}': float64 "
                        f"reference {label} in device code"))
        return findings


class DtypePromotionRule(Rule):
    id = "DTYPE002"
    doc = ("implicit promotion to float64 inside a jitted body: "
           "dtype=float / .astype(float) resolve to f64 under x64 and "
           "make the accumulator dtype platform-dependent — spell the "
           "f32 dtype explicitly")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        findings: List[Finding] = []
        for func, _static, _traced in _jit_bodies(parsed):
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                line = None
                for kw in node.keywords:
                    if kw.arg == "dtype" and \
                            isinstance(kw.value, ast.Name) and \
                            kw.value.id == "float":
                        line = kw.value.lineno
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "astype" and node.args and \
                        isinstance(node.args[0], ast.Name) and \
                        node.args[0].id == "float":
                    line = node.lineno
                if line is not None:
                    findings.append(self.finding(
                        parsed, line,
                        f"jitted function '{func.name}': builtin "
                        f"'float' as a dtype (resolves to float64); "
                        f"use an explicit f32 dtype"))
        return findings
