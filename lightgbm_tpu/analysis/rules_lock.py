"""Lock-discipline rules: unlocked shared state + lock-order cycles.

Ten classes in this codebase guard shared state with a `self._lock`
(the serving batcher/registry/metrics, the observability ring buffers,
the fault registry...). The convention the checker enforces:

LOCK001 — in any class whose `__init__` creates `self._lock`
(threading.Lock/RLock/Condition), every read or write of an
underscore-prefixed instance attribute that is *mutated after
construction* must happen inside a `with self._lock:` block.
Attributes only assigned in `__init__` are read-only after
construction and exempt (e.g. a worker Thread handle, a
threading.local). Methods whose names end in `_locked` are exempt —
the naming contract says "caller holds the lock". Nested functions
and lambdas count as unlocked contexts: they usually escape the
method and run later on another thread.

LOCK002 — a cross-class lock-acquisition-order graph: an edge A -> B
is recorded when code holding A's lock calls a method of class B that
acquires B's own lock. A cycle in that graph is a lock-inversion
hazard (thread 1 holds A waiting for B, thread 2 holds B waiting for
A) — the lightweight race detector for the serving batcher +
observability registry threads. Method-name matching is intentionally
conservative: names that collide with builtin container methods
(`get`, `add`, `clear`, ...) never create edges.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .dataflow import child_blocks, dotted_name, stmt_exprs
from .engine import Finding, ParsedFile, ProjectContext, ProjectRule, Rule

__all__ = ["LockDisciplineRule", "LockOrderRule", "collect_lock_classes"]

_LOCK_FACTORIES = ("Lock", "RLock", "Condition")

#: method names too generic to attribute to a lock class (they collide
#: with dict/list/set methods on plain containers)
_GENERIC_METHODS = frozenset((
    "get", "set", "add", "pop", "clear", "update", "remove", "append",
    "extend", "insert", "count", "index", "copy", "keys", "values",
    "items", "setdefault", "sort", "join", "split", "close", "start",
))


def _is_lock_ctor(expr: ast.expr) -> bool:
    """True for threading.Lock() / Lock() / threading.Condition(...)."""
    if not isinstance(expr, ast.Call):
        return False
    fn = expr.func
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        fn.id if isinstance(fn, ast.Name) else None
    return name in _LOCK_FACTORIES


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' when node is `self.x`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class LockClass:
    """Per-class lock model: lock attrs, guarded attrs, methods."""

    def __init__(self, node: ast.ClassDef, path: str):
        self.node = node
        self.path = path
        self.name = node.name
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.lock_attrs: Set[str] = set()
        init = self.methods.get("__init__")
        if init is not None:
            for sub in ast.walk(init):
                if isinstance(sub, ast.Assign) and \
                        _is_lock_ctor(sub.value):
                    for tgt in sub.targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            self.lock_attrs.add(attr)
        self.guarded_attrs = self._find_guarded() if self.lock_attrs \
            else set()
        # methods that acquire the lock somewhere in their own body
        self.acquiring_methods: Set[str] = {
            name for name, fn in self.methods.items()
            if name != "__init__" and self._acquires_lock(fn)}

    # ------------------------------------------------------------------
    def _find_guarded(self) -> Set[str]:
        """Underscore attrs written outside __init__ = shared mutable
        state that the lock must guard everywhere."""
        guarded: Set[str] = set()
        for name, fn in self.methods.items():
            if name == "__init__":
                continue
            for sub in ast.walk(fn):
                targets: List[ast.expr] = []
                if isinstance(sub, ast.Assign):
                    targets = list(sub.targets)
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                    targets = [sub.target]
                elif isinstance(sub, ast.Delete):
                    targets = list(sub.targets)
                for tgt in targets:
                    # tuple unpack: (a, self._x) = ...
                    parts = tgt.elts if isinstance(
                        tgt, (ast.Tuple, ast.List)) else [tgt]
                    for part in parts:
                        # container mutation counts: self._x[k] = v,
                        # del self._x[k]
                        while isinstance(part, (ast.Subscript,
                                                ast.Starred)):
                            part = part.value
                        attr = _self_attr(part)
                        if attr and attr.startswith("_") and \
                                not attr.startswith("__") and \
                                attr not in self.lock_attrs and \
                                attr not in self.methods:
                            guarded.add(attr)
        return guarded

    def _acquires_lock(self, fn: ast.FunctionDef) -> bool:
        for sub in ast.walk(fn):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    attr = _self_attr(item.context_expr)
                    if attr in self.lock_attrs:
                        return True
        return False


def _lock_held_spans(fn: ast.AST) -> List[Tuple[int, int]]:
    """Line spans inside `with <something named *lock*>:` blocks —
    the held-context heuristic for the `_locked` delegation check."""
    spans: List[Tuple[int, int]] = []
    for node in ast.walk(fn):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            name = dotted_name(item.context_expr)
            if not name and isinstance(item.context_expr, ast.Call):
                name = dotted_name(item.context_expr.func)
            if "lock" in name.lower():
                end = getattr(node, "end_lineno", None) or node.lineno
                spans.append((node.lineno, end))
                break
    return spans


def collect_lock_classes(parsed: ParsedFile) -> List[LockClass]:
    if parsed.tree is None:
        return []
    out = []
    for node in ast.walk(parsed.tree):
        if isinstance(node, ast.ClassDef):
            lc = LockClass(node, parsed.path)
            if lc.lock_attrs:
                out.append(lc)
    return out


class _LockWalker:
    """Statement walker tracking whether self's lock is held, reporting
    guarded-attr touches outside it and (for LOCK002) method calls made
    while holding it."""

    def __init__(self, cls: LockClass):
        self.cls = cls
        self.violations: List[Tuple[int, str, str]] = []  # line, attr, meth
        self.locked_calls: List[Tuple[int, str]] = []     # line, meth name

    def walk_method(self, fn: ast.FunctionDef) -> None:
        exempt = (fn.name == "__init__" or fn.name == "__del__" or
                  fn.name.endswith("_locked"))
        self._walk_body(fn.body, locked=False, method=fn.name,
                        exempt=exempt)

    # ------------------------------------------------------------------
    def _walk_body(self, stmts: Sequence[ast.stmt], locked: bool,
                   method: str, exempt: bool) -> None:
        for stmt in stmts:
            self._walk_stmt(stmt, locked, method, exempt)

    def _walk_stmt(self, stmt: ast.stmt, locked: bool, method: str,
                   exempt: bool) -> None:
        if isinstance(stmt, ast.With):
            acquires = any(
                _self_attr(item.context_expr) in self.cls.lock_attrs
                for item in stmt.items)
            for item in stmt.items:
                self._scan_expr(item.context_expr, locked, method, exempt)
            self._walk_body(stmt.body, locked or acquires, method, exempt)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # nested defs escape the method and run later (futures,
            # worker threads): treat their bodies as unlocked
            self._walk_body(stmt.body, locked=False, method=method,
                            exempt=exempt)
            return
        if isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    self._walk_body(sub.body, locked=False, method=method,
                                    exempt=exempt)
            return
        # generic statement: scan its own expressions (dataflow.
        # stmt_exprs), recurse into its blocks (dataflow.child_blocks)
        for expr in stmt_exprs(stmt):
            self._scan_expr(expr, locked, method, exempt)
        for block in child_blocks(stmt):
            self._walk_body(block, locked, method, exempt)

    def _scan_expr(self, expr: ast.expr, locked: bool, method: str,
                   exempt: bool) -> None:
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue    # handled as statements where relevant
            attr = _self_attr(node)
            if attr is None:
                continue
            if locked and isinstance(node, ast.Attribute):
                pass
            if attr in self.cls.guarded_attrs and not locked and \
                    not exempt and attr not in self.cls.methods:
                self.violations.append(
                    (node.lineno, attr, method))
            if locked:
                # record method calls made while holding the lock:
                # self.<obj>.<meth>(...) or <name>.<meth>(...) handled
                # by the caller via full-expression scan
                pass
        if locked:
            for node in ast.walk(expr):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute):
                    meth = node.func.attr
                    if meth not in _GENERIC_METHODS:
                        self.locked_calls.append((node.lineno, meth))


class LockDisciplineRule(Rule):
    id = "LOCK001"
    doc = ("read/write of a lock-guarded underscore attribute outside "
           "`with self._lock:` in a class that creates self._lock — "
           "torn reads / lost updates under the serving and "
           "observability threads; with the interprocedural engine, "
           "also calls into `*_locked` helpers (caller-holds-the-lock "
           "contract, resolved across modules) from lock-free contexts")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        findings: List[Finding] = []
        for cls in collect_lock_classes(parsed):
            walker = _LockWalker(cls)
            for fn in cls.methods.values():
                walker.walk_method(fn)
            seen = set()
            for line, attr, method in walker.violations:
                key = (line, attr)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(self.finding(
                    parsed, line,
                    f"{cls.name}.{method}: access to guarded attribute "
                    f"'self.{attr}' outside `with self.<lock>:` "
                    f"(guarded because it is written post-__init__)"))
        findings.extend(self._check_delegation(parsed))
        return findings

    # -- interprocedural `_locked` delegation ---------------------------
    def _check_delegation(self, parsed: ParsedFile) -> List[Finding]:
        """The `_locked` suffix is a contract: the caller holds the
        lock. With call-graph facts the contract is checked at every
        delegation edge, even when the helper lives in another module.
        Held-context heuristic: textually inside a `with` whose context
        expression names a lock (`self._lock`, `registry_lock`, ...).
        Callers that are themselves `_locked` (or __init__/__del__,
        where no other thread can race) inherit the contract upward."""
        facts = getattr(self, "facts", None)
        if facts is None or parsed.tree is None:
            return []
        class_of: Dict[int, str] = {}
        for node in ast.walk(parsed.tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        class_of[id(sub)] = node.name
        findings: List[Finding] = []
        for node in ast.walk(parsed.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name.endswith("_locked") or \
                    node.name in ("__init__", "__del__"):
                continue
            held = _lock_held_spans(node)
            for call, callee in facts.locked_delegate_calls(
                    parsed.path, node, class_of.get(id(node))):
                if any(lo <= call.lineno <= hi for lo, hi in held):
                    continue
                where = os.path.basename(callee.path)
                findings.append(self.finding(
                    parsed, call.lineno,
                    f"'{node.name}' calls '{callee.name}' "
                    f"({where}:{callee.node.lineno}) without holding a "
                    f"lock — the '_locked' suffix contract requires "
                    f"the caller to hold the lock"))
        return findings


class LockOrderRule(ProjectRule):
    id = "LOCK002"
    doc = ("cycle in the cross-class lock-acquisition-order graph: "
           "holding class A's lock while calling into class B's "
           "lock-acquiring method, and vice versa — deadlock hazard "
           "between library threads")

    def check_project(self, files: Sequence[ParsedFile],
                      ctx: ProjectContext) -> List[Finding]:
        classes: List[Tuple[LockClass, ParsedFile]] = []
        for parsed in files:
            for cls in collect_lock_classes(parsed):
                classes.append((cls, parsed))
        # method name -> owning lock classes (for edge resolution)
        owners: Dict[str, List[LockClass]] = {}
        for cls, _ in classes:
            for meth in cls.acquiring_methods:
                if meth not in _GENERIC_METHODS:
                    owners.setdefault(meth, []).append(cls)
        # build edges: call under A's lock to a lock-acquiring method
        edges: Dict[str, Set[str]] = {}
        sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for cls, parsed in classes:
            walker = _LockWalker(cls)
            for fn in cls.methods.values():
                walker.walk_method(fn)
            for line, meth in walker.locked_calls:
                for target in owners.get(meth, ()):  # may be ambiguous
                    if target.name == cls.name:
                        continue
                    edges.setdefault(cls.name, set()).add(target.name)
                    sites.setdefault((cls.name, target.name),
                                     (parsed.path, line))
        findings: List[Finding] = []
        for cycle in self._find_cycles(edges):
            a, b = cycle[0], cycle[1 % len(cycle)]
            path, line = sites.get((a, b), ("<project>", 1))
            findings.append(Finding(
                rule=self.id, severity=self.severity, path=path,
                line=line,
                message=("lock-order cycle between classes: "
                         + " -> ".join(cycle + [cycle[0]])
                         + " (lock inversion / deadlock hazard)")))
        return findings

    @staticmethod
    def _find_cycles(edges: Dict[str, Set[str]]) -> List[List[str]]:
        """Simple cycles via DFS; each cycle reported once, rotated to
        its lexicographically smallest node."""
        cycles: Set[Tuple[str, ...]] = set()

        def dfs(node: str, path: List[str], on_path: Set[str]) -> None:
            for nxt in sorted(edges.get(node, ())):
                if nxt in on_path:
                    i = path.index(nxt)
                    cyc = path[i:]
                    k = cyc.index(min(cyc))
                    cycles.add(tuple(cyc[k:] + cyc[:k]))
                    continue
                if len(path) < 16:
                    dfs(nxt, path + [nxt], on_path | {nxt})

        for start in sorted(edges):
            dfs(start, [start], {start})
        return [list(c) for c in sorted(cycles)]
