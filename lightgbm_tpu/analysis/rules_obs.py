"""Observability bracket audit (OBS001).

PR-10 adds the crash flight recorder (observability/flightrec.py): on a
watchdog abort or injected rank death, the post-mortem bundle is only
as good as the events that reached the ring. Every collective site
(rules_spmd.COLLECTIVE_MANIFEST) and device-dispatch fault site
(rules_faults.DISPATCH_MANIFEST) must therefore sit inside an
observability bracket — a span, a collective-guard bracket, or a
``record_*`` recorder call — so the last thing a dying rank did has a
name in ``postmortem_<rank>.json``.

A bracket is recognised as a call, anywhere in the function body
(nested defs included), whose final dotted segment is one of
`BRACKET_CALLS` or starts with ``record_``. Device-side learner entry
points run inside traced code where a host-side recorder call cannot
live; their bracket is audited in the host caller that dispatches them
(`DELEGATED_SITES`).

The rule is gated on the scanned set containing the flight recorder
itself (observability/flightrec.py): fixture trees that model other
subsystems (analysis_fixtures/fault_bad, spmd_registry_bad) are not
expected to carry observability plumbing.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .dataflow import call_name
from .engine import Finding, ParsedFile, ProjectContext, ProjectRule
from .rules_faults import DISPATCH_MANIFEST, _DIR_HINTS
from .rules_spmd import COLLECTIVE_MANIFEST

__all__ = ["ObservabilityBracketRule", "BRACKET_CALLS",
           "DELEGATED_SITES"]

_FLIGHTREC_BASENAME = "flightrec.py"

#: call names (final dotted segment) that count as an observability
#: bracket: the watchdog collective bracket and its context manager,
#: the bracketed collective wrappers (whose bodies feed the recorder),
#: span/profiler brackets, and the phase timer
BRACKET_CALLS = frozenset({
    "collective_guard",          # watchdog module-level bracket
    "guard",                     # CollectiveGuard.guard(...)
    "guarded_allgather",         # bracketed collective choke point
    "checkpoint_agree",          # delegates to guarded_allgather
    "_allgather_find_mappers",   # delegates to guarded_allgather
    "span",                      # registry.trace.span(...)
    "capture",                   # profiler.capture(...)
    "timeit",                    # global_timer phase bracket
})

#: any call whose name starts with this also counts (record_span,
#: record_collective, record_fused_block, record_streaming_chunk, ...)
BRACKET_PREFIX = "record_"

#: (manifest basename, function) -> (basename, dir hint, function) of
#: the host caller that owns the bracket for that site
DELEGATED_SITES = {
    ("grower.py", "grow_tree"): ("gbdt.py", "boosting", "_grow"),
    ("grower_mxu.py", "grow_tree_mxu"): ("gbdt.py", "boosting", "_grow"),
    # the shared growth core traced by both grower drivers (monolithic
    # grow_tree_mxu and the level-pipelined stage programs) — same
    # host-side bracket
    ("grower_mxu.py", "_make_grow_core"): ("gbdt.py", "boosting", "_grow"),
    ("histogram_mxu.py", "quantize_gradients"):
        ("gbdt.py", "boosting", "_grow"),
    ("loader.py", "_ingest_chunk_step"):
        ("loader.py", "streaming", "build_streamed_dataset"),
    ("hist_agg.py", "reduce_scatter_hist"):
        ("gbdt.py", "boosting", "_grow"),
}


def _obs_manifest() -> List[Tuple[str, Optional[str], str, str]]:
    """(basename, dir hint, function, provenance) rows to audit —
    the union of the collective registry and the fault-site dispatch
    manifest, with delegated device entries rewritten to their host
    caller. Provenance names the manifest row(s) behind each target,
    for the finding message."""
    rows: Dict[Tuple[str, Optional[str], str], List[str]] = {}

    def _add(basename: str, hint: Optional[str], fn: str,
             origin: str) -> None:
        target = DELEGATED_SITES.get((basename, fn))
        if target is not None:
            basename, hint, fn = target
            origin += " (delegated to host caller)"
        rows.setdefault((basename, hint, fn), []).append(origin)

    for basename, hint, fn, site, _mode, _tests in COLLECTIVE_MANIFEST:
        _add(basename, hint, fn, f"collective site '{site}'")
    for basename, fn, site in DISPATCH_MANIFEST:
        _add(basename, _DIR_HINTS.get((basename, fn)), fn,
             f"fault site '{site}'")
    return [(b, h, f, "; ".join(sorted(set(origins))))
            for (b, h, f), origins in sorted(
                rows.items(), key=lambda kv: (kv[0][0], kv[0][2]))]


def _function_has_bracket(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name and (name in BRACKET_CALLS or
                     name.startswith(BRACKET_PREFIX)):
            return True
    return False


class ObservabilityBracketRule(ProjectRule):
    id = "OBS001"
    doc = ("every registered collective site and device-dispatch fault "
           "site must run inside an observability bracket (a span, "
           "collective-guard bracket, bracketed collective wrapper, or "
           "record_* recorder call) so the crash flight recorder's "
           "postmortem bundle can name what a dying rank was doing")

    def check_project(self, files: Sequence[ParsedFile],
                      ctx: ProjectContext) -> List[Finding]:
        # gate: only audit trees that carry the flight recorder — the
        # subsystem whose bundles this bracketing exists to feed
        if not any(os.path.basename(p.path) == _FLIGHTREC_BASENAME and
                   "observability" in
                   os.path.normpath(p.path).split(os.sep)
                   for p in files):
            return []
        findings: List[Finding] = []
        for basename, hint, fn_name, origin in _obs_manifest():
            target = None
            for parsed in files:
                if os.path.basename(parsed.path) != basename or \
                        parsed.tree is None:
                    continue
                parts = os.path.normpath(parsed.path).split(os.sep)
                if hint is not None and hint not in parts:
                    continue
                target = parsed
                break
            if target is None:
                continue        # file not in scanned set; nothing to say
            fn = None
            for node in ast.walk(target.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        node.name == fn_name:
                    fn = node
                    break
            if fn is None:
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=target.path, line=1,
                    message=f"bracket target '{fn_name}' ({origin}) "
                    f"not found in {basename} — update the OBS001 "
                    f"delegation map if it moved"))
                continue
            if not _function_has_bracket(fn):
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=target.path, line=fn.lineno,
                    message=f"'{fn_name}' carries {origin} but no "
                    f"observability bracket — wrap the site in a span/"
                    f"collective guard or add a record_* recorder call "
                    f"so postmortem bundles can name it"))
        return findings
