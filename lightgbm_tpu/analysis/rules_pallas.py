"""Pallas kernel-hygiene rule (device code paths only).

PALLAS001 enforces the two conventions every `pl.pallas_call` site in
this codebase must follow, because both failure modes are silent or
cryptic at the Mosaic level:

1. **Block shapes must be declared.** Every pallas_call must pass
   either a `grid_spec=` (the PrefetchScalarGridSpec form) or both
   `in_specs=` and `out_specs=` BlockSpec declarations. A call without
   them lowers with whole-array blocks — on real shapes that either
   blows the VMEM budget at compile time with an opaque Mosaic error
   or, worse, works on toy tests and OOMs at the bench shape.

2. **Kernel bodies must not close over traced values.** A kernel
   function (or a kernel-factory call) evaluated inside a *jitted*
   function must not capture the jitted function's traced parameters —
   those are tracers at kernel-build time, and Pallas kernels can only
   close over static Python values; traced inputs must flow through
   pallas_call operands so they get a BlockSpec and a VMEM window.
   Kernel *factories* at module scope (`_hist_kernel(nb, f, b, ...)`)
   capture static ints and are the idiomatic pattern — they only fire
   the rule when fed a traced parameter name.

What does NOT fire, by design: nested functions inside jitted code
that are NOT passed to pallas_call (scan/cond bodies legitimately
close over traced values), and factories whose arguments are statics
or locals derived from `static_argnames` parameters.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .engine import Finding, ParsedFile, Rule
from .dataflow import dotted_name as _dotted_name
from .rules_jit import iter_jitted_functions

__all__ = ["PallasKernelRule"]


def _is_pallas_call(node: ast.Call) -> bool:
    name = _dotted_name(node.func)
    return bool(name) and name.split(".")[-1] == "pallas_call"


def _has_block_decls(node: ast.Call) -> bool:
    kws = {kw.arg for kw in node.keywords if kw.arg}
    return "grid_spec" in kws or {"in_specs", "out_specs"} <= kws


def _assigned_names(func: ast.FunctionDef) -> Set[str]:
    """Names bound inside `func` (params, assignments, for-targets,
    comprehension targets, inner defs) — everything that shadows an
    outer-scope capture."""
    names: Set[str] = set()
    a = func.args
    for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)):
        names.add(arg.arg)
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    for node in ast.walk(func):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(node.name)
    return names


def _free_loads(func: ast.FunctionDef) -> Set[str]:
    bound = _assigned_names(func)
    return {node.id for node in ast.walk(func)
            if isinstance(node, ast.Name) and
            isinstance(node.ctx, ast.Load) and node.id not in bound}


class PallasKernelRule(Rule):
    id = "PALLAS001"
    severity = "error"
    doc = ("pl.pallas_call must declare VMEM block shapes (grid_spec= "
           "or in_specs=+out_specs=), and kernels built inside jitted "
           "functions must not close over traced parameters — traced "
           "data reaches a kernel only through pallas_call operands")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        if parsed.tree is None or not parsed.in_device_dir():
            return []
        findings: List[Finding] = []
        calls = [node for node in ast.walk(parsed.tree)
                 if isinstance(node, ast.Call) and _is_pallas_call(node)]
        if not calls:
            return []
        for call in calls:
            if not _has_block_decls(call):
                findings.append(self.finding(
                    parsed, call.lineno,
                    "pallas_call without block-shape declarations: pass "
                    "grid_spec= or both in_specs= and out_specs= (whole-"
                    "array default blocks OOM VMEM at real shapes)"))
        for func, static, _via in iter_jitted_functions(parsed.tree):
            traced = {a.arg for a in (list(func.args.posonlyargs) +
                                      list(func.args.args) +
                                      list(func.args.kwonlyargs))
                      if a.arg not in static}
            if not traced:
                continue
            local_defs = {n.name: n for n in ast.walk(func)
                          if isinstance(n, ast.FunctionDef) and
                          n is not func}
            for call in calls:
                if not self._inside(func, call) or not call.args:
                    continue
                findings.extend(self._check_kernel_arg(
                    parsed, call.args[0], traced, local_defs))
        return findings

    @staticmethod
    def _inside(func: ast.FunctionDef, node: ast.AST) -> bool:
        return any(node is n for n in ast.walk(func))

    def _check_kernel_arg(self, parsed: ParsedFile, kernel: ast.expr,
                          traced: Set[str],
                          local_defs) -> List[Finding]:
        findings: List[Finding] = []
        if isinstance(kernel, ast.Name):
            target: Optional[ast.FunctionDef] = local_defs.get(kernel.id)
            if target is not None:
                for name in sorted(_free_loads(target) & traced):
                    findings.append(self.finding(
                        parsed, target.lineno,
                        f"pallas kernel '{target.name}' closes over "
                        f"traced parameter '{name}' of its jitted "
                        "enclosing function; route it through a "
                        "pallas_call operand with a BlockSpec"))
        elif isinstance(kernel, ast.Call):
            args = list(kernel.args) + [kw.value for kw in kernel.keywords]
            for arg in args:
                if isinstance(arg, ast.Name) and arg.id in traced:
                    findings.append(self.finding(
                        parsed, kernel.lineno,
                        f"kernel factory receives traced parameter "
                        f"'{arg.id}'; factories may only capture static "
                        "values — traced data reaches a kernel through "
                        "pallas_call operands"))
        return findings
