"""Hot-path performance rule (device code paths only).

PERF001 guards the round-6 partition win: the slot-grouped scatter
kernels used to order rows with `jnp.argsort` — O(N log N) work per
level where the blocked-prefix-sum scan partition does O(N) with the
per-slot counts the router already emits (docs/PerfNotes.md round 6,
Parallel Scan on Ascend arXiv:2505.15112).  A sort quietly
reintroduced into any registered device hot-path function would
silently reinstate the old cost at exactly the shapes where it hurts
(N = millions of rows, every tree level), so the manifest below pins
the entry points whose inner loops are row-linear by design.

The rule flags lexical `argsort` calls (``jnp.argsort``,
``jax.numpy.argsort``, ``np.argsort`` — any dotted tail) anywhere
inside a manifest function, including nested helpers (scan/cond
bodies defined inline).  The retained bit-parity oracle branch in
``partition_rows`` carries an explicit line suppression naming
PERF001 — visible, auditable, and the ONLY sanctioned sort on the
partition path.

Functions not in the manifest do not fire: argsort is a fine tool in
host-side setup (bin boundary construction, EFB greedy bundling) where
it runs once per Dataset rather than once per level.

Since the TRACE family landed, PERF001 is the *lexical fallback*: the
authoritative sort-free guarantee is TRACE001, which traces the hot
entries to jaxprs and rejects the `sort` primitive however it was
spelled or wherever the helper lives. PERF001 stays because it is
instant, points at the exact offending source line, and works on code
that does not trace yet.
"""

from __future__ import annotations

import ast
import os
from typing import List

from .dataflow import dotted_name as _dotted_name
from .engine import Finding, ParsedFile, Rule

__all__ = ["PerfHotPathSortRule", "HOT_PATH_MANIFEST"]

#: (module basename, function name) -> registered device hot-path
#: entry points whose whole lexical body must stay sort-free. Nested
#: defs (one_pass, sweep, scan bodies) are covered by their enclosing
#: entry. Kept as an explicit manifest — not "every function in
#: learner/" — so host-side preprocessing keeps its freedom.
HOT_PATH_MANIFEST = {
    ("histogram_pallas.py", "partition_rows"),
    ("histogram_pallas.py", "_stable_order_scan"),
    ("histogram_pallas.py", "build_histograms_scatter"),
    ("histogram_pallas.py", "build_histograms_pallas"),
    ("histogram_mxu.py", "route_rows_mxu"),
    ("histogram_mxu.py", "build_histograms_mxu"),
    ("histogram_mxu.py", "build_histograms_mxu_v2"),
    ("histogram_mxu.py", "fused_route_hist_mxu"),
    ("grower.py", "grow_tree"),
    ("grower_mxu.py", "_make_grow_core"),
    ("grower_mxu.py", "grow_tree_mxu"),
    ("grower_pipeline.py", "_stage"),
    ("grower_pipeline.py", "grow_tree_pipelined"),
}

_SORT_TAILS = ("argsort",)


class PerfHotPathSortRule(Rule):
    """PERF001: `argsort` inside a registered device hot-path
    function."""

    id = "PERF001"
    severity = "error"
    doc = ("O(N log N) `argsort` inside a registered device hot-path "
           "function (HOT_PATH_MANIFEST, rules_perf.py) — the scan "
           "partition made these paths row-linear; route the ordering "
           "through partition_rows(impl='scan') or, for a retained "
           "parity oracle, suppress the exact line (lexical fallback; "
           "TRACE001 checks the traced program)")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        if parsed.tree is None or not parsed.in_device_dir():
            return []
        base = os.path.basename(parsed.path)
        if not any(mod == base for mod, _ in HOT_PATH_MANIFEST):
            return []
        out: List[Finding] = []
        for node in ast.walk(parsed.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if (base, node.name) not in HOT_PATH_MANIFEST:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                name = _dotted_name(sub.func)
                if name and name.split(".")[-1] in _SORT_TAILS:
                    out.append(self.finding(
                        parsed, sub.lineno,
                        f"argsort in device hot path "
                        f"'{node.name}' ({name}): the scan partition "
                        f"keeps this path O(N); see "
                        f"docs/PerfNotes.md round 6"))
        return out
