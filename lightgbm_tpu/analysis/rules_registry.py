"""Registry-consistency rules.

The repo carries three registries whose consumers live in other files:
the ~160-entry config parameter registry (config.py `_PARAMS`) mirrored
in docs/Parameters.md and routed by cli.py, the named fault sites
(reliability/faults.py `KNOWN_SITES`) exercised by tests and documented
in docs/Reliability.md, and the Prometheus metric families emitted by
the observability/serving exporters and documented in
docs/Observability.md. Drift between a registry and its mirrors is
exactly the class of bug that passes every runtime test (nothing
*executes* a doc row) — so these rules diff the registries against
their mirrors structurally.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import Finding, ParsedFile, ProjectContext, ProjectRule

__all__ = [
    "ParamDocsRule", "CliTaskRoutingRule", "ConfigAttrRule",
    "FaultSiteRegistryRule", "PrometheusDocsRule",
]


def _find_file(files: Sequence[ParsedFile],
               basename: str) -> Optional[ParsedFile]:
    for f in files:
        if os.path.basename(f.path) == basename and f.tree is not None:
            return f
    return None


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _collect_params(config: ParsedFile) -> List[Tuple[str, Tuple[str, ...],
                                                      int]]:
    """(name, aliases, lineno) for every `_p(...)` registry entry."""
    out = []
    for node in ast.walk(config.tree):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Name) and
                node.func.id == "_p" and node.args):
            continue
        name = _str_const(node.args[0])
        if name is None:
            continue
        aliases: Tuple[str, ...] = ()
        alias_node = node.args[3] if len(node.args) > 3 else None
        for kw in node.keywords:
            if kw.arg == "aliases":
                alias_node = kw.value
        if isinstance(alias_node, (ast.Tuple, ast.List)):
            aliases = tuple(a for a in
                            (_str_const(e) for e in alias_node.elts)
                            if a is not None)
        out.append((name, aliases, node.lineno))
    return out


class ParamDocsRule(ProjectRule):
    id = "REG001"
    doc = ("config.py `_PARAMS` and docs/Parameters.md must agree: every "
           "param has a doc row with the same alias set, no stale rows, "
           "no duplicate/colliding aliases, matching total count "
           "(regenerate with helpers/generate_parameter_docs.py)")

    _ROW_RE = re.compile(r"^\|\s*`(\w+)`\s*\|[^|]*\|[^|]*\|([^|]*)\|")
    _TOTAL_RE = re.compile(r"Total:\s*(\d+)\s*parameters")

    def check_project(self, files: Sequence[ParsedFile],
                      ctx: ProjectContext) -> List[Finding]:
        config = _find_file(files, "config.py")
        if config is None:
            return []
        findings: List[Finding] = []
        params = _collect_params(config)
        doc = ctx.read_doc("Parameters.md")
        if doc is None:
            findings.append(Finding(
                rule=self.id, severity=self.severity, path=config.path,
                line=1, message="docs/Parameters.md is missing — run "
                "helpers/generate_parameter_docs.py"))
            return findings
        doc_rows: Dict[str, Set[str]] = {}
        for line in doc.splitlines():
            m = self._ROW_RE.match(line.strip())
            if m and m.group(1) != "Parameter":
                cell = m.group(2)
                doc_rows[m.group(1)] = set(re.findall(r"`(\w+)`", cell))
        # param <-> doc row diff
        names = {name for name, _, _ in params}
        for name, aliases, lineno in params:
            if name not in doc_rows:
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=config.path, line=lineno,
                    message=f"param '{name}' has no row in "
                    f"docs/Parameters.md (regenerate the doc)"))
            elif doc_rows[name] != set(aliases):
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=config.path, line=lineno,
                    message=f"param '{name}' alias set drifted from "
                    f"docs/Parameters.md: registry={sorted(aliases)} "
                    f"doc={sorted(doc_rows[name])}"))
        for row in doc_rows:
            if row not in names:
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=config.path, line=1,
                    message=f"docs/Parameters.md documents '{row}' which "
                    f"is not in the config.py registry (stale row)"))
        m = self._TOTAL_RE.search(doc)
        if m and int(m.group(1)) != len(params):
            findings.append(Finding(
                rule=self.id, severity=self.severity, path=config.path,
                line=1,
                message=f"docs/Parameters.md total says {m.group(1)} "
                f"params but the registry has {len(params)}"))
        # alias sanity inside the registry itself
        owner: Dict[str, str] = {}
        for name, aliases, lineno in params:
            for alias in aliases:
                if alias in names:
                    findings.append(Finding(
                        rule=self.id, severity=self.severity,
                        path=config.path, line=lineno,
                        message=f"alias '{alias}' of param '{name}' "
                        f"collides with a canonical param name"))
                elif alias in owner and owner[alias] != name:
                    findings.append(Finding(
                        rule=self.id, severity=self.severity,
                        path=config.path, line=lineno,
                        message=f"alias '{alias}' claimed by both "
                        f"'{owner[alias]}' and '{name}'"))
                else:
                    owner[alias] = name
        return findings


def _task_values_from_config(config: ParsedFile) -> Tuple[Set[str], int]:
    """Allowed `task` values: the `v in (...)` tuple inside the task
    param's check lambda."""
    for node in ast.walk(config.tree):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Name) and
                node.func.id == "_p" and node.args and
                _str_const(node.args[0]) == "task"):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Compare) and \
                    any(isinstance(op, ast.In) for op in sub.ops):
                vals = set()
                for comp in sub.comparators:
                    if isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                        vals |= {v for v in
                                 (_str_const(e) for e in comp.elts)
                                 if v is not None}
                return vals, node.lineno
    return set(), 1


def _task_values_from_cli(cli: ParsedFile) -> Tuple[Set[str], int]:
    """Task values `Application.run` dispatches on: every string
    compared (==/in) against a name called `task` inside run()."""
    for node in ast.walk(cli.tree):
        if isinstance(node, ast.FunctionDef) and node.name == "run":
            vals: Set[str] = set()
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Compare):
                    continue
                names = [n.id for n in ast.walk(sub)
                         if isinstance(n, ast.Name)]
                if "task" not in names:
                    continue
                for comp in sub.comparators:
                    v = _str_const(comp)
                    if v is not None:
                        vals.add(v)
                    elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                        vals |= {v for v in
                                 (_str_const(e) for e in comp.elts)
                                 if v is not None}
            return vals, node.lineno
    return set(), 1


class CliTaskRoutingRule(ProjectRule):
    id = "REG002"
    doc = ("the `task` values accepted by config.py's check and the "
           "branches `cli.Application.run` dispatches on must be the "
           "same set — otherwise a task is accepted but unroutable, or "
           "routable but rejected at config time")

    def check_project(self, files: Sequence[ParsedFile],
                      ctx: ProjectContext) -> List[Finding]:
        config = _find_file(files, "config.py")
        cli = _find_file(files, "cli.py")
        if config is None or cli is None:
            return []
        cfg_vals, cfg_line = _task_values_from_config(config)
        cli_vals, cli_line = _task_values_from_cli(cli)
        if not cfg_vals or not cli_vals:
            return []
        findings = []
        for task in sorted(cfg_vals - cli_vals):
            findings.append(Finding(
                rule=self.id, severity=self.severity, path=config.path,
                line=cfg_line,
                message=f"task '{task}' passes the config check but has "
                f"no dispatch branch in cli.Application.run"))
        for task in sorted(cli_vals - cfg_vals):
            findings.append(Finding(
                rule=self.id, severity=self.severity, path=cli.path,
                line=cli_line,
                message=f"cli.Application.run handles task '{task}' but "
                f"config.py's task check rejects it (dead branch — add "
                f"it to the check or drop the branch)"))
        return findings


def _config_members(config: ParsedFile) -> Set[str]:
    """Names resolvable as attributes of a Config instance: registered
    params, class-level defs, and self.<attr> assignments."""
    members: Set[str] = {name for name, _, _ in _collect_params(config)}
    for node in ast.walk(config.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    members.add(sub.name)
                elif isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for tgt in targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            members.add(tgt.attr)
                        elif isinstance(tgt, ast.Name):
                            members.add(tgt.id)
    return members


class ConfigAttrRule(ProjectRule):
    id = "REG003"
    severity = "error"
    doc = ("attribute access on a `cfg` / `self.config` object must "
           "resolve to a registered parameter or a Config class member "
           "— a typo'd param name silently reads nothing at runtime")

    def check_project(self, files: Sequence[ParsedFile],
                      ctx: ProjectContext) -> List[Finding]:
        config = _find_file(files, "config.py")
        if config is None:
            return []
        members = _config_members(config)
        findings: List[Finding] = []
        for parsed in files:
            if parsed.tree is None or parsed.path == config.path:
                continue
            for node in ast.walk(parsed.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                base = node.value
                is_cfg = isinstance(base, ast.Name) and base.id == "cfg"
                is_self_config = (
                    isinstance(base, ast.Attribute) and
                    base.attr == "config" and
                    isinstance(base.value, ast.Name) and
                    base.value.id == "self")
                if not (is_cfg or is_self_config):
                    continue
                if node.attr.startswith("__") or node.attr in members:
                    continue
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=parsed.path, line=node.lineno,
                    message=f"'{node.attr}' is not a registered config "
                    f"parameter or Config member (typo? register it in "
                    f"config.py _PARAMS)"))
        return findings


def _known_sites(faults: ParsedFile) -> Tuple[Dict[str, int], int]:
    sites: Dict[str, int] = {}
    line = 1
    for node in ast.walk(faults.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "KNOWN_SITES"
                for t in node.targets):
            line = node.lineno
            if isinstance(node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    v = _str_const(elt)
                    if v is not None:
                        sites[v] = elt.lineno
    return sites, line


class FaultSiteRegistryRule(ProjectRule):
    id = "REG004"
    doc = ("every site in reliability/faults.py KNOWN_SITES must be "
           "wired to an injection point in the package, documented in "
           "docs/Reliability.md, and exercised by tests/; every literal "
           "passed to .inject() must be a known site")

    def check_project(self, files: Sequence[ParsedFile],
                      ctx: ProjectContext) -> List[Finding]:
        faults = _find_file(files, "faults.py")
        if faults is None:
            return []
        sites, decl_line = _known_sites(faults)
        if not sites:
            return []
        findings: List[Finding] = []
        # literals used as sites anywhere in the package except faults.py
        wired: Set[str] = set()
        for parsed in files:
            if parsed.tree is None or parsed.path == faults.path:
                continue
            for node in ast.walk(parsed.tree):
                v = _str_const(node)
                if v in sites:
                    wired.add(v)
                # literal .inject("...") args must be known sites
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "inject" and node.args:
                    arg = _str_const(node.args[0])
                    if arg is not None and arg not in sites and \
                            not arg.startswith("env:"):
                        findings.append(Finding(
                            rule=self.id, severity=self.severity,
                            path=parsed.path, line=node.lineno,
                            message=f"inject site '{arg}' is not in "
                            f"KNOWN_SITES (reliability/faults.py) — "
                            f"register it or fix the name"))
        doc = ctx.read_doc("Reliability.md") or ""
        tests = ctx.read_tests()
        for site, line in sorted(sites.items()):
            if site not in wired:
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=faults.path, line=line,
                    message=f"known site '{site}' has no injection "
                    f"point wired anywhere in the package"))
            if site not in doc:
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=faults.path, line=line,
                    message=f"known site '{site}' is not documented in "
                    f"docs/Reliability.md"))
            if tests and site not in tests:
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=faults.path, line=line,
                    message=f"known site '{site}' is never exercised by "
                    f"anything under tests/"))
        return findings


class PrometheusDocsRule(ProjectRule):
    id = "REG005"
    doc = ("every Prometheus metric-family literal (lightgbm_tpu_*) "
           "emitted by an exporter must appear in "
           "docs/Observability.md — dashboards are built from the doc, "
           "an undocumented family is invisible")

    _FAMILY_RE = re.compile(r"^lightgbm_tpu_[a-z0-9_]+$")

    def check_project(self, files: Sequence[ParsedFile],
                      ctx: ProjectContext) -> List[Finding]:
        doc = ctx.read_doc("Observability.md")
        findings: List[Finding] = []
        seen: Set[Tuple[str, str]] = set()
        for parsed in files:
            if parsed.tree is None:
                continue
            for node in ast.walk(parsed.tree):
                v = _str_const(node)
                if v is None or not self._FAMILY_RE.match(v):
                    continue
                key = (parsed.path, v)
                if key in seen:
                    continue
                seen.add(key)
                if doc is None:
                    findings.append(Finding(
                        rule=self.id, severity=self.severity,
                        path=parsed.path, line=node.lineno,
                        message=f"metric family '{v}' emitted but "
                        f"docs/Observability.md is missing"))
                elif v not in doc:
                    findings.append(Finding(
                        rule=self.id, severity=self.severity,
                        path=parsed.path, line=node.lineno,
                        message=f"metric family '{v}' is not documented "
                        f"in docs/Observability.md"))
        return findings
