"""SPMD collective-discipline rules (COLL001-COLL004).

Multihost training is SPMD: every rank runs the same program, and every
collective (`psum`, `all_gather`, `process_allgather`, the package's
own `_allgather_find_mappers` / `mapper_sync` wrappers) is a barrier
all ranks must reach together, the same number of times, with the same
operand shapes. The failure modes are nasty because they are *silent
at the failing rank*: a branch taken on rank-local state routes one
rank around the collective and the peers hang (or, worse, the gather
completes against the wrong rank's data and the model is silently
wrong). PR 7's `stream_bin_parity` bug was exactly this shape — one
rank raised on a rank-local coverage check while its peers sat in the
mapper allgather.

The rules run on the CFG + rank-taint engine in `dataflow.py`:

- **COLL001** — a collective reachable under a rank-divergent branch
  whose other arm does not perform the matching collective (the
  deadlock shape). Also: collectives inside loops with rank-divergent
  trip counts, and `psum(x) if <tainted> else x` expressions.
- **COLL002** — a `raise` guarded by a rank-divergent condition with a
  collective downstream in the same function and no collective
  participation before the raise (the stranded-peer shape). Branching
  on a collective *result* is the sanctioned agreement-sync idiom:
  collective results are rank-uniform, so such guards are not tainted.
- **COLL003** — a rank-variable-shaped operand fed to a fixed-shape
  collective without padding to a static wire shape (`np.pad` and the
  other `dataflow.SHAPE_SANITIZERS` clear the taint).
- **COLL004** — cross-file registry: every function containing a
  collective call must appear in `COLLECTIVE_MANIFEST`, mapping it to
  a fault site (so the reliability harness can kill the collective)
  and to a test file that exercises it by name — new collectives
  cannot land untested.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .engine import Finding, ParsedFile, ProjectContext, ProjectRule, Rule
from .dataflow import (CFG, COLLECTIVE_CALLABLES, RankTaint, call_name,
                       collective_calls, iter_top_functions, stmt_exprs)

__all__ = ["CollectiveBranchRule", "CollectiveRaiseRule",
           "CollectiveShapeRule", "CollectiveRegistryRule",
           "COLLECTIVE_MANIFEST"]


# ---------------------------------------------------------------------------
# shared per-function analysis (memoized: three rules share it)

class _FunctionAnalysis:
    """CFG + taint + guard chains for one top-level function.

    `extra` carries interprocedurally-resolved collective spellings
    (helpers that transitively psum/allgather, from
    callgraph.collective_call_names) — the taint launder, the
    reachability sets and the participate-before check all treat them
    exactly like the base collectives."""

    def __init__(self, fn: ast.FunctionDef, shape_seeds: bool,
                 extra: frozenset = frozenset()):
        self.fn = fn
        self.all_collectives = COLLECTIVE_CALLABLES | extra
        self.extra = extra
        self.cfg = CFG(fn)
        self.taint = RankTaint(fn, shape_seeds=shape_seeds,
                               extra_collectives=extra)
        #: id(stmt) -> chain of (guard stmt, arm statements) from the
        #: outermost enclosing branch/loop inward
        self.guards: Dict[int, Tuple[Tuple[ast.stmt, List[ast.stmt]], ...]] \
            = {}
        self._map_guards(fn.body, ())
        #: CFG node -> collective callee names in the node's OWN exprs
        self.node_collectives: Dict[object, Set[str]] = {}
        for node in self.cfg.nodes:
            names: Set[str] = set()
            for expr in stmt_exprs(node.stmt):
                for sub in ast.walk(expr):
                    if isinstance(sub, ast.Call) and \
                            call_name(sub) in self.all_collectives:
                        names.add(call_name(sub))
            if names:
                self.node_collectives[node] = names

    def _map_guards(self, stmts: Sequence[ast.stmt],
                    chain: Tuple) -> None:
        for stmt in stmts:
            self.guards[id(stmt)] = chain
            if isinstance(stmt, (ast.If, ast.While)):
                arm = chain + (((stmt, stmt.body)),)
                self._map_guards(stmt.body, arm)
                if stmt.orelse:
                    self._map_guards(stmt.orelse,
                                     chain + ((stmt, stmt.orelse),))
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._map_guards(stmt.body, chain + ((stmt, stmt.body),))
                if stmt.orelse:
                    self._map_guards(stmt.orelse, chain)
            else:
                for field in ("body", "orelse", "finalbody"):
                    block = getattr(stmt, field, None)
                    if isinstance(block, list) and block and \
                            isinstance(block[0], ast.stmt):
                        self._map_guards(block, chain)
                for handler in getattr(stmt, "handlers", ()) or ():
                    self._map_guards(handler.body, chain)
                for case in getattr(stmt, "cases", ()) or ():
                    self._map_guards(case.body, chain)

    # -- queries --------------------------------------------------------
    def reach_collectives(self, start) -> Set[str]:
        """Collective names on any path from CFG node `start`."""
        names: Set[str] = set()
        for node in self.cfg.reachable(start):
            names |= self.node_collectives.get(node, set())
        return names

    def stranded_raises(self) -> List[Tuple[ast.stmt, ast.stmt, str]]:
        """COLL002 candidates: (raise stmt, guarding branch, downstream
        collective name)."""
        out: List[Tuple[ast.stmt, ast.stmt, str]] = []
        for node in self.cfg.nodes:
            if node.kind != "raise":
                continue
            r = node.stmt
            chain = self.guards.get(id(r), ())
            tainted = [(g, arm) for g, arm in chain
                       if self.taint.stmt_test_tainted(g)]
            if not tainted:
                continue
            guard, arm = tainted[-1]            # innermost divergent guard
            if self._participates_before(arm, r):
                continue
            gnode = self.cfg.node(guard)
            if gnode is None:
                continue
            downstream: Set[str] = set()
            for nd in self.cfg.reachable(gnode, avoid=node):
                downstream |= self.node_collectives.get(nd, set())
            if downstream:
                out.append((r, guard, sorted(downstream)[0]))
        return out

    def _participates_before(self, arm: Sequence[ast.stmt],
                             raise_stmt: ast.stmt) -> bool:
        """A collective call inside the guarded arm, textually before
        the raise, means this rank joins the barrier before failing
        (the participate-then-raise idiom)."""
        r_line = raise_stmt.lineno
        for stmt in arm:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and \
                        call_name(node) in self.all_collectives and \
                        node.lineno < r_line:
                    return True
        return False


_CACHE: Dict[Tuple[str, int, frozenset], _FunctionAnalysis] = {}


def _extra_collectives(rule: Rule, parsed: ParsedFile) -> frozenset:
    """Interprocedural collective spellings for this file, when the
    analyzer attached callgraph facts to the rule."""
    facts = getattr(rule, "facts", None)
    if facts is None:
        return frozenset()
    return facts.collective_call_names(parsed.path)


def _analyses(parsed: ParsedFile,
              extra: frozenset = frozenset()
              ) -> Iterator[_FunctionAnalysis]:
    """One analysis per top function that contains a collective call
    (base or interprocedurally-resolved)."""
    if parsed.tree is None:
        return
    shape_seeds = not parsed.in_device_dir()
    for fn in iter_top_functions(parsed.tree):
        if not collective_calls(fn, extra):
            continue
        key = (parsed.path, fn.lineno, extra)
        fa = _CACHE.get(key)
        if fa is None or fa.fn is not fn:
            fa = _FunctionAnalysis(fn, shape_seeds, extra)
            _CACHE[key] = fa
        yield fa


# ---------------------------------------------------------------------------

class CollectiveBranchRule(Rule):
    id = "COLL001"
    doc = ("collective call reachable under a rank-divergent branch "
           "whose other arm performs no matching collective — ranks "
           "that take the other path strand their peers in the "
           "barrier; hoist the collective out of the branch or make "
           "the condition an agreement (branch on a collective result)")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        findings: List[Finding] = []
        for fa in _analyses(parsed, _extra_collectives(self, parsed)):
            raise_guards = {id(g) for _, g, _ in fa.stranded_raises()}
            for node in fa.cfg.nodes:
                stmt = node.stmt
                if isinstance(stmt, ast.If) and \
                        fa.taint.expr_tainted(stmt.test):
                    if id(stmt) in raise_guards:
                        continue        # reported as COLL002
                    then_c = fa.reach_collectives(node.succs[0])
                    else_c = fa.reach_collectives(node.succs[1])
                    if then_c != else_c:
                        odd = sorted(then_c ^ else_c)[0]
                        findings.append(self.finding(
                            parsed, stmt.lineno,
                            f"function '{fa.fn.name}': collective "
                            f"'{odd}' is reached on only one arm of a "
                            f"branch on rank-local state — peers on "
                            f"the other arm never enter the barrier"))
                elif isinstance(stmt, (ast.While, ast.For)) and \
                        fa.taint.stmt_test_tainted(stmt):
                    inner = {call_name(c)
                             for c in collective_calls(stmt, fa.extra)}
                    # names in the loop header don't iterate with the body
                    header = set()
                    for expr in stmt_exprs(stmt):
                        for sub in ast.walk(expr):
                            if isinstance(sub, ast.Call) and \
                                    call_name(sub) in fa.all_collectives:
                                header.add(call_name(sub))
                    inner -= header
                    if inner:
                        findings.append(self.finding(
                            parsed, stmt.lineno,
                            f"function '{fa.fn.name}': collective "
                            f"'{sorted(inner)[0]}' inside a loop whose "
                            f"trip count is rank-local — ranks fall "
                            f"out of the barrier after different "
                            f"iteration counts"))
            # conditional-expression form: psum(x) if <tainted> else x
            for node in ast.walk(fa.fn):
                if not isinstance(node, ast.IfExp) or \
                        not fa.taint.expr_tainted(node.test):
                    continue
                then_c = {call_name(c)
                          for c in collective_calls(node.body, fa.extra)}
                else_c = {call_name(c)
                          for c in collective_calls(node.orelse, fa.extra)}
                if then_c != else_c:
                    findings.append(self.finding(
                        parsed, node.lineno,
                        f"function '{fa.fn.name}': conditional "
                        f"expression runs collective "
                        f"'{sorted(then_c ^ else_c)[0]}' on only one "
                        f"arm of a rank-divergent condition"))
        return findings


class CollectiveRaiseRule(Rule):
    id = "COLL002"
    doc = ("raise guarded by a rank-divergent condition with a "
           "collective downstream in the same function — one rank "
           "aborts while its peers block in the barrier (the PR-7 "
           "stream_bin_parity bug shape); allgather an agreement flag "
           "first, or participate in the collective before raising")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        findings: List[Finding] = []
        for fa in _analyses(parsed, _extra_collectives(self, parsed)):
            for r, guard, coll in fa.stranded_raises():
                findings.append(self.finding(
                    parsed, r.lineno,
                    f"function '{fa.fn.name}': raise under "
                    f"rank-divergent condition (line {guard.lineno}) "
                    f"while peers proceed to collective '{coll}' — "
                    f"sync agreement (allgather an error flag) or "
                    f"join the collective before raising"))
        return findings


class CollectiveShapeRule(Rule):
    id = "COLL003"
    doc = ("rank-variable-shaped operand fed to a fixed-shape "
           "collective — gather shapes must be identical on every "
           "rank; pad to a static wire shape (np.pad / np.zeros) and "
           "ship the true length alongside")

    def check(self, parsed: ParsedFile) -> List[Finding]:
        findings: List[Finding] = []
        for fa in _analyses(parsed, _extra_collectives(self, parsed)):
            for call in collective_calls(fa.fn, fa.extra):
                for arg in call.args:
                    if fa.taint.expr_shape_tainted(arg):
                        findings.append(self.finding(
                            parsed, call.lineno,
                            f"function '{fa.fn.name}': operand of "
                            f"collective '{call_name(call)}' has a "
                            f"rank-local shape — pad to the fixed "
                            f"wire shape before gathering"))
                        break
        return findings


# ---------------------------------------------------------------------------
# COLL004: cross-file collective-site registry

#: (file basename, parent-dir hint, function, fault site, coverage mode,
#:  test files that must exercise the function by name).
#: Coverage modes: "body" — the function itself injects the site
#: (literal or registered wrapper, rules_faults.SITE_WRAPPERS);
#: "delegate" — its collectives are calls to other manifest functions;
#: "dispatch" — a device collective whose site fires at the dispatch
#: boundary (rules_faults.DISPATCH_MANIFEST carries the site).
COLLECTIVE_MANIFEST = (
    ("comm.py", "parallel", "guarded_allgather", "collective_psum",
     "body", ("test_watchdog.py", "test_multihost.py")),
    ("comm.py", "parallel", "checkpoint_agree", "collective_psum",
     "delegate", ("test_checkpoint.py", "test_multihost.py")),
    ("basic.py", None, "_allgather_find_mappers", "collective_psum",
     "body", ("test_multihost.py", "test_streaming.py")),
    ("basic.py", None, "_distributed_bin_mappers", "collective_psum",
     "delegate", ("test_multihost.py",)),
    ("basic.py", None, "_streaming_mapper_sync", "collective_psum",
     "delegate", ("test_streaming.py", "test_multihost.py")),
    ("loader.py", "streaming", "build_streamed_dataset",
     "streaming_ingest", "body", ("test_streaming.py",)),
    ("gbdt.py", "boosting", "_setup_train", "collective_psum",
     "body", ("test_multihost.py",)),
    ("gbdt.py", "boosting", "_setup_parallel", "collective_psum",
     "body", ("test_multihost.py",)),
    ("gbdt.py", "boosting", "_sync_renewed_leaves", "collective_psum",
     "body", ("test_multihost.py",)),
    ("gbdt.py", "boosting", "_boost_from_average", "collective_psum",
     "body", ("test_multihost.py",)),
    ("grower.py", "learner", "grow_tree", "collective_psum",
     "dispatch", ("test_distributed.py",)),
    ("grower_mxu.py", "learner", "grow_tree_mxu", "collective_psum",
     "dispatch", ("test_distributed.py",)),
    # the shared growth core both grower drivers trace (the psum sites
    # moved here from grow_tree_mxu's body in the level-pipeline
    # refactor; same fault site, same multihost coverage)
    ("grower_mxu.py", "learner", "_make_grow_core", "collective_psum",
     "dispatch", ("test_distributed.py", "test_level_pipeline.py")),
    ("histogram_mxu.py", "learner", "quantize_gradients",
     "collective_psum", "dispatch",
     ("test_distributed.py", "test_hist_backends.py")),
    ("hist_agg.py", "distributed", "build_feature_shards",
     "distributed_hist_agg", "body", ("test_distributed_learner.py",)),
    ("hist_agg.py", "distributed", "reduce_scatter_hist",
     "collective_psum", "dispatch", ("test_distributed_learner.py",)),
    ("binning.py", "distributed", "merge_streaming_sketch",
     "collective_psum", "delegate", ("test_distributed_learner.py",)),
    # elastic membership (distributed/elastic.py): the epoch-agreement
    # gather and the reshard row-count exchange both delegate to
    # guarded_allgather (the shrink VOTE itself is deliberately NOT a
    # collective — it rides the heartbeat directory because the old
    # world's collectives just failed)
    ("elastic.py", "distributed", "epoch_agree", "collective_psum",
     "delegate", ("test_elastic.py",)),
    ("elastic.py", "distributed", "reshard_offsets", "collective_psum",
     "delegate", ("test_elastic.py",)),
)


class CollectiveRegistryRule(ProjectRule):
    id = "COLL004"
    doc = ("every function containing a collective call must be "
           "registered in rules_spmd.COLLECTIVE_MANIFEST with a fault "
           "site the reliability harness can fire and a test file "
           "that exercises it by name — new collectives cannot land "
           "untested")

    def check_project(self, files: Sequence[ParsedFile],
                      ctx: ProjectContext) -> List[Finding]:
        # fixture isolation: only meaningful when a package root
        # (config.py) is in the scanned set, like the registry rules
        if not any(os.path.basename(f.path) == "config.py"
                   for f in files):
            return []
        findings: List[Finding] = []
        findings += self._check_manifest(files, ctx)
        findings += self._check_discovery(files)
        return findings

    # -- manifest rows --------------------------------------------------
    def _check_manifest(self, files: Sequence[ParsedFile],
                        ctx: ProjectContext) -> List[Finding]:
        from .rules_faults import DISPATCH_MANIFEST, _function_covers_site
        from .rules_registry import _known_sites
        findings: List[Finding] = []
        faults = next(
            (f for f in files
             if os.path.basename(f.path) == "faults.py"
             and f.tree is not None), None)
        known = _known_sites(faults)[0] if faults is not None else None
        dispatch_sites = {site for _, _, site in DISPATCH_MANIFEST}
        manifest_fns = {row[2] for row in COLLECTIVE_MANIFEST}
        for basename, hint, fn_name, site, mode, test_files in \
                COLLECTIVE_MANIFEST:
            target = self._resolve(files, basename, hint)
            if target is None:
                continue        # file not in scanned set; nothing to say
            fn = self._find_fn(target, fn_name)
            if fn is None:
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=target.path, line=1,
                    message=f"collective manifest names '{fn_name}' "
                            f"which does not exist in {basename}"))
                continue
            if known is not None and site not in known:
                findings.append(self.finding(
                    target, fn.lineno,
                    f"collective entry '{fn_name}' maps to unknown "
                    f"fault site '{site}' (not in "
                    f"reliability/faults.py KNOWN_SITES)"))
            if mode == "body" and not _function_covers_site(fn, site):
                findings.append(self.finding(
                    target, fn.lineno,
                    f"collective entry '{fn_name}' declares fault "
                    f"site '{site}' but neither uses the literal nor "
                    f"calls a registered wrapper — the reliability "
                    f"harness cannot kill this collective"))
            elif mode == "delegate" and not any(
                    call_name(c) in manifest_fns
                    for c in collective_calls(fn)):
                findings.append(self.finding(
                    target, fn.lineno,
                    f"collective entry '{fn_name}' is marked "
                    f"delegate but calls no other manifest function"))
            elif mode == "dispatch" and site not in dispatch_sites:
                findings.append(self.finding(
                    target, fn.lineno,
                    f"collective entry '{fn_name}' is marked dispatch "
                    f"but site '{site}' is not in "
                    f"rules_faults.DISPATCH_MANIFEST"))
            named = self._named_in_tests(ctx, fn_name, test_files)
            if named is False:
                findings.append(self.finding(
                    target, fn.lineno,
                    f"collective entry '{fn_name}' is not exercised "
                    f"by name in any of: {', '.join(test_files)}"))
        return findings

    # -- reverse discovery ----------------------------------------------
    def _check_discovery(self, files: Sequence[ParsedFile]
                         ) -> List[Finding]:
        registered = {(row[0], row[2]) for row in COLLECTIVE_MANIFEST}
        findings: List[Finding] = []
        for parsed in files:
            if parsed.tree is None:
                continue
            parts = os.path.normpath(parsed.path).split(os.sep)
            if "analysis" in parts:
                continue        # the analyzer names collectives, by trade
            basename = os.path.basename(parsed.path)
            for fn in iter_top_functions(parsed.tree):
                calls = collective_calls(fn)
                if not calls or (basename, fn.name) in registered:
                    continue
                findings.append(self.finding(
                    parsed, fn.lineno,
                    f"unregistered collective entry point: "
                    f"'{fn.name}' calls "
                    f"'{call_name(calls[0])}' but is not in "
                    f"rules_spmd.COLLECTIVE_MANIFEST (map it to a "
                    f"fault site and a multihost test)"))
        return findings

    # -- helpers ---------------------------------------------------------
    @staticmethod
    def _resolve(files: Sequence[ParsedFile], basename: str,
                 hint: Optional[str]) -> Optional[ParsedFile]:
        for parsed in files:
            if os.path.basename(parsed.path) != basename or \
                    parsed.tree is None:
                continue
            parts = os.path.normpath(parsed.path).split(os.sep)
            if hint is not None and hint not in parts:
                continue
            return parsed
        return None

    @staticmethod
    def _find_fn(parsed: ParsedFile,
                 fn_name: str) -> Optional[ast.FunctionDef]:
        for fn in iter_top_functions(parsed.tree):
            if fn.name == fn_name:
                return fn
        return None

    @staticmethod
    def _named_in_tests(ctx: ProjectContext, fn_name: str,
                        test_files: Sequence[str]) -> Optional[bool]:
        seen_any = False
        for name in test_files:
            path = os.path.join(ctx.tests_dir, name)
            try:
                with open(path, "r") as fh:
                    text = fh.read()
            except OSError:
                continue
            seen_any = True
            if fn_name in text:
                return True
        # no named test file readable (fixture runs): nothing to say
        return False if seen_any else None
