"""TRACE rules: contracts over the *traced* hot path (tracecheck.py).

The AST rules reason about source tokens; these rules reason about the
jaxpr the compiler actually receives. Each entry in
``tracecheck.TRACE_MANIFEST`` is traced under abstract inputs (CPU,
nothing executes) and the resulting program is checked against the
entry's declared contract. A sort routed through a helper module, an
f64 upcast introduced by promotion, a `jax.debug.print` left in a
scan body, a donation that silently stopped aliasing, a Python scalar
baked into the program — all invisible to the lexical rules, all
violations here.

Modes:

- **real**: when the scan set contains the analyzer's own package
  (its ``config.py``), the rules trace the production manifest.
  Findings anchor at each entry's target function definition.
- **fixture**: when a scanned file is named ``trace_manifest.py``, it
  is imported and its ``TRACE_MANIFEST`` / ``WAIVERS`` (and optional
  ``DISPATCH_ROWS``) are checked instead — this is how
  tests/analysis_fixtures/trace_bad/ pins one finding per rule
  without planting violations in the package.

All six rules share one trace pass per run: the first rule to fire
builds the report bundle and stashes it on the ProjectContext; trace
reports are served from the incremental cache (cache.py) when the
entry's dependency files are unchanged.
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .engine import Finding, ParsedFile, ProjectRule
from . import tracecheck

__all__ = [
    "TraceSortFreeRule", "TraceF64Rule", "TraceCallbackRule",
    "TraceDonationRule", "TraceRetraceStableRule",
    "TraceManifestCoverageRule",
]

_FIXTURE_BASENAME = "trace_manifest.py"
_fixture_counter = [0]


class _Bundle:
    """One trace pass: manifest + per-entry reports + anchors."""

    def __init__(self, entries, waivers, dispatch_rows,
                 anchor_of, default_path):
        self.entries = list(entries)
        self.waivers = dict(waivers)
        self.dispatch_rows = list(dispatch_rows)
        self.anchor_of = anchor_of          # entry -> (path, line)
        self.default_path = default_path    # coverage findings anchor
        self.reports: Dict[str, tracecheck.TraceReport] = {}

    def report(self, entry) -> tracecheck.TraceReport:
        rep = self.reports.get(entry.name)
        if rep is None:
            rep = tracecheck.build_report(entry)
            self.reports[entry.name] = rep
        return rep


def _find_def_line(files: Sequence[ParsedFile], rel_file: str,
                   fn_name: str) -> Optional[Tuple[str, int]]:
    suffix = rel_file.replace("/", os.sep)
    for parsed in files:
        if not os.path.normpath(parsed.path).endswith(suffix):
            continue
        if parsed.tree is None:
            return parsed.path, 1
        for node in ast.walk(parsed.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == fn_name:
                return parsed.path, node.lineno
        return parsed.path, 1
    return None


def _load_fixture_manifest(path: str):
    """Import a fixture trace_manifest.py under a unique module name
    (repeated scans in one test process must not alias each other)."""
    _fixture_counter[0] += 1
    name = f"_tpulint_trace_fixture_{_fixture_counter[0]}"
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _entry_key(cache, entry) -> Optional[str]:
    if cache is None:
        return None
    contract = (entry.sort_free, entry.forbid_callbacks, entry.x64_mode,
                entry.donate, entry.stable_over)
    return cache.trace_key(entry.name, entry.deps, repr(contract))


def _bundle(files: Sequence[ParsedFile], ctx) -> Optional[_Bundle]:
    cached = getattr(ctx, "_trace_bundle", "unset")
    if cached != "unset":
        return cached
    bundle = None
    # real mode only for the analyzer's own package — a fixture
    # mini-project shipping a config.py must not trigger production
    # trace builds
    own_pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg_scan = any(
        os.path.basename(f.path) == "config.py"
        and os.path.dirname(os.path.abspath(f.path)) == own_pkg
        for f in files)
    fixture = next((f for f in files
                    if os.path.basename(f.path) == _FIXTURE_BASENAME),
                   None)
    if pkg_scan:
        from .rules_faults import DISPATCH_MANIFEST
        anchors = {}
        for entry in tracecheck.TRACE_MANIFEST:
            hit = _find_def_line(files, entry.target_file,
                                 entry.target_fn)
            anchors[entry.name] = hit or (
                os.path.join(ctx.package_dir, "analysis",
                             "tracecheck.py"), 1)
        bundle = _Bundle(
            tracecheck.TRACE_MANIFEST, tracecheck.WAIVERS,
            [(r[0], r[1], r[2]) for r in DISPATCH_MANIFEST],
            lambda e: anchors[e.name],
            os.path.join(ctx.package_dir, "analysis", "tracecheck.py"))
        cache = getattr(ctx, "lint_cache", None)
        for entry in bundle.entries:
            key = _entry_key(cache, entry)
            hit = cache.get_trace_report(key) if key else None
            if hit is not None:
                bundle.reports[entry.name] = \
                    tracecheck.TraceReport.from_dict(hit)
            else:
                rep = bundle.report(entry)
                if key and rep.error is None:
                    cache.put_trace_report(key, rep.to_dict())
    elif fixture is not None:
        try:
            mod = _load_fixture_manifest(fixture.path)
        except Exception as exc:
            bundle = _Bundle((), {}, (), lambda e: (fixture.path, 1),
                             fixture.path)
            bundle.load_error = f"{type(exc).__name__}: {exc}"
            ctx._trace_bundle = bundle
            return bundle
        rows = getattr(mod, "DISPATCH_ROWS", ())
        bundle = _Bundle(
            getattr(mod, "TRACE_MANIFEST", ()),
            getattr(mod, "WAIVERS", {}), rows,
            lambda e: (fixture.path, e.line or 1), fixture.path)
    ctx._trace_bundle = bundle
    return bundle


class _TraceRule(ProjectRule):
    severity = "error"

    def _anchored(self, bundle, entry, message: str) -> Finding:
        path, line = bundle.anchor_of(entry)
        return Finding(rule=self.id, severity=self.severity, path=path,
                       line=line, message=message)


class TraceSortFreeRule(_TraceRule):
    id = "TRACE001"
    doc = ("traced hot entry contains a `sort` primitive — the semantic "
           "form of PERF001's lexical argsort ban; catches sorts routed "
           "through helpers or alternate spellings (jnp.sort, top_k)")

    def check_project(self, files, ctx) -> List[Finding]:
        bundle = _bundle(files, ctx)
        out: List[Finding] = []
        if bundle is None:
            return out
        for entry in bundle.entries:
            if not entry.sort_free:
                continue
            rep = bundle.report(entry)
            if rep.error is None and rep.has_sort:
                out.append(self._anchored(
                    bundle, entry,
                    f"traced program of '{entry.name}' "
                    f"({entry.target_fn}) contains a `sort` primitive; "
                    f"the entry's contract is sort-free — O(n log n) "
                    f"with poor MXU utilization on the hot path"))
        return out


class TraceF64Rule(_TraceRule):
    id = "TRACE002"
    doc = ("traced hot entry emits strongly-typed float64 values — "
           "f64 runs at a fraction of f32 throughput on TPU and "
           "doubles every buffer it touches")

    def check_project(self, files, ctx) -> List[Finding]:
        bundle = _bundle(files, ctx)
        out: List[Finding] = []
        if bundle is None:
            return out
        for entry in bundle.entries:
            rep = bundle.report(entry)
            if rep.error is None and rep.f64:
                out.append(self._anchored(
                    bundle, entry,
                    f"traced program of '{entry.name}' emits "
                    f"strongly-typed float64 from "
                    f"{', '.join(rep.f64)} — keep the hot path f32"))
        return out


class TraceCallbackRule(_TraceRule):
    id = "TRACE003"
    doc = ("traced hot entry contains a host callback primitive "
           "(pure_callback/io_callback/debug_callback) — each one is a "
           "device->host round trip serializing the dispatch pipeline")

    def check_project(self, files, ctx) -> List[Finding]:
        bundle = _bundle(files, ctx)
        out: List[Finding] = []
        if bundle is None:
            return out
        for entry in bundle.entries:
            if not entry.forbid_callbacks:
                continue
            rep = bundle.report(entry)
            if rep.error is None and rep.callbacks:
                out.append(self._anchored(
                    bundle, entry,
                    f"traced program of '{entry.name}' contains host "
                    f"callback primitive(s) "
                    f"{', '.join(rep.callbacks)} — remove jax.debug/"
                    f"callback calls from the hot path"))
        return out


class TraceDonationRule(_TraceRule):
    id = "TRACE004"
    doc = ("entry declares buffer donation but the lowering records no "
           "input/output aliasing — JAX keeps both buffers silently, "
           "doubling peak memory on the largest arrays")

    def check_project(self, files, ctx) -> List[Finding]:
        bundle = _bundle(files, ctx)
        out: List[Finding] = []
        if bundle is None:
            return out
        for entry in bundle.entries:
            if not entry.donate:
                continue
            rep = bundle.report(entry)
            if rep.error is None and rep.donation_consumed is False:
                out.append(self._anchored(
                    bundle, entry,
                    f"'{entry.name}' declares donation but the lowered "
                    f"program has no input/output aliasing "
                    f"(no {tracecheck._DONATION_MARKER}) — the donated "
                    f"buffer is copied, not reused"))
        return out


class TraceRetraceStableRule(_TraceRule):
    id = "TRACE005"
    doc = ("re-tracing an entry with different values for its "
           "dispatch-stable scalars changed the jaxpr — the scalar is "
           "baked into the program and every new value recompiles")

    def check_project(self, files, ctx) -> List[Finding]:
        bundle = _bundle(files, ctx)
        out: List[Finding] = []
        if bundle is None:
            return out
        for entry in bundle.entries:
            if entry.stable_over is None:
                continue
            rep = bundle.report(entry)
            if rep.error is None and rep.stable is False:
                out.append(self._anchored(
                    bundle, entry,
                    f"'{entry.name}' re-traced with different "
                    f"{entry.stable_over} values yields a different "
                    f"jaxpr — the value is static to the program and "
                    f"each distinct value triggers a recompile"))
        return out


class TraceManifestCoverageRule(_TraceRule):
    id = "TRACE006"
    doc = ("TRACE_MANIFEST integrity: every DISPATCH_MANIFEST device "
           "entry must be covered by a trace entry or waived with a "
           "reason; entries must trace successfully; waivers must not "
           "be stale")

    def check_project(self, files, ctx) -> List[Finding]:
        bundle = _bundle(files, ctx)
        out: List[Finding] = []
        if bundle is None:
            return out

        def at_default(message: str) -> Finding:
            return Finding(rule=self.id, severity=self.severity,
                           path=bundle.default_path, line=1,
                           message=message)

        load_error = getattr(bundle, "load_error", None)
        if load_error is not None:
            return [at_default(
                f"fixture trace manifest failed to import: {load_error}")]
        covered = set()
        for entry in bundle.entries:
            covered.update(tuple(site) for site in entry.covers)
            rep = bundle.report(entry)
            if rep.error is not None:
                out.append(self._anchored(
                    bundle, entry,
                    f"trace entry '{entry.name}' failed to trace: "
                    f"{rep.error} — the contract is unverifiable"))
            elif entry.x64_mode and rep.x64_error is not None:
                out.append(self._anchored(
                    bundle, entry,
                    f"trace entry '{entry.name}' declares "
                    f"x64_mode but the enable_x64 trace failed: "
                    f"{rep.x64_error}"))
        rows = {tuple(r) for r in bundle.dispatch_rows}
        for row in sorted(rows):
            if row not in covered and row not in bundle.waivers:
                out.append(at_default(
                    f"dispatch site {row} is neither covered by a "
                    f"TRACE_MANIFEST entry nor waived in WAIVERS — add "
                    f"a trace entry or a waiver with a reason"))
        for waived in sorted(bundle.waivers):
            if waived not in rows:
                out.append(at_default(
                    f"stale waiver {waived}: no such DISPATCH_MANIFEST "
                    f"row — delete it"))
            elif waived in covered:
                out.append(at_default(
                    f"waiver {waived} is redundant: the site is covered "
                    f"by a TRACE_MANIFEST entry — delete the waiver"))
        return out
