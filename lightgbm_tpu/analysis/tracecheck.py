"""Trace-level contract checking for the device hot path.

The AST rules verify what the source *says*; this module verifies what
the compiler is actually *given*. Every entry in ``TRACE_MANIFEST`` is
a hot entry point traced under abstract inputs (``jax.make_jaxpr`` /
the jit AOT ``.trace`` API) on CPU — no device is touched, nothing
executes — and the resulting jaxpr is asserted against a per-entry
contract (rules_trace.py turns violations into TRACE00x findings):

- **sort-free** (TRACE001): no ``sort`` primitive anywhere in the
  program, including scan/cond/pjit sub-jaxprs. This is the semantic
  version of PERF001's lexical argsort ban — a sort smuggled in through
  any spelling (``jnp.sort``, ``lax.top_k`` lowered via sort, a helper
  module) is caught here.
- **no f64** (TRACE002): entries with ``x64_mode=True`` are traced
  under ``jax.experimental.enable_x64`` and must produce no
  strongly-typed float64 avals (weak-typed Python-float constants are
  fine). With x64 off JAX canonicalizes every aval to 32-bit, so the
  check would be vacuous — entries whose programs cannot trace under
  x64 (i32/i64 branch mismatches in lax.cond carry paths) declare
  ``x64_mode=False`` and keep the default-mode tripwire only.
- **no host callbacks** (TRACE003): no ``pure_callback`` /
  ``io_callback`` / ``debug_callback`` primitives — each one serializes
  the dispatch pipeline on a device->host round trip.
- **donation consumed** (TRACE004): for entries that declare buffer
  donation, the CPU lowering must carry ``tf.aliasing_output`` — JAX
  silently keeps both buffers when a declared donation is unusable,
  doubling peak memory on exactly the largest arrays.
- **retrace stability** (TRACE005): tracing the jitted entry twice
  with different values for its dispatch-stable scalars (iteration
  counter, live-tree count) must yield byte-identical jaxprs. A
  difference means the scalar is baked into the program — one silent
  recompile per distinct value at serve time.

Coverage (TRACE006): every device entry in FAULT001's
``DISPATCH_MANIFEST`` must be covered by a trace entry or explicitly
waived in ``WAIVERS`` with a reason (host-side IO, multihost-only
collective, delegation to a covered entry).

Everything here imports jax lazily and forces
``jax.default_device(cpu)`` around input construction, so the linter
can never wedge an accelerator (the BENCH_r06 tunnel lesson).
tests/test_partition_scan.py and tests/test_level_pipeline.py import
the jaxpr helpers from here so lint and tests assert one predicate.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    "CALLBACK_PRIMITIVES", "TraceEntry", "TraceReport", "TRACE_MANIFEST",
    "WAIVERS", "iter_primitives", "primitive_names",
    "has_sort_primitive", "callback_primitives",
    "strong_f64_primitives", "donation_consumed", "retrace_stable",
    "build_report",
]

#: jaxpr primitive names that are host callbacks
CALLBACK_PRIMITIVES = ("debug_callback", "io_callback", "pure_callback")

_DONATION_MARKER = "tf.aliasing_output"


# ---------------------------------------------------------------------------
# jaxpr walkers (shared with tests — one predicate for lint and pytest)

def _as_jaxpr(obj):
    """Accept a ClosedJaxpr, a Jaxpr, or anything carrying `.jaxpr`."""
    while not hasattr(obj, "eqns") and hasattr(obj, "jaxpr"):
        obj = obj.jaxpr
    return obj


def iter_primitives(jaxpr):
    """Yield every eqn in `jaxpr` and its sub-jaxprs (scan/cond/pjit
    bodies), depth-first."""
    jaxpr = _as_jaxpr(jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            for sub in (val if isinstance(val, (list, tuple)) else (val,)):
                if hasattr(sub, "jaxpr") or hasattr(sub, "eqns"):
                    yield from iter_primitives(sub)


def primitive_names(jaxpr) -> Set[str]:
    return {eqn.primitive.name for eqn in iter_primitives(jaxpr)}


def has_sort_primitive(jaxpr) -> bool:
    """True if any (sub-)jaxpr equation is the `sort` primitive — the
    shared sort-free predicate (TRACE001 and the partition-scan tests)."""
    return any(eqn.primitive.name == "sort"
               for eqn in iter_primitives(jaxpr))


def callback_primitives(jaxpr) -> List[str]:
    """Host-callback primitive names present in the program."""
    return sorted(p for p in primitive_names(jaxpr)
                  if p in CALLBACK_PRIMITIVES)


def strong_f64_primitives(jaxpr) -> List[str]:
    """Primitives emitting a strongly-typed float64 output. Weak-typed
    f64 (bare Python floats before canonicalization) does not count —
    it never survives a binary op against an f32 operand."""
    import numpy as np
    hits: Set[str] = set()
    for eqn in iter_primitives(jaxpr):
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if getattr(aval, "dtype", None) == np.float64 and \
                    not getattr(aval, "weak_type", False):
                hits.add(eqn.primitive.name)
    return sorted(hits)


def donation_consumed(lowered_text: str) -> bool:
    """True when the StableHLO text records an input/output aliasing —
    the only reliable signal that a declared donation was usable."""
    return _DONATION_MARKER in lowered_text


def retrace_stable(jitted, argsets: Sequence,
                   **static_kwargs) -> bool:
    """Trace `jitted` once per argset (same shapes/dtypes, different
    scalar values) and compare jaxpr pretty-prints. Identical text
    means the varied values are not baked into the program — the jit
    cache serves every value with one compile.

    Each argset is either a tuple of positional arguments or a dict of
    keyword arguments (for entry points whose traced inputs are
    keyword-only); dict argsets are merged over `static_kwargs`."""
    texts = []
    for args in argsets:
        if isinstance(args, dict):
            traced = jitted.trace(**{**static_kwargs, **args})
        else:
            traced = jitted.trace(*args, **static_kwargs)
        texts.append(str(traced.jaxpr))
    return all(t == texts[0] for t in texts)


# ---------------------------------------------------------------------------
# manifest machinery

@dataclasses.dataclass
class TraceEntry:
    """One hot entry point plus its contract.

    `build` returns the raw trace materials as a dict with any of:
    ``jaxpr`` (default-mode trace), ``jaxpr_x64`` / ``x64_error``
    (enable_x64 trace, when ``x64_mode``), ``lowered_text`` (when
    ``donate``), ``stable`` (bool, when ``stable_over``). `deps` are
    package-relative source files whose content hashes key the trace
    cache. `line` anchors findings for fixture manifests."""
    name: str
    target_file: str                      # package-relative, findings anchor
    target_fn: str
    build: Callable[[], Dict]
    covers: Tuple[Tuple[str, str, str], ...] = ()
    sort_free: bool = True
    forbid_callbacks: bool = True
    x64_mode: bool = False
    donate: bool = False
    stable_over: Optional[str] = None     # human label of varied scalars
    deps: Tuple[str, ...] = ()
    line: int = 0


@dataclasses.dataclass
class TraceReport:
    """Cacheable result of tracing one entry against its contract."""
    name: str
    prims: List[str] = dataclasses.field(default_factory=list)
    has_sort: bool = False
    callbacks: List[str] = dataclasses.field(default_factory=list)
    f64: List[str] = dataclasses.field(default_factory=list)
    x64_error: Optional[str] = None
    donation_consumed: Optional[bool] = None
    stable: Optional[bool] = None
    error: Optional[str] = None

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "TraceReport":
        return cls(**d)


def build_report(entry: TraceEntry) -> TraceReport:
    """Trace one entry (CPU, abstract inputs, nothing executes) and
    derive the contract-relevant facts."""
    rep = TraceReport(name=entry.name)
    try:
        import jax
    except Exception as exc:            # pragma: no cover - jax is baked in
        rep.error = f"jax unavailable: {exc}"
        return rep
    try:
        with jax.default_device(jax.devices("cpu")[0]):
            mat = entry.build()
    except Exception as exc:
        rep.error = f"{type(exc).__name__}: {exc}"
        return rep
    jaxpr = mat.get("jaxpr")
    if jaxpr is not None:
        prims = primitive_names(jaxpr)
        rep.prims = sorted(prims)
        rep.has_sort = "sort" in prims
        rep.callbacks = sorted(p for p in prims
                               if p in CALLBACK_PRIMITIVES)
    if entry.x64_mode:
        x64 = mat.get("jaxpr_x64")
        if x64 is not None:
            rep.f64 = strong_f64_primitives(x64)
        else:
            rep.x64_error = mat.get(
                "x64_error", "builder returned no jaxpr_x64")
    elif jaxpr is not None:
        # x64-off canonicalizes avals to 32-bit: vacuous by design, but
        # an honest tripwire if the session runs with x64 globally on
        rep.f64 = strong_f64_primitives(jaxpr)
    if "lowered_text" in mat:
        rep.donation_consumed = donation_consumed(mat["lowered_text"])
    if "stable" in mat:
        rep.stable = bool(mat["stable"])
    return rep


# ---------------------------------------------------------------------------
# builders for the real manifest (tiny concrete inputs, CPU only)

def _tiny_dataset():
    import numpy as np
    from ..data import BinnedDataset, Metadata
    rng = np.random.RandomState(0)
    n = 64
    x = rng.randn(n, 3).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    return BinnedDataset.from_raw(x, Metadata(n, label=y), max_bin=15), y


def _tiny_forest(num_models: int = 2, num_nodes: int = 4):
    import jax.numpy as jnp
    from ..learner.grower import TreeArrays

    def mk(value, dtype):
        return jnp.full((num_models, num_nodes), value, dtype)

    return TreeArrays(
        split_feature=mk(0, jnp.int32), threshold_bin=mk(1, jnp.int32),
        default_left=mk(False, bool), is_cat=mk(False, bool),
        cat_bitset=jnp.zeros((num_models, num_nodes, 1), jnp.uint32),
        left=mk(-1, jnp.int32), right=mk(-1, jnp.int32),
        parent=mk(-1, jnp.int32), leaf_value=mk(0.0, jnp.float32),
        sum_grad=mk(0.0, jnp.float32), sum_hess=mk(0.0, jnp.float32),
        count=mk(0.0, jnp.float32), gain=mk(0.0, jnp.float32),
        depth=mk(0, jnp.int32), is_leaf=mk(True, bool),
        num_nodes=jnp.full((num_models,), 1, jnp.int32),
        num_leaves=jnp.full((num_models,), 1, jnp.int32))


def _grower_kwargs(ds):
    from ..learner.split import SplitHyperParams
    return dict(num_leaves=4, max_depth=0,
                hp=SplitHyperParams(min_data_in_leaf=5),
                bmax=int(ds.num_bins.max()), hist_backend="mxu",
                interpret=True)


def _probe_partition_rows() -> Dict:
    import functools
    import jax
    import jax.numpy as jnp
    from ..learner.histogram_pallas import partition_rows
    fn = functools.partial(partition_rows, num_slots=8, row_block=64,
                           impl="scan")
    jaxpr = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((512,), jnp.int32))
    return {"jaxpr": jaxpr}


def _probe_grow_tree_mxu() -> Dict:
    import jax
    import jax.numpy as jnp
    from ..learner.grower_mxu import grow_tree_mxu
    ds, _y = _tiny_dataset()
    kw = _grower_kwargs(ds)
    bins = jnp.asarray(ds.bins)
    n = bins.shape[0]
    shaped = jax.ShapeDtypeStruct((n,), jnp.float32)

    def grow(grad, hess):
        return grow_tree_mxu(
            bins, grad, hess, jnp.ones(n, jnp.float32),
            jnp.ones(ds.num_features, jnp.float32),
            jnp.asarray(ds.num_bins), jnp.asarray(ds.missing_types == 2),
            jnp.asarray(ds.is_categorical), **kw)

    return {"jaxpr": jax.make_jaxpr(grow)(shaped, shaped)}


def _probe_route_rows_mxu() -> Dict:
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64
    from ..learner.histogram_mxu import pack_route_tables, route_rows_mxu
    m_pad, bmax, feats = 8, 16, 3
    zeros_i = jnp.zeros(m_pad, jnp.int32)
    zeros_b = jnp.zeros(m_pad, bool)
    tbl, member = pack_route_tables(
        zeros_b, zeros_i, zeros_i, zeros_b, zeros_b, zeros_i, zeros_i,
        zeros_i, jnp.zeros((m_pad, 1), jnp.uint32), m_pad, bmax)
    feat_tbl = jnp.stack([jnp.full(feats, float(bmax)),
                          jnp.zeros(feats)], axis=1)

    def route(bins, row_node):
        return route_rows_mxu(bins, row_node, tbl, member, feat_tbl,
                              row_block=256, emit_counts=True,
                              num_slots=8, interpret=True)

    s_bins = jax.ShapeDtypeStruct((256, feats), jnp.int8)
    s_rows = jax.ShapeDtypeStruct((256,), jnp.int32)
    out = {"jaxpr": jax.make_jaxpr(route)(s_bins, s_rows)}
    try:
        with enable_x64():
            out["jaxpr_x64"] = jax.make_jaxpr(route)(s_bins, s_rows)
    except Exception as exc:
        out["x64_error"] = f"{type(exc).__name__}: {exc}"
    return out


def _probe_predict_packed() -> Dict:
    import jax
    import jax.numpy as jnp
    from ..serving.multimodel import _packed_fn
    stacked = _tiny_forest()
    fn = _packed_fn()
    zeros2 = jnp.zeros(2, jnp.int32)
    bins = jnp.zeros((32, 4), jnp.int32)
    num_bins = jnp.ones((2, 4), jnp.int32)
    missing = jnp.zeros((2, 4), bool)
    args = (stacked, zeros2, zeros2, 2, bins, num_bins, missing)
    traced = fn.trace(*args, num_outputs=1, row_block=16,
                      row_valid=None)
    # t_real (live-tree count) is deliberately a traced device scalar so
    # rebuilt packs reuse the compiled program — vary it and demand a
    # byte-identical jaxpr (the base trace above doubles as argset 0)
    args_b = (stacked, zeros2, zeros2, 1, bins, num_bins, missing)
    other = fn.trace(*args_b, num_outputs=1, row_block=16,
                     row_valid=None)
    stable = str(traced.jaxpr) == str(other.jaxpr)
    return {"jaxpr": traced.jaxpr, "stable": stable}


def _probe_predict_binned_forest() -> Dict:
    import jax
    import jax.numpy as jnp
    from ..learner.predict import predict_binned_forest
    stacked = _tiny_forest()
    tree_class = jnp.zeros(2, jnp.int32)
    bins = jnp.zeros((32, 4), jnp.int32)
    num_bins = jnp.ones(4, jnp.int32)
    missing = jnp.zeros(4, bool)
    traced = predict_binned_forest.trace(
        stacked, tree_class, bins, num_bins, missing, num_outputs=1)
    return {"jaxpr": traced.jaxpr}


def _probe_fused_train() -> Dict:
    import jax
    import jax.numpy as jnp
    from ..boosting.fused import build_fused_train
    ds, y = _tiny_dataset()
    kw = _grower_kwargs(ds)
    n = ds.bins.shape[0]
    label = jnp.asarray(y)

    class _Objective:
        def get_gradients(self, score):
            return score - label, jnp.ones_like(score)

    run = build_fused_train(
        objective=_Objective(), bins=jnp.asarray(ds.bins),
        cnt_weight=jnp.ones(n, jnp.float32),
        feature_mask_fn=lambda it: jnp.ones(ds.num_features,
                                            jnp.float32),
        num_bins=jnp.asarray(ds.num_bins),
        missing_is_nan=jnp.asarray(ds.missing_types == 2),
        is_cat=jnp.asarray(ds.is_categorical), grower_kwargs=kw,
        shrinkage=0.1, extra_seed=3, needs_rng=False)
    score = jnp.zeros(n, jnp.float32)
    traced = run.trace(score, 0, k=2)
    # it0 (global iteration offset) must not bake into the program —
    # the base trace above doubles as retrace argset 0
    other = run.trace(score, 7, k=2)
    stable = str(traced.jaxpr) == str(other.jaxpr)
    lowered = traced.lower().as_text()
    return {"jaxpr": traced.jaxpr, "stable": stable,
            "lowered_text": lowered}


# ---------------------------------------------------------------------------
# the manifest

_GROW_DEPS = ("learner/grower_mxu.py", "learner/histogram_mxu.py",
              "learner/histogram_pallas.py", "learner/split.py",
              "learner/grower.py", "data.py")

TRACE_MANIFEST: Tuple[TraceEntry, ...] = (
    TraceEntry(
        name="partition_rows_scan",
        target_file="learner/histogram_pallas.py",
        target_fn="partition_rows",
        build=_probe_partition_rows,
        deps=("learner/histogram_pallas.py",),
    ),
    TraceEntry(
        name="grow_tree_mxu",
        target_file="learner/grower_mxu.py",
        target_fn="grow_tree_mxu",
        build=_probe_grow_tree_mxu,
        covers=(("gbdt.py", "_grow", "histogram_build"),),
        # the cond-pass carry mixes i32 node counters with i64 under
        # x64; the grow program is x64-off by construction
        x64_mode=False,
        deps=_GROW_DEPS,
    ),
    TraceEntry(
        name="route_rows_mxu",
        target_file="learner/histogram_mxu.py",
        target_fn="route_rows_mxu",
        build=_probe_route_rows_mxu,
        x64_mode=True,
        deps=("learner/histogram_mxu.py",),
    ),
    TraceEntry(
        name="predict_packed_forest",
        target_file="serving/multimodel.py",
        target_fn="_predict_packed_impl",
        build=_probe_predict_packed,
        covers=(("multimodel.py", "dispatch_pack",
                 "serving_pack_predict"),),
        stable_over="t_real (live-tree count)",
        deps=("serving/multimodel.py", "learner/predict.py",
              "learner/grower.py"),
    ),
    TraceEntry(
        name="predict_binned_forest",
        target_file="learner/predict.py",
        target_fn="predict_binned_forest",
        build=_probe_predict_binned_forest,
        covers=(("engine.py", "predict_raw", "serving_device_predict"),),
        deps=("learner/predict.py", "learner/grower.py"),
    ),
    TraceEntry(
        name="fused_train_run",
        target_file="boosting/fused.py",
        target_fn="build_fused_train",
        build=_probe_fused_train,
        covers=(("gbdt.py", "train_many_dispatch", "fused_dispatch"),),
        donate=True,
        stable_over="it0 (iteration offset)",
        deps=_GROW_DEPS + ("boosting/fused.py",),
    ),
)

#: DISPATCH_MANIFEST rows with no device program to trace — each waiver
#: names why. TRACE006 flags any row that is neither covered nor here.
WAIVERS: Dict[Tuple[str, str, str], str] = {
    ("gbdt.py", "_grow", "collective_psum"):
        "multi-device psum across the mesh; COLL004's manifest and the "
        "distributed chaos tier own this barrier — no single-host "
        "abstract trace exists",
    ("replicas.py", "dispatch", "serving_replica_predict"):
        "routing shim; delegates to predict_raw, covered by the "
        "predict_binned_forest entry",
    ("server.py", "hot_swap", "serving_hot_swap"):
        "host-side registry mutation, no device program",
    ("server.py", "hot_swap", "serving_hot_swap_commit"):
        "host-side registry mutation, no device program",
    ("checkpoint.py", "save_checkpoint", "checkpoint_io"):
        "host filesystem IO, no device program",
    ("loader.py", "_ingest_chunk_step", "streaming_ingest"):
        "host-side fault hook around chunk ingest, no device program",
    ("trainer.py", "_publish", "loop_publish"):
        "host-side atomic publish into the serving registry",
    ("comm.py", "guarded_allgather", "collective_psum"):
        "multihost collective; requires a live mesh, watchdog-bracketed "
        "and chaos-tested instead",
    ("hist_agg.py", "build_feature_shards", "distributed_hist_agg"):
        "multihost reduce-scatter; requires a live mesh",
    ("elastic.py", "propose_shrink", "elastic_resize"):
        "host-side membership vote over the heartbeat directory",
}
