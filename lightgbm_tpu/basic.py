"""User-facing Dataset and Booster (reference python-package/lightgbm/basic.py).

The reference reaches the C++ core through ctypes over the 80-function C API
(c_api.h:53-1361); here `Booster` drives the JAX boosting core directly —
there is no FFI hop, but the public surface mirrors basic.py:
`Dataset(data, label, ...)` with lazy construction (basic.py:1163
_lazy_init) and `Booster(params, train_set)` (basic.py:2594) with
update/eval/predict/save_model/feature_importance.
"""

from __future__ import annotations

import io
import json
from typing import Any, Dict, List, Optional, Union
from typing import Sequence as TypingSequence

import numpy as np

from .binning import BinMapper
from .config import Config, param_dict_to_config
from .data import BinnedDataset, Metadata
from .metrics import METRIC_ALIASES, create_metric
from .objectives import create_objective
from .utils.log import Log, LightGBMError
from .utils.file_io import open_file

__all__ = ["Dataset", "Booster", "LightGBMError"]


class Sequence:
    """Generic row-chunk provider for streamed Dataset construction
    (reference lightgbm.Sequence, basic.py; the C path is ChunkedArray +
    LGBM_DatasetPushRows). Subclasses implement __len__ and
    __getitem__ supporting slices returning 2-D row blocks; batch_size
    bounds how many rows are materialized at once."""

    batch_size = 4096

    def __len__(self):  # pragma: no cover - interface
        raise NotImplementedError

    def __getitem__(self, idx):  # pragma: no cover - interface
        raise NotImplementedError


def _is_chunked(data) -> bool:
    """list of row chunks (2-D arrays / Sequences) or a single Sequence:
    the streamed construction path."""
    if isinstance(data, Sequence):
        return True
    if isinstance(data, list) and data and not isinstance(data[0], list):
        return all(
            isinstance(c, Sequence) or
            (hasattr(c, "ndim") and getattr(c, "ndim", 0) == 2)
            for c in data)
    return False


def _is_sparse(data) -> bool:
    """scipy sparse matrix/array, duck-typed (no hard scipy import)."""
    return hasattr(data, "tocsc") and hasattr(data, "nnz")


def _is_pandas_df(data) -> bool:
    return hasattr(data, "dtypes") and hasattr(data, "columns") and \
        hasattr(data, "select_dtypes")


def _data_from_pandas(df, pandas_categorical=None):
    """DataFrame -> (f64 matrix, feature names, categorical column
    indices, pandas_categorical). Mirrors the reference's
    _data_from_pandas (basic.py:541-624): category-dtype columns are
    encoded as their category codes; the per-column category lists are
    remembered (training) or applied (prediction, so codes follow the
    TRAINING ordering regardless of the frame's own categories);
    unseen categories / NaN become NaN."""
    cat_cols = [str(c) for c in df.select_dtypes(
        include=["category"]).columns]
    names = [str(c) for c in df.columns]
    if pandas_categorical is None:   # training
        # .tolist() yields native python scalars so the model-file JSON
        # round-trips int/float categories exactly (np.int64 would
        # stringify and never match at predict time)
        pandas_categorical = [df[c].cat.categories.tolist()
                              for c in cat_cols]
    else:                            # prediction with a trained model
        if len(cat_cols) != len(pandas_categorical):
            raise ValueError(
                "train and valid dataset categorical_feature do not "
                "match.")
    df = df.copy(deep=False)
    for col, cats in zip(cat_cols, pandas_categorical):
        codes = df[col].cat.set_categories(cats).cat.codes
        df[col] = np.where(codes.values < 0, np.nan,
                           codes.values.astype(np.float64))
    X = np.ascontiguousarray(
        df.astype(np.float64).values, dtype=np.float64)
    cat_idx = [names.index(c) for c in cat_cols]
    return X, names, cat_idx, pandas_categorical


def _to_2d_float(data) -> np.ndarray:
    if hasattr(data, "values") and not isinstance(data, np.ndarray):
        data = data.values  # pandas
    if _is_sparse(data):
        return np.ascontiguousarray(data.toarray(), dtype=np.float64)
    arr = np.asarray(data)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    if hasattr(arr, "toarray"):
        arr = arr.toarray()
    return np.ascontiguousarray(arr, dtype=np.float64)


def _numeric_2d_view(data) -> Optional[np.ndarray]:
    """All-numeric input that can skip `_to_2d_float`'s full float64
    copy: already a 2-D float ndarray (or memmap). Binning reads f32
    natively (cext) and casts chunk-wise otherwise, so these route
    through the streaming spine zero-copy (docs/Streaming.md)."""
    if isinstance(data, np.ndarray) and data.ndim == 2 and \
            data.dtype in (np.float32, np.float64) and data.shape[0] > 0:
        return data
    return None


def _load_svmlight_or_csv(path: str) -> np.ndarray:
    """Minimal text loader: CSV/TSV with optional label in first column.
    (Reference Parser auto-detect, src/io/parser.cpp.)"""
    with open_file(path) as fh:
        first = fh.readline()
    delim = "\t" if "\t" in first else ","
    with open_file(path) as fh:
        return np.loadtxt(fh, delimiter=delim)


def _sample_chunked_rows(chunks, take: int, seed: int) -> np.ndarray:
    """Materialize a row sample from a list of chunks/Sequences without
    loading more than one batch window at a time (the streamed analog of
    the reference's pre-allgather sampling, dataset_loader.cpp:722)."""
    lens = [len(c) if not hasattr(c, "shape") else c.shape[0]
            for c in chunks]
    offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    num_data = int(offsets[-1])
    rng = np.random.RandomState(seed)
    if num_data <= take:
        idx = np.arange(num_data)
    elif num_data > 4 * take:
        idx = np.unique(rng.randint(0, num_data, size=take))
    else:
        idx = np.sort(rng.choice(num_data, size=take, replace=False))
    parts = []
    for ci in range(len(chunks)):
        sel = idx[(idx >= offsets[ci]) & (idx < offsets[ci + 1])]
        if len(sel) == 0:
            continue
        local = sel - offsets[ci]
        step = getattr(chunks[ci], "batch_size", 65536) or 65536
        for lo in range(0, lens[ci], step):
            hi = min(lo + step, lens[ci])
            sel_b = local[(local >= lo) & (local < hi)]
            if len(sel_b) == 0:
                continue
            block = np.asarray(chunks[ci][lo:hi], dtype=np.float64)
            parts.append(block.reshape(hi - lo, -1)[sel_b - lo])
    return np.concatenate(parts, axis=0)


def _multihost_process_count() -> int:
    import jax
    try:
        return jax.process_count()
    except RuntimeError:
        return 1


def _allgather_find_mappers(sample, cfg, cat, sparse_in=False):
    """Collective half of distributed bin finding: every rank ships an
    equal-size subsample of its local `sample` rows via allgather and
    all ranks derive IDENTICAL BinMappers from the union — the TPU form
    of the reference's per-rank FindBin + Allgather of serialized
    mappers (dataset_loader.cpp:722-807). Must be called by every rank
    at the same program point.

    `sample=None` signals that this rank failed rank-local validation
    (e.g. its stream partition was empty): the rank still joins the
    agreement gather below, and then EVERY rank raises the same error.
    That agreement-before-data protocol is what makes rank-local
    failure safe here — a bare raise before the row allgather would
    strand peers in the collective (tpulint COLL002, the PR-7
    stream_bin_parity bug shape)."""
    import jax
    from .binning import find_bin_mappers
    from .parallel.comm import guarded_allgather
    from .reliability.watchdog import maybe_start_watchdog
    maybe_start_watchdog(cfg)
    nproc = jax.process_count()
    # agreement sync: gather one ok-flag per rank before any rank ships
    # rows, so validation failure is raised identically everywhere
    ok = np.asarray(0 if sample is None else 1, np.int64)
    oks = guarded_allgather(ok, label="bin_mapper_agree").reshape(-1)
    if int(oks.min(initial=1)) == 0:
        bad = [r for r in range(oks.shape[0]) if int(oks[r]) == 0]
        raise LightGBMError(
            f"distributed bin finding: rank(s) {bad} produced no "
            f"sample rows (empty partition?) — all ranks abort "
            f"together")
    per = max(1, cfg.bin_construct_sample_cnt // nproc)
    n_local = sample.shape[0]
    # variable-size sample gather with fixed wire shapes: every rank
    # ships `per` rows (zero-padded) plus its true count, and the
    # padding is stripped after the gather — the reference's
    # variable-size mapper allgather (dataset_loader.cpp:722-807)
    n_samp = min(per, n_local)
    if n_local > n_samp:
        rng = np.random.RandomState(cfg.data_random_seed)
        idx = np.sort(rng.choice(n_local, size=n_samp, replace=False))
        sample = sample[idx]
    else:
        sample = sample[:n_samp]
    if sparse_in:
        sample = sample.toarray()  # densify the sample rows only
    sample = np.ascontiguousarray(sample, dtype=np.float64)
    if n_samp < per:
        sample = np.pad(sample, ((0, per - n_samp), (0, 0)))
    sizes = guarded_allgather(np.asarray(n_samp, np.int64),
                              label="bin_mapper_sizes")
    gathered = guarded_allgather(sample, label="bin_mapper_rows")
    union = np.concatenate(
        [gathered[r, :int(sizes[r])] for r in range(nproc)])
    return find_bin_mappers(
        union, max_bin=cfg.max_bin, min_data_in_bin=cfg.min_data_in_bin,
        sample_cnt=len(union), use_missing=cfg.use_missing,
        zero_as_missing=cfg.zero_as_missing, categorical_features=cat,
        seed=cfg.data_random_seed)


def _distributed_bin_mappers(X, cfg, cat, sparse_in):
    """Multi-machine bin finding over local random-access data: sample
    locally, then `_allgather_find_mappers`. Returns None
    single-process."""
    if _multihost_process_count() <= 1:
        return None
    import jax
    nproc = jax.process_count()
    per = max(1, cfg.bin_construct_sample_cnt // nproc)
    chunked = not (hasattr(X, "shape") or _is_sparse(X))
    if chunked:
        # streamed input: sample rows out of the local chunk iterator and
        # allgather exactly like the array path — the reference's
        # distributed loader samples from any local iterator the same way
        # (dataset_loader.cpp:722-807 sample-then-allgather)
        X = _sample_chunked_rows(X, per, cfg.data_random_seed)
        sparse_in = False
    return _allgather_find_mappers(X, cfg, cat, sparse_in)


def _streaming_mapper_sync(cfg, cat):
    """Multihost hook for pure streams (no random-access `.array`): the
    loader hands each rank's pass-1 sketch sample to this closure, which
    runs the same allgather the array path uses, so every rank freezes
    IDENTICAL bin boundaries before the collective histogram psum.
    Returns None single-process (the loader then bins locally).

    Resolution goes through `distributed.binning.distributed_mapper_sync`
    (sketch telemetry + the documented distributed-binning entry point);
    the fallback below keeps the delegate target explicit for the
    collective manifest: the closure ultimately runs
    `_allgather_find_mappers(sample, cfg, cat)` either way."""
    from .distributed.binning import distributed_mapper_sync
    sync = distributed_mapper_sync(cfg, cat)
    if sync is None and _multihost_process_count() > 1:
        return lambda sample: _allgather_find_mappers(sample, cfg, cat)
    return sync


class Dataset:
    """Lazily-constructed binned dataset (reference basic.py:1163)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._binned: Optional[BinnedDataset] = None
        self.used_indices = None

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._binned is not None:
            return self
        cfg = param_dict_to_config(self.params)
        data = self.data
        if isinstance(data, str):
            if BinnedDataset.is_binary_file(data):
                # binary fast path (reference LoadFromBinFile,
                # dataset_loader.cpp:274): skip parsing + bin finding;
                # constructor-arg metadata overrides what the cache stored
                self._binned = BinnedDataset.load_binary(data)
                if self.reference is not None:
                    # a cached valid set must share the training dataset's
                    # bin mappers (reference Dataset::CheckAlign via
                    # LGBM_BoosterAddValidData: "different bin mappers
                    # with training data")
                    self.reference.construct()
                    ref = self.reference._binned
                    same = (
                        ref.num_total_features ==
                        self._binned.num_total_features and
                        np.array_equal(ref.used_features,
                                       self._binned.used_features) and
                        all(a.to_dict() == b.to_dict() for a, b in
                            zip(ref.mappers, self._binned.mappers)))
                    if not same:
                        raise ValueError(
                            "Cannot use binary dataset file as validation "
                            "data: it has different bin mappers than the "
                            "training data. Re-save it with "
                            "reference=<train dataset>.")
                md = self._binned.metadata
                self._binned.metadata = Metadata(
                    self._binned.num_data,
                    label=self.label if self.label is not None else md.label,
                    weight=self.weight if self.weight is not None
                    else md.weight,
                    group=np.asarray(self.group) if self.group is not None
                    else md.query_boundaries,
                    init_score=self.init_score
                    if self.init_score is not None else md.init_score)
                if self.free_raw_data:
                    self.data = None
                return self
            if cfg.stream_input:
                # out-of-core route: never materialize the text file —
                # chunks stream through the two-pass loader instead
                from .streaming import source_from_path
                # the raw label_column spec (index, digit string, or
                # name:) resolves per source format inside
                # source_from_path — Parquet maps it to a schema column
                lc = cfg.label_column if cfg.label_column else 0
                data = source_from_path(
                    data, chunk_rows=int(cfg.stream_chunk_rows),
                    label_col=None if self.label is not None else lc,
                    header=bool(cfg.header))
            else:
                raw = _load_svmlight_or_csv(data)
                if self.label is None:
                    self.label, raw = raw[:, 0], raw[:, 1:]
                data = raw
        from .streaming import ChunkSource
        stream_src = data if isinstance(data, ChunkSource) else None
        chunked_in = stream_src is None and _is_chunked(data)
        if chunked_in:
            data = [data] if isinstance(data, Sequence) else data
        sparse_in = stream_src is None and not chunked_in and \
            _is_sparse(data)
        pandas_cat = None
        pandas_cat_idx: List[int] = []
        if chunked_in:
            X = data  # row chunks; streamed two-pass construction
            names_from_df = None
        elif _is_pandas_df(data):
            # category-dtype columns: codes + remembered category lists
            # (reference basic.py:541-624); round-trips through the
            # model file's pandas_categorical JSON. Valid sets encode
            # with the TRAINING dataset's category order.
            ref_pc = None
            if self.reference is not None:
                self.reference.construct()
                ref_pc = getattr(self.reference._binned,
                                 "pandas_categorical", None)
            X, df_names, pandas_cat_idx, pandas_cat = \
                _data_from_pandas(data, ref_pc)
            names_from_df = df_names
        elif stream_src is not None:
            X = stream_src
            names_from_df = None
        else:
            # sparse stays sparse through binning (reference SparseBin /
            # __init_from_csr): only the uint8 bin matrix is densified
            if sparse_in:
                X = data
            else:
                X = None if cfg.linear_tree else _numeric_2d_view(data)
                if X is None:
                    X = _to_2d_float(data)
            names_from_df = None
        names: Optional[List[str]] = None
        if self.feature_name != "auto" and self.feature_name is not None:
            names = list(self.feature_name)
        elif names_from_df is not None:
            names = names_from_df
        elif hasattr(self.data, "columns"):
            names = [str(c) for c in self.data.columns]
        cat: List[int] = []
        if self.categorical_feature != "auto" and self.categorical_feature:
            for c in self.categorical_feature:
                if isinstance(c, str):
                    if names and c in names:
                        cat.append(names.index(c))
                else:
                    cat.append(int(c))
        elif cfg.categorical_feature:
            cat = [int(c) for c in str(cfg.categorical_feature).split(",")
                   if c != ""]
        elif pandas_cat_idx:
            cat = list(pandas_cat_idx)  # 'auto': category-dtype columns
        if stream_src is None and not chunked_in and not sparse_in and \
                not cfg.linear_tree and _numeric_2d_view(X) is not None:
            # all-numeric in-memory input rides the same ChunkSource
            # spine as disk streams — zero-copy row slices instead of a
            # separate whole-matrix float64 copy path
            from .streaming import ArraySource
            stream_src = ArraySource(X,
                                     chunk_rows=int(cfg.stream_chunk_rows))
        if stream_src is not None:
            self._binned = self._construct_streamed(
                stream_src, cfg, cat, names)
            self._binned.pandas_categorical = pandas_cat
            if self.free_raw_data:
                self.data = None
            return self
        construct_binned = (
            BinnedDataset.from_chunks if chunked_in
            else BinnedDataset.from_sparse if sparse_in
            else BinnedDataset.from_raw)
        n_rows = sum(len(c) for c in X) if chunked_in else X.shape[0]
        label = None if self.label is None else \
            np.asarray(self.label, dtype=np.float32).reshape(-1)
        md = Metadata(n_rows, label=label,
                      weight=None if self.weight is None else
                      np.asarray(self.weight, np.float32),
                      group=None if self.group is None else
                      np.asarray(self.group),
                      init_score=None if self.init_score is None else
                      np.asarray(self.init_score))
        ref_mappers: Optional[List[BinMapper]] = None
        if self.reference is not None:
            self.reference.construct()
            ref = self.reference._binned
            # align: valid sets reuse the training BinMappers
            # (reference LoadFromFileAlignWithOtherDataset,
            # dataset_loader.cpp:299)
            full = [None] * ref.num_total_features
            for j, f in enumerate(ref.used_features):
                full[int(f)] = ref.mappers[j]
            trivial = BinMapper()
            ref_mappers = [m if m is not None else trivial for m in full]
            self._binned = construct_binned(
                X, md, max_bin=cfg.max_bin,
                min_data_in_bin=cfg.min_data_in_bin,
                mappers=ref_mappers, feature_names=names,
                feature_pre_filter=False, keep_raw=cfg.linear_tree)
            # keep only the reference's used features
            keep = ref.used_features
            self._binned = BinnedDataset(
                self._binned.bins[:, keep], [ref_mappers[int(f)] for f in keep],
                keep, ref.num_total_features, md, names,
                raw=None if self._binned.raw is None
                else self._binned.raw[:, keep])
        else:
            dist_mappers = _distributed_bin_mappers(X, cfg, cat, sparse_in)
            self._binned = construct_binned(
                X, md, max_bin=cfg.max_bin,
                min_data_in_bin=cfg.min_data_in_bin,
                sample_cnt=cfg.bin_construct_sample_cnt,
                use_missing=cfg.use_missing,
                zero_as_missing=cfg.zero_as_missing,
                categorical_features=cat, seed=cfg.data_random_seed,
                feature_names=names,
                feature_pre_filter=cfg.feature_pre_filter,
                keep_raw=cfg.linear_tree, mappers=dist_mappers,
                pre_filter_with_mappers=dist_mappers is not None)
        self._binned.pandas_categorical = pandas_cat
        if self.free_raw_data:
            self.data = None
        return self

    def _construct_streamed(self, source, cfg, cat, names):
        """Two-pass construction over a ChunkSource (streaming/loader):
        the out-of-core route for disk streams and the zero-copy route
        for in-memory numeric arrays. Covering sketches reproduce the
        in-memory bin mappers bit-for-bit (docs/Streaming.md)."""
        from .streaming import build_streamed_dataset
        kwargs = dict(
            label=None if self.label is None else
            np.asarray(self.label, dtype=np.float32).reshape(-1),
            weight=None if self.weight is None else
            np.asarray(self.weight, np.float32),
            group=None if self.group is None else np.asarray(self.group),
            init_score=None if self.init_score is None else
            np.asarray(self.init_score),
            max_bin=cfg.max_bin, min_data_in_bin=cfg.min_data_in_bin,
            sample_cnt=cfg.bin_construct_sample_cnt,
            use_missing=cfg.use_missing,
            zero_as_missing=cfg.zero_as_missing,
            categorical_features=cat, seed=cfg.data_random_seed,
            feature_names=names,
            sample_rows=int(cfg.stream_sample_rows),
            bin_parity=bool(cfg.stream_bin_parity))
        if self.reference is not None:
            # align: valid sets reuse the training BinMappers and bin
            # exactly its used columns (reference
            # LoadFromFileAlignWithOtherDataset, dataset_loader.cpp:299)
            self.reference.construct()
            ref = self.reference._binned
            full = [None] * ref.num_total_features
            for j, f in enumerate(ref.used_features):
                full[int(f)] = ref.mappers[j]
            trivial = BinMapper()
            ref_mappers = [m if m is not None else trivial for m in full]
            return build_streamed_dataset(
                source, mappers=ref_mappers, feature_pre_filter=False,
                used_override=np.asarray(ref.used_features, np.int32),
                **kwargs)
        dist = None
        sync = None
        if source.array is not None:
            dist = _distributed_bin_mappers(source.array, cfg, cat, False)
        else:
            # pure stream (no random-access matrix): the loader's pass-1
            # sketch sample feeds this collective so every rank freezes
            # identical boundaries; None single-process
            sync = _streaming_mapper_sync(cfg, cat)
        return build_streamed_dataset(
            source, mappers=dist, mapper_sync=sync,
            feature_pre_filter=cfg.feature_pre_filter,
            pre_filter_with_mappers=dist is not None,
            checkpoint_dir=cfg.checkpoint_dir or None, **kwargs)

    # ------------------------------------------------------------------
    def num_data(self) -> int:
        self.construct()
        return self._binned.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._binned.num_total_features

    def get_label(self):
        if self.label is not None:
            return np.asarray(self.label)
        if self._binned is not None:
            return self._binned.metadata.label
        return None

    def get_weight(self):
        return self.weight

    def get_group(self):
        return self.group

    def get_init_score(self):
        return self.init_score

    def set_label(self, label):
        self.label = label
        if self._binned is not None:
            self._binned.metadata.label = np.asarray(
                label, np.float32).reshape(-1)
        return self

    def set_weight(self, weight):
        self.weight = weight
        if self._binned is not None and weight is not None:
            self._binned.metadata.weight = np.asarray(weight, np.float32)
        return self

    def set_group(self, group):
        self.group = group
        if self._binned is not None and group is not None:
            self._binned.metadata.__init__(
                self._binned.num_data, self._binned.metadata.label,
                self._binned.metadata.weight, np.asarray(group),
                self._binned.metadata.init_score)
        return self

    def set_init_score(self, init_score):
        self.init_score = init_score
        # a user-provided score replaces any continuation seed, so the
        # init_model double-count guard must see it as user-owned again
        self._seeded_init_score = False
        if self._binned is not None:
            self._binned.metadata.init_score = None if init_score is None \
                else np.asarray(init_score, np.float32)
        return self

    def set_field(self, name, data):
        return {"label": self.set_label, "weight": self.set_weight,
                "group": self.set_group,
                "init_score": self.set_init_score}[name](data)

    def get_field(self, name):
        return {"label": self.get_label, "weight": self.get_weight,
                "group": self.get_group,
                "init_score": self.get_init_score}[name]()

    def subset(self, used_indices: TypingSequence[int], params=None) -> "Dataset":
        self.construct()
        sub = Dataset(None, params=params or self.params)
        sub._binned = self._binned.subset(np.asarray(used_indices))
        sub._binned.pandas_categorical = getattr(
            self._binned, "pandas_categorical", None)
        sub.reference = self
        return sub

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        """Validation Dataset aligned with this one's bin mappers
        (reference basic.py Dataset.create_valid; the C path is
        LoadFromFileAlignWithOtherDataset, dataset_loader.cpp:299)."""
        return Dataset(data, label=label, weight=weight, group=group,
                       init_score=init_score, reference=self,
                       params=params or self.params,
                       free_raw_data=self.free_raw_data)

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        """Change the categorical features (reference basic.py
        set_categorical_feature, :2092-2100): after construction the
        binned data is dropped and lazily rebuilt — possible only while
        the raw data is retained (free_raw_data=False)."""
        if self.categorical_feature == categorical_feature:
            return self
        if self._binned is not None:
            if self.data is None:
                raise LightGBMError(
                    "Cannot set categorical feature after freed raw "
                    "data, set free_raw_data=False when construct "
                    "Dataset to avoid this.")
            from .utils.log import Log
            Log.warning("categorical_feature in Dataset is overridden.\n"
                        "New categorical_feature is %s",
                        sorted(list(categorical_feature))
                        if not isinstance(categorical_feature, str)
                        else categorical_feature)
            self._binned = None  # lazily re-constructed with the new set
        self.categorical_feature = categorical_feature
        return self

    def save_binary(self, filename: str) -> "Dataset":
        """Write the constructed dataset to a binary cache file
        (reference basic.py Dataset.save_binary / LGBM_DatasetSaveBinary)."""
        self.construct()
        if getattr(self, "_seeded_init_score", False):
            # continuation seeds are transient training state; persisting
            # them would silently shift any model later trained from the
            # cache (the loaded Dataset cannot know they were seeded)
            saved = self._binned.metadata.init_score
            self._binned.metadata.init_score = None
            try:
                self._binned.save_binary(filename)
            finally:
                self._binned.metadata.init_score = saved
        else:
            self._binned.save_binary(filename)
        return self

    @property
    def binned(self) -> BinnedDataset:
        self.construct()
        return self._binned


class Booster:
    """Training/prediction handle (reference basic.py:2594 + c_api.cpp:106).

    Thread-safety note: the reference guards the C Booster with a
    shared_mutex (c_api.cpp:827); here the GIL plus JAX's functional arrays
    make mutation points (update/save) naturally serialized.
    """

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        from .boosting.gbdt import create_boosting
        self.params = dict(params or {})
        self.config = param_dict_to_config(self.params)
        Log.set_verbosity(self.config.verbosity)
        from .observability import registry as _obs
        _obs.configure_from_config(self.config)
        self._model = None          # HostModel once finalized/loaded
        self.gbdt = None
        self.train_set = None
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_metric_objs = []
        if model_file is not None:
            with open_file(model_file) as fh:
                model_str = fh.read()
        if model_str is not None:
            from .tree import HostModel
            self._model = HostModel.from_string(model_str)
            return
        if train_set is None:
            raise LightGBMError("Booster needs train_set or a model")
        if not isinstance(train_set, Dataset):
            raise TypeError("train_set must be a Dataset")
        self.train_set = train_set
        merged = dict(train_set.params)
        merged.update(self.params)
        train_set.params = merged
        train_set.construct()
        cfg = self.config
        objective = create_objective(cfg.objective, cfg)
        metric_names = cfg.metric_list()
        if not metric_names and cfg.objective in METRIC_ALIASES:
            metric_names = [cfg.objective]
        metrics = [m for m in (create_metric(nm, cfg) for nm in metric_names)
                   if m is not None]
        binned = train_set.binned
        for m in metrics:
            m.init(binned.metadata, binned.num_data)
        self._metric_names = metric_names
        self.gbdt = create_boosting(cfg, binned, objective,
                                    metrics if cfg.is_provide_training_metric
                                    else metrics)
        self.name_valid_sets: List[str] = []
        self._valid_data: List[Dataset] = []

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.reference = self.train_set
        if self.config.linear_tree and data._binned is None:
            # valid sets need raw values too when leaves hold linear models
            data.params = dict(data.params or {}, linear_tree=True)
        data.construct()
        cfg = self.config
        metrics = [m for m in (create_metric(nm, cfg)
                               for nm in self._metric_names) if m is not None]
        for m in metrics:
            m.init(data.binned.metadata, data.binned.num_data)
        self.gbdt.add_valid(data.binned, name, metrics)
        self.name_valid_sets.append(name)
        self._valid_data.append(data)
        return self

    def update(self, train_set=None, fobj=None) -> bool:
        """One boosting iteration; returns True if no further splits
        (reference LGBM_BoosterUpdateOneIter)."""
        self._model = None
        if fobj is not None:
            import jax.numpy as jnp
            # user-supplied gradients: the configured objective's
            # constant-hessian promise no longer holds (engine.train
            # handles this by resetting objective to "none"; this direct
            # path must neutralize the fast-path gate itself)
            self.gbdt.set_custom_objective()
            score = self.gbdt.train_score
            grad, hess = fobj(np.asarray(score), self.train_set)
            return self.gbdt.train_one_iter(
                jnp.asarray(grad, jnp.float32).reshape(score.shape),
                jnp.asarray(hess, jnp.float32).reshape(score.shape))
        return self.gbdt.train_one_iter()

    def update_batch(self, num_iterations: int) -> bool:
        """Run several boosting iterations with a single device dispatch
        (the fused on-device scan, boosting/fused.py) when the
        configuration allows, else a plain update() loop. Semantically
        identical to calling update() num_iterations times; the win is
        host-boundary amortization on remoted accelerators. Returns True
        if training cannot continue."""
        self._model = None
        return self.gbdt.train_many(num_iterations)

    def update_batch_dispatch(self, num_iterations: int) -> dict:
        """update_batch split at the tree-unpack boundary: run the block
        (scores/RNG/valid trajectories fully advanced) and return a
        handle whose finalize_block call appends the trees. The
        pipelined executor (pipeline/executor.py) defers finalize into
        the next block's device window; update_batch == finalize_block(
        update_batch_dispatch(n)) exactly."""
        self._model = None
        return self.gbdt.train_many_dispatch(num_iterations)

    def finalize_block(self, handle: dict) -> bool:
        self._model = None
        return self.gbdt.finalize_block(handle)

    def rollback_one_iter(self) -> "Booster":
        self._model = None
        self.gbdt.rollback_one_iter()
        return self

    # ------------------------------------------------------------------
    # training-state serialization (reliability/checkpoint.py bundles)
    def _training_state(self):
        """(json-state, arrays) capturing everything `model_to_string`
        does NOT: the exact f32 score state, RNG stream position,
        mid-period bagging mask and boost-from-average flags. Together
        with the saved model text this is sufficient for
        `_restore_training_state` to continue the run bit-for-bit."""
        state, arrays = self.gbdt.training_state()
        state["best_iteration"] = int(self.best_iteration)
        return state, arrays

    def _restore_training_state(self, ckpt) -> None:
        """Restore from a `reliability.checkpoint.CheckpointState`.

        The caller (engine.train resume path) has already attached the
        checkpointed model as `_base_model`; this restores the live
        training state on top of it."""
        self._model = None
        self.gbdt.restore_training_state(ckpt.iteration, ckpt.state,
                                         ckpt.arrays)
        best = int(ckpt.state.get("best_iteration", -1))
        if best >= 0:
            self.best_iteration = best

    def current_iteration(self) -> int:
        if self.gbdt is not None:
            n = self.gbdt.current_iteration()
            base = getattr(self, "_base_model", None)
            if base is not None:
                n += base.current_iteration()  # continued training
            return n
        return self._model.num_iterations if self._model else 0

    @property
    def num_trees_per_iteration(self) -> int:
        if self.gbdt is not None:
            return self.gbdt.num_tree_per_iteration
        return self._model.num_tree_per_iteration if self._model else 1

    def num_model_per_iteration(self) -> int:
        return self.num_trees_per_iteration

    def num_trees(self) -> int:
        if self.gbdt is not None:
            n = len(self.gbdt.trees)
            base = getattr(self, "_base_model", None)
            if base is not None:
                n += base.num_trees()   # continued training keeps base trees
            return n
        return len(self._model.trees) if self._model else 0

    # ------------------------------------------------------------------
    train_data_name = "training"

    def eval_train(self, feval=None) -> List:
        res = []
        for name, val in self.gbdt.eval_train().items():
            higher = name in ("auc", "ndcg", "map", "average_precision",
                              "auc_mu") or name.split("@")[0] in ("ndcg", "map")
            res.append((self.train_data_name, name, val, higher))
        res.extend(self._custom_eval(feval, self.train_data_name, None))
        return res

    def eval_valid(self, feval=None) -> List:
        res = []
        for i, name in enumerate(self.name_valid_sets):
            for mname, val in self.gbdt.eval_valid(i).items():
                higher = mname.split("@")[0] in (
                    "auc", "ndcg", "map", "average_precision", "auc_mu")
                res.append((name, mname, val, higher))
            res.extend(self._custom_eval(feval, name, i))
        return res

    def _custom_eval(self, feval, data_name, valid_idx):
        if feval is None:
            return []
        funcs = feval if isinstance(feval, (list, tuple)) else [feval]
        if valid_idx is None:
            score, data = self.gbdt.train_score, self.train_set
        else:
            score, data = self.gbdt.valid_scores[valid_idx], \
                self._valid_data[valid_idx]
        out = []
        for fn in funcs:
            r = fn(np.asarray(score), data)
            rs = r if isinstance(r, list) else [r]
            for name, val, higher in rs:
                out.append((data_name, name, val, higher))
        return out

    # ------------------------------------------------------------------
    def _host_model(self):
        from .tree import HostModel
        if self._model is None:
            model = HostModel.from_gbdt(self.gbdt, self.train_set)
            base = getattr(self, "_base_model", None)
            if base is not None:
                # continued training: the saved/served model keeps the
                # base model's trees in front of the new ones (reference
                # Booster(model_file=...) + train semantics)
                bm = base._host_model()
                model.trees = list(bm.trees) + model.trees
                model.tree_class = list(bm.tree_class) + model.tree_class
                if not model.feature_names and bm.feature_names:
                    model.feature_names = bm.feature_names
                    model.feature_infos = bm.feature_infos
                    model.max_feature_idx = bm.max_feature_idx
            self._model = model
        return self._model

    def device_forest(self):
        """Memoized device-stacked serving forest (serving/forest.py).

        Repeated serving calls reuse the resident arrays instead of
        re-stacking the trees per call. Invalidation is by HostModel
        identity: every mutation point (update / update_batch /
        rollback_one_iter / model reload) clears `self._model`, so the
        next call here sees a fresh HostModel object and rebuilds."""
        model = self._host_model()
        cached = getattr(self, "_device_forest", None)
        if cached is not None and cached._model is model:
            return cached
        from .serving.forest import build_device_forest
        self._device_forest = build_device_forest(model)
        return self._device_forest

    def predict(self, data, start_iteration: int = 0,
                num_iteration: Optional[int] = None, raw_score: bool = False,
                pred_leaf: bool = False, pred_contrib: bool = False,
                validate_features: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0,
                **kwargs) -> np.ndarray:
        model = self._host_model()
        kw = dict(start_iteration=start_iteration,
                  num_iteration=num_iteration, raw_score=raw_score,
                  pred_leaf=pred_leaf, pred_contrib=pred_contrib,
                  pred_early_stop=pred_early_stop,
                  pred_early_stop_freq=pred_early_stop_freq,
                  pred_early_stop_margin=pred_early_stop_margin)
        if _is_pandas_df(data) and model.pandas_categorical is not None:
            # encode category columns with the TRAINING category order
            # (reference basic.py predict-time _data_from_pandas)
            data, _, _, _ = _data_from_pandas(
                data, model.pandas_categorical)
        if _is_sparse(data):
            # densify in row chunks so wide-sparse inputs never need the
            # full dense matrix in memory (reference predicts CSR rows
            # natively, c_api.cpp PredictForCSR)
            csr = data.tocsr()
            if csr.shape[0] == 0:
                return model.predict(
                    np.zeros((0, csr.shape[1]), np.float64), **kw)
            chunk = max(1, int(32 << 20) // max(1, 8 * csr.shape[1]))
            outs = [model.predict(_to_2d_float(csr[i:i + chunk]), **kw)
                    for i in range(0, csr.shape[0], chunk)]
            if pred_contrib:
                # contribs are [n, F+1]: dense would defeat the chunking
                # on wide-sparse inputs; the reference also returns a
                # sparse matrix for sparse contrib input (c_api
                # PredictForCSR contrib path)
                import scipy.sparse as _sp
                return _sp.vstack([_sp.csr_matrix(o) for o in outs])
            return np.concatenate(outs, axis=0)
        return model.predict(_to_2d_float(data), **kw)

    def refit(self, data, label, decay_rate: Optional[float] = None,
              **kwargs) -> "Booster":
        """Refit leaf values on new data (reference gbdt.cpp:287 RefitTree)."""
        model = self._host_model()
        decay = self.config.refit_decay_rate if decay_rate is None \
            else decay_rate
        new_model = model.refit(_to_2d_float(data),
                                np.asarray(label, np.float32), decay,
                                self.config)
        new_booster = Booster(params=self.params,
                              model_str=new_model.to_string())
        return new_booster

    # ------------------------------------------------------------------
    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> "Booster":
        with open_file(filename, "w") as fh:
            fh.write(self.model_to_string(num_iteration, start_iteration,
                                          importance_type))
        return self

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        return self._host_model().to_string(
            num_iteration=num_iteration, start_iteration=start_iteration)

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> dict:
        return self._host_model().to_json(num_iteration, start_iteration)

    # ------------------------------------------------------------------
    def feature_name(self) -> List[str]:
        return self._host_model().feature_names

    def num_feature(self) -> int:
        return self._host_model().max_feature_idx + 1

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        return self._host_model().feature_importance(importance_type)

    def lower_bound(self):
        model = self._host_model()
        return min((t.leaf_value.min() for t in model.trees), default=0.0)

    def upper_bound(self):
        model = self._host_model()
        return max((t.leaf_value.max() for t in model.trees), default=0.0)

    def free_dataset(self) -> "Booster":
        self.train_set = None
        return self

    def free_network(self) -> "Booster":
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        self.params.update(params)
        self.config.update(params)
        if self.gbdt is not None:
            self.gbdt.shrinkage_rate = float(self.config.learning_rate)
            self.gbdt.config = self.config
            # the fused multi-tree scan bakes shrinkage/grower settings
            # into its compiled closure — rebuild on next update_batch
            self.gbdt._fused_run = None
        return self

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, _):
        return Booster(params=self.params, model_str=self.model_to_string())
