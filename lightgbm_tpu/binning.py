"""Host-side feature quantization: value -> bin mapping.

Behavior-equivalent redesign of the reference BinMapper
(include/LightGBM/bin.h:61-236, src/io/bin.cpp:78-470):

- numerical features: distinct values of a sample are packed greedily into at
  most `max_bin` bins (big-count values get dedicated bins, zero always sits
  alone in its own bin, NaN occupies the last bin when missing_type==NaN);
- categorical features: category codes sorted by frequency, rare categories
  beyond 99% cumulative count dropped, bin 0 reserved for NaN/unseen;
- `value_to_bin` vectorized with searchsorted (replaces the reference's
  per-value binary search bin.h:149).

This runs on host NumPy once per dataset; the result (uint8/uint16 bin
matrix) is what lives in HBM. A C++ fast path can plug in underneath via
lightgbm_tpu.cext without changing this API.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["BinMapper", "MissingType", "find_bin_mappers"]

_ZERO_THRESHOLD = 1e-35


class MissingType:
    NONE = 0
    ZERO = 1
    NAN = 2


def _check_double_equal(a: float, b: float) -> bool:
    upper = b + 1e-9 * max(abs(a), abs(b))
    return a <= upper and a >= b - 1e-9 * max(abs(a), abs(b))


def _greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                     max_bin: int, total_cnt: int,
                     min_data_in_bin: int) -> List[float]:
    """Pack distinct values into <= max_bin bins; returns bin upper bounds
    (last bound is +inf). Mirrors src/io/bin.cpp:78 GreedyFindBin.
    Dispatches to the native cext implementation when built."""
    from . import cext
    if cext.available() and len(distinct_values):
        return cext.greedy_find_bin(
            distinct_values, counts, max_bin, total_cnt,
            min_data_in_bin).tolist()
    n = len(distinct_values)
    bounds: List[float] = []
    if n == 0:
        return [math.inf]
    if n <= max_bin:
        cur = 0
        for i in range(n - 1):
            cur += int(counts[i])
            if cur >= min_data_in_bin:
                val = (distinct_values[i] + distinct_values[i + 1]) / 2.0
                if not bounds or not _check_double_equal(bounds[-1], val):
                    bounds.append(val)
                    cur = 0
        bounds.append(math.inf)
        return bounds
    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = total_cnt - int(counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    uppers: List[float] = []
    lowers: List[float] = [float(distinct_values[0])]
    cur = 0
    for i in range(n - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur += int(counts[i])
        if is_big[i] or cur >= mean_bin_size or \
                (is_big[i + 1] and cur >= max(1.0, mean_bin_size * 0.5)):
            uppers.append(float(distinct_values[i]))
            lowers.append(float(distinct_values[i + 1]))
            if len(uppers) >= max_bin - 1:
                break
            cur = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    for i in range(len(uppers)):
        val = (uppers[i] + lowers[i + 1]) / 2.0
        if not bounds or not _check_double_equal(bounds[-1], val):
            bounds.append(val)
    bounds.append(math.inf)
    return bounds


def _find_bin_zero_as_one(distinct_values: np.ndarray, counts: np.ndarray,
                          max_bin: int, total_sample_cnt: int,
                          min_data_in_bin: int) -> List[float]:
    """Zero gets a dedicated bin; negatives binned left of it, positives right.
    Mirrors src/io/bin.cpp:256 FindBinWithZeroAsOneBin."""
    n = len(distinct_values)
    if n == 0:
        return [math.inf]
    neg_mask = distinct_values <= -_ZERO_THRESHOLD
    pos_mask = distinct_values > _ZERO_THRESHOLD
    left_cnt_data = int(counts[neg_mask].sum())
    right_cnt_data = int(counts[pos_mask].sum())
    cnt_zero = total_sample_cnt - left_cnt_data - right_cnt_data

    left_idx = np.nonzero(~neg_mask)[0]
    left_cnt = int(left_idx[0]) if len(left_idx) else n
    right_idx = np.nonzero(pos_mask)[0]
    right_start = int(right_idx[0]) if len(right_idx) else -1

    bounds: List[float] = []
    if left_cnt > 0:
        left_max_bin = max(
            1, int(left_cnt_data / max(total_sample_cnt, 1) / 2 * (max_bin - 1)))
        bounds = _greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                                  left_max_bin, left_cnt_data, min_data_in_bin)
        bounds[-1] = -_ZERO_THRESHOLD
    if right_start >= 0:
        right_max_bin = max_bin - 1 - len(bounds)
        if right_max_bin > 0:
            right = _greedy_find_bin(
                distinct_values[right_start:], counts[right_start:],
                right_max_bin, right_cnt_data, min_data_in_bin)
            bounds.append(_ZERO_THRESHOLD)
            bounds.extend(right)
        else:
            bounds.append(math.inf)
    else:
        bounds.append(math.inf)
    if cnt_zero <= 0 and len(bounds) >= 2:
        # no actual zeros: boundaries stay, harmless (matches upstream which
        # still inserts the zero bin only when zeros exist in the sample path)
        pass
    return bounds


class BinMapper:
    """Per-feature value -> bin quantizer (reference bin.h:61)."""

    def __init__(self) -> None:
        self.num_bin: int = 1
        self.missing_type: int = MissingType.NONE
        self.is_categorical: bool = False
        self.is_trivial: bool = True
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0  # bin of value 0.0 (reference bin.h:131)
        self.sparse_rate: float = 0.0

    # ---- construction -------------------------------------------------
    @staticmethod
    def from_sample(values: np.ndarray, total_sample_cnt: int, max_bin: int,
                    min_data_in_bin: int = 3, use_missing: bool = True,
                    zero_as_missing: bool = False,
                    is_categorical: bool = False,
                    forced_bounds: Optional[Sequence[float]] = None
                    ) -> "BinMapper":
        """Build from a (possibly subsampled) value vector. Values absent
        from `values` relative to total_sample_cnt are implicit zeros
        (reference FindBin bin.cpp:325-360 treats them so)."""
        m = BinMapper()
        values = np.asarray(values, dtype=np.float64)
        nan_mask = np.isnan(values)
        na_cnt = int(nan_mask.sum())
        values = values[~nan_mask]

        if not use_missing:
            m.missing_type = MissingType.NONE
        elif zero_as_missing:
            m.missing_type = MissingType.ZERO
        else:
            m.missing_type = MissingType.NAN if na_cnt > 0 else MissingType.NONE

        zero_cnt = int(total_sample_cnt - len(values) - na_cnt)
        if is_categorical:
            m._build_categorical(values, na_cnt, total_sample_cnt, max_bin)
            return m

        # distinct values with zero spliced in at its sorted position
        if len(values):
            values = np.sort(values)
            # merge nearly-equal neighbours, keeping the larger value
            keep = np.ones(len(values), dtype=bool)
            diffs = np.diff(values)
            tol = 1e-9 * np.maximum(np.abs(values[:-1]), np.abs(values[1:]))
            keep[:-1] = diffs > tol
            distinct = values[keep]
            counts = np.diff(np.concatenate(
                [[0], np.nonzero(keep)[0] + 1])).astype(np.int64)
        else:
            distinct = np.array([], dtype=np.float64)
            counts = np.array([], dtype=np.int64)
        if zero_cnt > 0 or len(distinct) == 0:
            pos = int(np.searchsorted(distinct, 0.0))
            if pos >= len(distinct) or abs(distinct[pos]) > _ZERO_THRESHOLD:
                distinct = np.insert(distinct, pos, 0.0)
                counts = np.insert(counts, pos, max(zero_cnt, 0))
        m.min_val = float(distinct[0]) if len(distinct) else 0.0
        m.max_val = float(distinct[-1]) if len(distinct) else 0.0

        if m.missing_type == MissingType.NAN:
            bounds = _find_bin_zero_as_one(
                distinct, counts, max_bin - 1, total_sample_cnt - na_cnt,
                min_data_in_bin)
            bounds.append(math.nan)  # last bin = NaN bin (bin.cpp:401-404)
        else:
            bounds = _find_bin_zero_as_one(
                distinct, counts, max_bin, total_sample_cnt, min_data_in_bin)
            if m.missing_type == MissingType.ZERO and len(bounds) == 2:
                m.missing_type = MissingType.NONE
        m.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
        m.num_bin = len(bounds)
        # trivial when all data lands in one bin (constant feature) —
        # reference prunes via is_trivial + feature_pre_filter. Bins are
        # monotone over the sorted distinct values, so "one occupied bin"
        # reduces to first and last landing together.
        if len(distinct):
            ends = m.values_to_bins_numeric_only(distinct[[0, -1]])
            occupied = 1 if ends[0] == ends[1] else 2
        else:
            occupied = 0
        if na_cnt > 0:
            occupied += 1
        m.is_trivial = m.num_bin <= 1 or occupied <= 1
        m.default_bin = m._value_to_bin_scalar(0.0)
        if total_sample_cnt > 0:
            m.sparse_rate = zero_cnt / total_sample_cnt
        return m

    @staticmethod
    def _from_native(bounds: np.ndarray, mtype: int, minmax, zero_na,
                     total_sample_cnt: int) -> "BinMapper":
        """Assemble a numeric mapper from lgbt_find_numeric_bounds
        output (cext/binning.cpp) — the scalar tail of from_sample."""
        m = BinMapper()
        m.missing_type = int(mtype)
        m.bin_upper_bound = np.asarray(bounds, np.float64)
        m.num_bin = len(bounds)
        m.min_val = float(minmax[0])
        m.max_val = float(minmax[1])
        zero_cnt, na_cnt = int(zero_na[0]), int(zero_na[1])
        ends = m.values_to_bins_numeric_only(
            np.asarray([m.min_val, m.max_val]))
        occupied = (1 if ends[0] == ends[1] else 2) + (1 if na_cnt else 0)
        m.is_trivial = m.num_bin <= 1 or occupied <= 1
        m.default_bin = m._value_to_bin_scalar(0.0)
        if total_sample_cnt > 0:
            m.sparse_rate = zero_cnt / total_sample_cnt
        return m

    def _build_categorical(self, values: np.ndarray, na_cnt: int,
                           total_sample_cnt: int, max_bin: int) -> None:
        self.is_categorical = True
        ints = values.astype(np.int64)
        neg = ints < 0
        na_cnt += int(neg.sum())
        ints = ints[~neg]
        cats, counts = np.unique(ints, return_counts=True)
        order = np.argsort(-counts, kind="stable")
        cats, counts = cats[order], counts[order]
        # implicit zeros
        zero_cnt = total_sample_cnt - len(values) - (na_cnt - int(neg.sum()))
        if zero_cnt > 0:
            if 0 in cats:
                idx = int(np.nonzero(cats == 0)[0][0])
                counts[idx] += zero_cnt
                order = np.argsort(-counts, kind="stable")
                cats, counts = cats[order], counts[order]
            else:
                cats = np.append(cats, 0)
                counts = np.append(counts, zero_cnt)
                order = np.argsort(-counts, kind="stable")
                cats, counts = cats[order], counts[order]
        cut_cnt = int(round((total_sample_cnt - na_cnt) * 0.99))
        # bin 0 is the NaN/unseen dummy (bin.cpp:452-456)
        self.bin_2_categorical = [-1]
        self.categorical_2_bin = {-1: 0}
        self.num_bin = 1
        used = 0
        i = 0
        while i < len(cats) and self.num_bin < max_bin:
            if used >= cut_cnt and self.num_bin >= 2:
                break
            self.bin_2_categorical.append(int(cats[i]))
            self.categorical_2_bin[int(cats[i])] = self.num_bin
            used += int(counts[i])
            self.num_bin += 1
            i += 1
        self.is_trivial = self.num_bin <= 2 and na_cnt == 0
        self.missing_type = MissingType.NAN
        self.default_bin = self.categorical_2_bin.get(0, 0)
        self.min_val = float(cats.min()) if len(cats) else 0.0
        self.max_val = float(cats.max()) if len(cats) else 0.0

    # ---- mapping ------------------------------------------------------
    def _value_to_bin_scalar(self, value: float) -> int:
        return int(self.values_to_bins(np.array([value]))[0])

    def values_to_bins_numeric_only(self, values: np.ndarray) -> np.ndarray:
        """Bin finite values during construction (no NaN branch needed)."""
        n_numeric = self.num_bin
        if self.missing_type == MissingType.NAN:
            n_numeric -= 1
        search_bounds = self.bin_upper_bound[:max(n_numeric - 1, 0)]
        return np.searchsorted(search_bounds, values, side="left")

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin (reference bin.h:149 ValueToBin)."""
        vals = np.asarray(values, dtype=np.float64)
        if self.is_categorical:
            return self._cat_bins_from_f64(vals)
        out = self._numeric_bins_from_f64(vals, own=vals is not values)
        return out.astype(np.int32, copy=False)

    def _cat_bins_from_f64(self, vals: np.ndarray) -> np.ndarray:
        """Categorical value->bin over a float64 vector: sorted-key LUT
        (searchsorted + equality mask) instead of a per-value dict loop;
        unseen/negative/non-finite all land in dummy bin 0."""
        ints = np.where(~np.isfinite(vals), -1, vals).astype(np.int64)
        items = sorted(self.categorical_2_bin.items())
        keys = np.asarray([k for k, _ in items], dtype=np.int64)
        bins = np.asarray([b for _, b in items], dtype=np.int32)
        if not len(keys):
            return np.zeros(len(ints), dtype=np.int32)
        pos = np.minimum(np.searchsorted(keys, ints), len(keys) - 1)
        return np.where(keys[pos] == ints, bins[pos], 0).astype(np.int32)

    def _numeric_bins_from_f64(self, vals: np.ndarray,
                               own: bool = False) -> np.ndarray:
        """Numeric value->bin over a float64 vector. `own=True` marks
        `vals` as a scratch buffer this call may mutate in place (the
        ZERO-missing rewrite then skips its defensive copy). NaN fixups
        run only when NaNs are actually present, so the common all-finite
        column pays searchsorted + one mask scan and nothing else."""
        n_numeric = self.num_bin
        has_nan_bin = self.missing_type == MissingType.NAN
        if has_nan_bin:
            n_numeric -= 1
        search_bounds = self.bin_upper_bound[:max(n_numeric - 1, 0)]
        nan_mask = np.isnan(vals)
        has_nan = bool(nan_mask.any())
        if has_nan and self.missing_type == MissingType.ZERO:
            if not own:
                vals = vals.copy()
            vals[nan_mask] = 0.0
        # searchsorted(left) gives first bound >= v, matching "v <= bound"
        out = np.searchsorted(search_bounds, vals, side="left")
        if has_nan:
            # ZERO already rewrote NaN->0.0, whose searchsorted result IS
            # default_bin, so overwriting again is a no-op kept for parity
            out[nan_mask] = self.num_bin - 1 if has_nan_bin \
                else self.default_bin
        return out

    def bin_to_threshold_value(self, bin_idx: int) -> float:
        """Real-valued split threshold for `value <= threshold` given the
        chosen bin (used for model serialization; reference stores the bin
        upper bound as the tree threshold, tree.cpp RecomputeMaxDepth path)."""
        if self.is_categorical:
            return float(bin_idx)
        b = min(bin_idx, len(self.bin_upper_bound) - 1)
        v = float(self.bin_upper_bound[b])
        if math.isinf(v) or math.isnan(v):
            v = float(self.max_val)
        return v

    # ---- serialization ------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "is_categorical": self.is_categorical,
            "is_trivial": self.is_trivial,
            "bin_upper_bound": self.bin_upper_bound.tolist(),
            "bin_2_categorical": self.bin_2_categorical,
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
            "sparse_rate": self.sparse_rate,
        }

    @staticmethod
    def from_dict(d: dict) -> "BinMapper":
        m = BinMapper()
        m.num_bin = d["num_bin"]
        m.missing_type = d["missing_type"]
        m.is_categorical = d["is_categorical"]
        m.is_trivial = d["is_trivial"]
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = list(d["bin_2_categorical"])
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = d["min_val"]
        m.max_val = d["max_val"]
        m.default_bin = d["default_bin"]
        m.sparse_rate = d.get("sparse_rate", 0.0)
        return m


def find_bin_mappers(X: np.ndarray, max_bin: int = 255,
                     min_data_in_bin: int = 3,
                     sample_cnt: int = 200000,
                     use_missing: bool = True,
                     zero_as_missing: bool = False,
                     categorical_features: Optional[Sequence[int]] = None,
                     seed: int = 1,
                     feature_names: Optional[Sequence[str]] = None
                     ) -> List[BinMapper]:
    """Find per-feature BinMappers from (a sample of) X.

    Reference: DatasetLoader::ConstructBinMappersFromTextData two-round
    sampling (dataset_loader.cpp:~690); in distributed mode each rank bins a
    feature slice then allgathers (dataset_loader.cpp:722-807) — here binning
    is cheap enough to run redundantly on each host, keeping mappers
    identical by construction.
    """
    from .utils.timer import global_timer
    from . import cext
    num_data, num_features = X.shape
    cat_set = set(categorical_features or [])
    # first cext touch may lazily g++-build the library — keep that
    # one-time cost out of the sample timer bucket
    has_cext = cext.available()
    with global_timer.timeit("dataset_sample"):
        sample_t = None
        if num_data > sample_cnt:
            rng = np.random.RandomState(seed)
            idx = np.sort(rng.choice(num_data, size=sample_cnt,
                                     replace=False))
            total = sample_cnt
            if (has_cext and isinstance(X, np.ndarray)
                    and X.dtype in (np.float32, np.float64)
                    and X.flags["C_CONTIGUOUS"]):
                # fused native gather+transpose+f64 cast: one streaming
                # pass (lgbt_sample_transpose), bit-identical to the
                # NumPy chain below
                sample_t = cext.sample_transpose(X, idx)
            else:
                sample = X[idx]
        else:
            sample = X
            total = num_data
        if sample_t is None:
            # transpose once: per-feature slices become contiguous, which
            # makes the per-column mask/filter/sort work ~5x faster than
            # strided views (transpose + dtype conversion fused into a
            # single allocation)
            sample_t = np.ascontiguousarray(np.asarray(sample).T,
                                            dtype=np.float64)
    numeric = [f for f in range(num_features) if f not in cat_set]
    if cext.available() and numeric:
        # native whole-matrix boundary search (cext/binning.cpp
        # lgbt_find_numeric_bounds, the reference's OMP FindBin loop,
        # dataset_loader.cpp:~690); behavior-exact vs the NumPy path
        sub = sample_t[numeric] if cat_set else sample_t
        with global_timer.timeit("dataset_bounds"):
            blist, mtype, minmax, zero_na = cext.find_numeric_bounds(
                sub, max_bin, min_data_in_bin, use_missing,
                zero_as_missing)
        mappers: List[BinMapper] = [None] * num_features  # type: ignore
        for j, fi in enumerate(numeric):
            mappers[fi] = BinMapper._from_native(
                blist[j], mtype[j], minmax[j], zero_na[j], total)
        for fi in sorted(cat_set):
            if fi >= num_features:
                continue
            col = sample_t[fi]
            nonzero = col[(np.abs(col) > _ZERO_THRESHOLD) | np.isnan(col)]
            mappers[fi] = BinMapper.from_sample(
                nonzero, total, max_bin, min_data_in_bin, use_missing,
                zero_as_missing, is_categorical=True)
        return mappers
    mappers = []
    for f in range(num_features):
        col = sample_t[f]
        nonzero = col[(np.abs(col) > _ZERO_THRESHOLD) | np.isnan(col)]
        mappers.append(BinMapper.from_sample(
            nonzero, total, max_bin, min_data_in_bin, use_missing,
            zero_as_missing, is_categorical=f in cat_set))
    return mappers


def bin_columns(X: np.ndarray, feat_indices: Sequence[int],
                mappers: Sequence["BinMapper"], dtype) -> np.ndarray:
    """Quantize X[:, feat_indices[j]] with mappers[j] into a [N, len(used)]
    bin matrix. Numeric features go through the native OpenMP whole-matrix
    kernel when available (reference: DatasetLoader bins with full OMP,
    dataset_loader.cpp); categorical features and the no-compiler fallback
    use the vectorized NumPy path."""
    from . import cext
    num_data = X.shape[0]
    numeric = [j for j, m in enumerate(mappers) if not m.is_categorical]
    if cext.available() and numeric and num_data > 10000:
        bounds, offs, nsearch, nanb = [], [0], [], []
        for j in numeric:
            m = mappers[j]
            n_numeric = m.num_bin - (1 if m.missing_type == MissingType.NAN
                                     else 0)
            sb = m.bin_upper_bound[:max(n_numeric - 1, 0)]
            bounds.append(sb)
            offs.append(offs[-1] + len(sb))
            nsearch.append(len(sb))
            nanb.append(m.num_bin - 1
                        if m.missing_type == MissingType.NAN
                        else m.default_bin)
        flat = (np.concatenate(bounds) if bounds
                else np.zeros(0, np.float64))
        sub = cext.bin_matrix(
            X, np.asarray([feat_indices[j] for j in numeric], np.int32),
            flat, np.asarray(offs[:-1], np.int64),
            np.asarray(nsearch, np.int32), np.asarray(nanb, np.int32),
            dtype)
        if len(numeric) == len(mappers):
            # all-numeric (the dense ingestion common case): the native
            # output IS the bin matrix — skip the [N, F] fancy-index copy
            return sub
        out = np.empty((num_data, len(feat_indices)), dtype=dtype)
        out[:, numeric] = sub
        rest = [j for j, m in enumerate(mappers) if m.is_categorical]
    else:
        out = np.empty((num_data, len(feat_indices)), dtype=dtype)
        rest = list(range(len(mappers)))
    if rest:
        # fused quantize pass: one reusable contiguous float64 scratch
        # per column (copyto, no per-column allocation) that the mapper
        # may mutate in place (own=True skips the ZERO-missing copy),
        # searchsorted, then a single strided store. The working set
        # stays one column (~8 bytes/row) so the searchsorted read hits
        # cache; replaces the copy / np.where / int32-cast chain that
        # dominated the quantize wall on the NumPy path. (A whole-matrix
        # [F, N] staging pass measures SLOWER here — it streams the full
        # matrix through memory twice and evicts every column before its
        # bound search runs.)
        scratch = np.empty(num_data, dtype=np.float64)
        for j in rest:
            m = mappers[j]
            np.copyto(scratch, X[:, feat_indices[j]], casting="unsafe")
            out[:, j] = m._cat_bins_from_f64(scratch) if m.is_categorical \
                else m._numeric_bins_from_f64(scratch, own=True)
    return out


def find_bin_mappers_sparse(X_csc, max_bin: int = 255,
                            min_data_in_bin: int = 3,
                            sample_cnt: int = 200000,
                            use_missing: bool = True,
                            zero_as_missing: bool = False,
                            categorical_features: Optional[Sequence[int]]
                            = None, seed: int = 1) -> List[BinMapper]:
    """find_bin_mappers over a scipy CSC matrix WITHOUT densifying: each
    column contributes only its stored values; absent entries are the
    implicit zeros BinMapper.from_sample already models via
    total_sample_cnt (reference FindBin, bin.cpp:325-360 — and the
    distributed loader samples the same way, dataset_loader.cpp:560)."""
    num_data, num_features = X_csc.shape
    cat_set = set(categorical_features or [])
    if num_data > sample_cnt:
        rng = np.random.RandomState(seed)
        idx = np.sort(rng.choice(num_data, size=sample_cnt, replace=False))
        total = sample_cnt
    else:
        idx = None
        total = num_data
    indptr, indices, vals = X_csc.indptr, X_csc.indices, X_csc.data
    mappers = []
    for f in range(num_features):
        lo, hi = int(indptr[f]), int(indptr[f + 1])
        rows_f = indices[lo:hi]
        v = np.asarray(vals[lo:hi], dtype=np.float64)
        if idx is not None:
            pos = np.searchsorted(idx, rows_f)
            pos_c = np.minimum(pos, len(idx) - 1)
            sel = idx[pos_c] == rows_f
            v = v[sel]
        nonzero = v[(np.abs(v) > _ZERO_THRESHOLD) | np.isnan(v)]
        mappers.append(BinMapper.from_sample(
            nonzero, total, max_bin, min_data_in_bin, use_missing,
            zero_as_missing, is_categorical=f in cat_set))
    return mappers
