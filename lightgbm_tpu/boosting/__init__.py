from .gbdt import GBDT, create_boosting

__all__ = ["GBDT", "create_boosting"]
