"""DART boosting: dropout trees + normalization (reference dart.hpp:23-211).

Per iteration: select dropped trees (by rate or uniform-one, optionally
weighted by tree weight), subtract their contribution from all scores, train
on the modified gradients, then normalize the new tree and the dropped trees
(xgboost_dart_mode supported). Tree weights tracked per tree.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from ..learner.predict import predict_binned_tree
from ..utils.log import Log
from .gbdt import GBDT

__all__ = ["DART"]


class DART(GBDT):
    def __init__(self, config, train_set, objective, metrics):
        super().__init__(config, train_set, objective, metrics)
        self.tree_weights: List[float] = []
        self.drop_indices: List[int] = []
        self.sum_weight = 0.0
        self._random = np.random.RandomState(config.drop_seed)
        self.shrinkage_rate = float(config.learning_rate)

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        self._select_dropping_trees()
        self._drop_trees()
        stop = super().train_one_iter(gradients, hessians)
        self._normalize()
        return stop

    # reference dart.hpp:95-125 DroppingTrees
    def _select_dropping_trees(self) -> None:
        cfg = self.config
        k = self.num_tree_per_iteration
        num_iters_done = len(self.trees) // k
        self.drop_indices = []
        if num_iters_done == 0:
            return
        if cfg.uniform_drop:
            for i in range(num_iters_done):
                if self._random.rand() < cfg.drop_rate:
                    self.drop_indices.append(i)
        else:
            w = np.asarray(self.tree_weights[:num_iters_done])
            p = w / max(w.sum(), 1e-15)
            for i in range(num_iters_done):
                if self._random.rand() < cfg.drop_rate * p[i] * \
                        num_iters_done:
                    self.drop_indices.append(i)
        if len(self.drop_indices) > cfg.max_drop > 0:
            self._random.shuffle(self.drop_indices)
            self.drop_indices = sorted(self.drop_indices[:cfg.max_drop])
        if not self.drop_indices and num_iters_done > 0 and \
                self._random.rand() >= self.config.skip_drop:
            self.drop_indices = [self._random.randint(num_iters_done)]

    def _tree_delta(self, it: int, cls: int, factor: float):
        tree = self.trees[it * self.num_tree_per_iteration + cls]
        scaled = tree._replace(leaf_value=tree.leaf_value * factor)
        return scaled

    def _apply_tree_to_scores(self, it: int, cls: int, factor: float,
                              bins_u=None) -> None:
        k = self.num_tree_per_iteration
        idx = it * k + cls
        tree = self.trees[idx]
        lin = self._lin(idx)
        if bins_u is None:
            bins_u = self._train_bins_unpacked()
        vals = self._tree_values(tree, lin, bins_u, self.raw,
                                 self._efb)[:self.num_data] * factor
        if k == 1:
            self.train_score = self.train_score + vals
        else:
            self.train_score = self.train_score.at[:, cls].add(vals)
        for i in range(len(self.valid_sets)):
            vv = self._tree_values(tree, lin, self.valid_bins[i],
                                   self.valid_raws[i]) * factor
            if k == 1:
                self.valid_scores[i] = self.valid_scores[i] + vv
            else:
                self.valid_scores[i] = self.valid_scores[i].at[:, cls].add(vv)

    def _drop_trees(self) -> None:
        # one device unpack per iteration, not per dropped tree
        bins_u = self._train_bins_unpacked() if self.drop_indices else None
        for it in self.drop_indices:
            for cls in range(self.num_tree_per_iteration):
                self._apply_tree_to_scores(it, cls, -1.0, bins_u)
        if not self.config.xgboost_dart_mode:
            self.shrinkage_rate = float(self.config.learning_rate)
        else:
            self.shrinkage_rate = float(self.config.learning_rate) / \
                max(1.0, 1.0 + len(self.drop_indices))

    # reference dart.hpp:127-181 Normalize
    def _normalize(self) -> None:
        cfg = self.config
        k_drop = len(self.drop_indices)
        k = self.num_tree_per_iteration
        if cfg.xgboost_dart_mode:
            new_factor = 1.0  # folded into shrinkage above
            old_factor = k_drop / (k_drop + float(cfg.learning_rate)) \
                if k_drop > 0 else 1.0
        else:
            new_factor = 1.0 / (k_drop + 1.0)
            old_factor = k_drop / (k_drop + 1.0)
        # one device unpack for the whole normalize step (new-tree
        # rescale AND the dropped-tree old_factor loop below)
        bins_u = self._train_bins_unpacked() \
            if (new_factor != 1.0 or
                (self.drop_indices and old_factor != 1.0)) else None
        # scale the new trees (trained this iter) by new_factor
        for cls in range(k):
            idx = len(self.trees) - k + cls
            tree = self.trees[idx]
            lin = self._lin(idx)
            if new_factor != 1.0:
                # remove over-counted part from scores
                vals = self._tree_values(tree, lin, bins_u,
                                         self.raw, self._efb) \
                    [:self.num_data] * (new_factor - 1.0)
                cls_id = self.tree_class[idx]
                if k == 1:
                    self.train_score = self.train_score + vals
                else:
                    self.train_score = \
                        self.train_score.at[:, cls_id].add(vals)
                for i in range(len(self.valid_sets)):
                    vv = self._tree_values(
                        tree, lin, self.valid_bins[i],
                        self.valid_raws[i]) * (new_factor - 1.0)
                    if k == 1:
                        self.valid_scores[i] = self.valid_scores[i] + vv
                    else:
                        self.valid_scores[i] = \
                            self.valid_scores[i].at[:, cls_id].add(vv)
                self.trees[idx] = tree._replace(
                    leaf_value=tree.leaf_value * new_factor)
                if lin is not None:
                    self.linear_models[idx] = lin._replace(
                        const=lin.const * new_factor,
                        coeff=lin.coeff * new_factor)
        self.tree_weights.append(new_factor)
        # scale dropped trees back in with old_factor
        for it in self.drop_indices:
            for cls in range(k):
                self._apply_tree_to_scores(it, cls, old_factor, bins_u)
                idx = it * k + cls
                self.trees[idx] = self.trees[idx]._replace(
                    leaf_value=self.trees[idx].leaf_value * old_factor)
                lm = self._lin(idx)
                if lm is not None:
                    self.linear_models[idx] = lm._replace(
                        const=lm.const * old_factor,
                        coeff=lm.coeff * old_factor)
            self.tree_weights[it] *= old_factor
        if self.drop_indices:
            Log.debug("DART: dropped %d trees", len(self.drop_indices))


# DART trees already carry their weights inside leaf_value; prediction and
# serialization need no special casing.
