"""Fused multi-tree training: K boosting iterations per device dispatch.

The reference's training loop crosses the host boundary every iteration
(gbdt.cpp:371 TrainOneIter, driven from Python via
LGBM_BoosterUpdateOneIter) — cheap on a local device, but on a remoted
accelerator every crossing pays dispatch/sync latency comparable to the
tree compute itself (measured ~100 ms/tree through the tunnel,
docs/PerfNotes.md round 3). The TPU-native reformulation: the boosting
loop itself is a `lax.scan` whose body grows one tree (or one tree per
class) — objective gradients, bagging/GOSS sampling, quantization,
growth, prune, exact leaf refit and the score update all stay on device
— so the host sees ONE dispatch per K trees and receives the K stacked
TreeArrays plus the advanced scores.

In-scan sampling (round 4): bagging masks are STATELESS — the mask at
iteration `it` depends only on (bagging_seed, it - it % bagging_freq),
so the scan recomputes exactly what the per-iteration path
(gbdt.py:_bagging, reference gbdt.cpp:183-264) stores; GOSS consumes
per-iteration keys passed as scan inputs (the same _next_key sequence
the per-iteration path draws, goss.hpp:76-95), keeping the two paths
bit-identical. Multiclass grows num_class trees per scan step
(gbdt.cpp:371 TrainOneIter's per-class loop).

Eligibility is decided by the caller (GBDT.train_many): serial MXU
growth path, plain gbdt/goss boosting, no L1-family leaf renewal —
every excluded feature falls back to the per-iteration path unchanged.
Validation sets DO ride along (round 5): the stacked block is replayed
over each valid set after the dispatch (stacked_score_traj), giving
the exact per-iteration valid-score trajectory for metric evaluation
and early stopping between dispatches.
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp

__all__ = ["build_fused_train", "stacked_score_traj"]

# Score carries are donated (jax.jit donate_argnames): XLA reuses the
# input buffer for the output instead of allocating a fresh [N] (or
# [N, K]) f32 per block — on TPU the f32 score cache is the largest
# recurring training allocation. The CPU backend cannot honor donation
# and warns on every dispatch; that warning is noise for this
# by-design-portable code path, so it is silenced here and ONLY here.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


@functools.partial(jax.jit, static_argnames=("num_class",),
                   donate_argnames=("score0",))
def stacked_score_traj(stacked, score0, bins, num_bins, missing_is_nan,
                       *, num_class: int = 1):
    """Per-iteration score trajectory of a stacked tree block over a
    binned matrix: scan the K stacked trees from `score0`, returning
    (final score, [K, ...] score after each iteration). This replays
    the per-iteration valid-score updates (gbdt._update_score — the
    reference's AddScore(valid) cadence, score_updater.hpp:21-110) for
    a block trained by the fused scan: leaf values in `stacked` already
    carry shrinkage, so the trajectory is exactly what K train_one_iter
    calls would have left on the valid set, one point per iteration."""
    from ..learner.predict import predict_binned_tree

    def body(s, tr):
        if num_class == 1:
            s = s + predict_binned_tree(tr, bins, num_bins,
                                        missing_is_nan)
        else:
            for cls in range(num_class):
                tcls = jax.tree_util.tree_map(lambda a: a[cls], tr)
                s = s.at[:, cls].add(
                    predict_binned_tree(tcls, bins, num_bins,
                                        missing_is_nan))
        return s, s

    return jax.lax.scan(body, score0, stacked)


def build_fused_train(*, objective, bins, cnt_weight, feature_mask_fn,
                      num_bins, missing_is_nan, is_cat, grower_kwargs,
                      shrinkage: float, extra_seed: int, needs_rng: bool,
                      sample_fn=None, num_class: int = 1,
                      debug: bool = False):
    """Return run(score, it0, k, sample_keys=None) ->
    (score', stacked TreeArrays).

    `objective.get_gradients` must be pure jnp (all built-in objectives
    are); `grower_kwargs` are the static grow_tree_mxu settings
    (GBDT._mxu_grow_kwargs — shared with the per-iteration path);
    `feature_mask_fn(it)` produces the per-iteration feature_fraction
    mask (traced iteration index).

    sample_fn(grad, hess, it, key) -> (grad', hess', cnt) implements
    bagging/GOSS inside the scan (None = no sampling; cnt_weight used).
    For key-consuming samplers (GOSS) the caller passes sample_keys
    [k, 2] — the same keys the per-iteration path would draw.

    num_class > 1 grows one tree per class per step; stacked tree
    leaves gain a leading [k, num_class] shape and score is [N, K].

    debug=True additionally stacks per-tree growth counters
    (fixup_iters, pre_prune_leaves) — the decay instrumentation
    (docs/PerfNotes.md round 4); stacked becomes (trees, counters).
    """
    from ..learner.grower_mxu import grow_tree_mxu
    from ..learner.histogram_mxu import node_values_mxu

    shrink = jnp.float32(shrinkage)
    interpret = bool(grower_kwargs.get("interpret", False))
    # the histogram backend is a static grow arg and must reach the
    # scan already resolved — "auto" here would mean the caller skipped
    # GBDT._resolved_hist_backend and each recompile could re-decide
    if grower_kwargs.get("hist_backend", "mxu") == "auto":
        raise ValueError("build_fused_train requires a resolved "
                         "hist_backend (mxu|pallas|scatter), not 'auto'")

    def one_tree(grad, hess, cnt, fmask, it):
        rng = jax.random.fold_in(jax.random.PRNGKey(extra_seed), it) \
            if needs_rng else None
        out = grow_tree_mxu(
            bins, grad, hess, cnt, fmask, num_bins,
            missing_is_nan, is_cat, rng_key=rng, debug_info=debug,
            **grower_kwargs)
        tree, row_node = out[0], out[1]
        # device-side stand-in for the "no further splits" break: a tree
        # that made no split becomes all-zero and the scan carries on
        # (train_one_iter's ok-zeroing, gbdt.py)
        ok = (tree.num_leaves > 1).astype(jnp.float32)
        tree = tree._replace(leaf_value=tree.leaf_value * (shrink * ok))
        vals = node_values_mxu(row_node, tree.leaf_value,
                               interpret=interpret)
        return tree, vals, (out[2] if debug else None)

    def body(score, xs):
        it, key = xs
        grad, hess = objective.get_gradients(score)
        if sample_fn is not None:
            grad, hess, cnt = sample_fn(grad, hess, it, key)
        else:
            cnt = cnt_weight
        fmask = feature_mask_fn(it)
        if num_class == 1:
            tree, vals, dbg = one_tree(grad, hess, cnt, fmask, it)
            out = (tree, dbg) if debug else tree
            return score + vals, out
        trees, dbgs = [], []
        for cls in range(num_class):
            t, vals, dbg = one_tree(grad[:, cls], hess[:, cls], cnt,
                                    fmask, it)
            score = score.at[:, cls].add(vals)
            trees.append(t)
            dbgs.append(dbg)
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *trees)
        if debug:
            return score, (stacked,
                           jax.tree_util.tree_map(
                               lambda *xs: jnp.stack(xs), *dbgs))
        return score, stacked

    # `score` is donated: the caller hands over its train-score buffer
    # and must treat the passed-in array as consumed (use the returned
    # score'). GBDT.train_many reassigns self.train_score from the
    # result and its fault paths check .is_deleted() before reusing the
    # old buffer — tpulint JIT004 guards the bare-name discipline.
    @functools.partial(jax.jit, static_argnames=("k",),
                       donate_argnames=("score",))
    def run(score, it0, *, k: int, sample_keys=None):
        its = jnp.asarray(it0, jnp.int32) + jnp.arange(k, dtype=jnp.int32)
        if sample_keys is None:
            sample_keys = jnp.zeros((k, 2), jnp.uint32)
        return jax.lax.scan(body, score, (its, sample_keys))

    return run
