"""Fused multi-tree training: K boosting iterations per device dispatch.

The reference's training loop crosses the host boundary every iteration
(gbdt.cpp:371 TrainOneIter, driven from Python via
LGBM_BoosterUpdateOneIter) — cheap on a local device, but on a remoted
accelerator every crossing pays dispatch/sync latency comparable to the
tree compute itself (measured ~100 ms/tree through the tunnel,
docs/PerfNotes.md round 3). The TPU-native reformulation: the boosting
loop itself is a `lax.scan` whose body grows one tree — objective
gradients, quantization, growth, prune, exact leaf refit and the score
update all stay on device — so the host sees ONE dispatch per K trees
and receives the K stacked TreeArrays plus the advanced scores.

Eligibility is decided by the caller (GBDT.train_many): serial MXU
growth path, plain gbdt boosting, single tree per iteration, no bagging
/ GOSS, no validation-score replay, no L1-family leaf renewal — every
excluded feature falls back to the per-iteration path unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["build_fused_train"]


def build_fused_train(*, objective, bins, cnt_weight, feature_mask_fn,
                      num_bins, missing_is_nan, is_cat, grower_kwargs,
                      shrinkage: float, extra_seed: int, needs_rng: bool):
    """Return run(score, it0, k) -> (score', stacked TreeArrays).

    `objective.get_gradients` must be pure jnp (all built-in objectives
    are); `grower_kwargs` are the static grow_tree_mxu settings
    (GBDT._mxu_grow_kwargs — shared with the per-iteration path);
    `feature_mask_fn(it)` produces the per-iteration feature_fraction
    mask (traced iteration index).
    """
    from ..learner.grower_mxu import grow_tree_mxu
    from ..learner.histogram_mxu import node_values_mxu

    shrink = jnp.float32(shrinkage)
    interpret = bool(grower_kwargs.get("interpret", False))

    def body(score, it):
        grad, hess = objective.get_gradients(score)
        fmask = feature_mask_fn(it)
        rng = jax.random.fold_in(jax.random.PRNGKey(extra_seed), it) \
            if needs_rng else None
        tree, row_node = grow_tree_mxu(
            bins, grad, hess, cnt_weight, fmask, num_bins,
            missing_is_nan, is_cat, rng_key=rng, **grower_kwargs)
        # device-side stand-in for the "no further splits" break: a tree
        # that made no split becomes all-zero and the scan carries on
        # (train_one_iter's ok-zeroing, gbdt.py)
        ok = (tree.num_leaves > 1).astype(jnp.float32)
        tree = tree._replace(leaf_value=tree.leaf_value * (shrink * ok))
        vals = node_values_mxu(row_node, tree.leaf_value,
                               interpret=interpret)
        return score + vals, tree

    @functools.partial(jax.jit, static_argnames=("k",))
    def run(score, it0, *, k: int):
        its = jnp.asarray(it0, jnp.int32) + jnp.arange(k, dtype=jnp.int32)
        return jax.lax.scan(body, score, its)

    return run
