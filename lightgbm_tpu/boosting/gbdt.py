"""GBDT training driver: the TrainOneIter loop, bagging, scores, eval.

Redesign of the reference boosting layer (src/boosting/gbdt.cpp:266-572,
gbdt.h:35): objective gradients, bagging/GOSS, per-class tree training,
shrinkage, learner-side score updates and metric evaluation. TPU-shape
differences:

- gradients/hessians/scores are device-resident; the objective runs in JAX
  so there is no H2D gradient copy per iteration (contrast
  cuda_single_gpu_tree_learner.cpp:79-80).
- bagging is a mask, not an index subset (gbdt.cpp:183-264 copies subsets;
  masks keep shapes static and HBM traffic sequential). The `cnt_weight`
  channel of the histogram makes min_data_in_leaf count in-bag rows only.
- trees accumulate on device as stacked arrays for fast forest prediction;
  host copies materialize lazily for serialization.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..data import BinnedDataset
from ..learner.grower import TreeArrays, grow_tree
from ..learner.predict import predict_binned_tree
from ..learner.renew import renew_tree_output
from ..learner.split import SplitHyperParams
from ..metrics import Metric
from ..objectives import ObjectiveFunction
from ..observability import registry as _obs
from ..observability.profile import profiler as _profiler
from ..reliability import counters, faults, guards, retry_call
from ..utils.log import Log, LightGBMError
from ..utils.timer import global_timer
from ..utils.file_io import open_file

__all__ = ["GBDT", "create_boosting"]

_FAULT_ENV = "LGBM_TPU_INJECT_FUSED_FAULT"


def _maybe_inject_fused_fault(env: str = _FAULT_ENV):
    """Fail upcoming fused dispatches on request, so the bench/fallback
    robustness paths can be exercised without a real device outage. Env
    format: "N" (fail the next N dispatches) or "S:N" (let S dispatches
    through, then fail N).

    Shim over the unified fault registry (reliability/faults.py): the
    env var is only an initial-schedule *source* — the countdown lives
    in the in-process registry and the environment is never mutated
    (the old counter-in-env leaked state across tests and raced under
    threads). The default env maps to the registered `fused_dispatch`
    site; other env names (bench.py's block-fault hook) get their own
    ad-hoc site."""
    site = "fused_dispatch" if env == _FAULT_ENV else f"env:{env}"
    faults.schedule_from_env(site, env)
    faults.inject(site)


class GBDT:
    """Gradient Boosted Decision Trees driver (reference gbdt.h:35)."""

    def __init__(self, config: Config, train_set: Optional[BinnedDataset],
                 objective: Optional[ObjectiveFunction],
                 train_metrics: Optional[List[Metric]] = None):
        self.config = config
        self.objective = objective
        self.train_set = train_set
        self.train_metrics = train_metrics or []
        self.shrinkage_rate = float(config.learning_rate)
        self.num_class = max(int(config.num_class), 1)
        self.num_tree_per_iteration = (
            objective.num_model_per_iteration if objective else self.num_class)
        self.iter_ = 0
        self.trees: List[TreeArrays] = []       # flat: iter*K + class
        self.tree_class: List[int] = []
        self.linear_models: List = []           # LinearLeaves or None, per tree
        self._pending_nleaves = None            # device scalar, lagged poll
        self._exact_stop_poll = False
        self._stop_poll_every = 8               # host-sync amortization
        self.models_meta: List[dict] = []       # host-side per-tree info
        self.valid_sets: List[BinnedDataset] = []
        self.valid_names: List[str] = []
        self.valid_metrics: List[List[Metric]] = []
        self.best_iter = -1
        self._rng_key = jax.random.PRNGKey(int(config.seed))
        # checkpoint resume: iter_ stays ABSOLUTE over the merged model
        # (RNG fold-ins and bagging cadence key off it) while the trees
        # list only holds this instance's trees; the offset reconciles
        # the two for current_iteration()/rollback accounting
        self._iter_offset = 0

        if train_set is not None:
            self._setup_train(train_set)

    # ------------------------------------------------------------------
    def _setup_train(self, ds: BinnedDataset) -> None:
        cfg = self.config
        self.num_data = ds.num_data
        self.num_bins_d = jnp.asarray(ds.num_bins)
        self.missing_is_nan_d = jnp.asarray(ds.missing_types == 2)
        self.is_cat_d = jnp.asarray(ds.is_categorical)
        self.bmax = int(ds.num_bins.max()) if ds.num_features else 2
        # EFB (reference feature_group.h:25; efb.py): bundle mutually-
        # exclusive sparse features so histogram work scales with the
        # bundle count, not the raw feature count. Only the device bin
        # matrix changes shape; growers translate through static tables.
        self._efb = None
        try:
            nproc_now = jax.process_count()
        except RuntimeError:
            nproc_now = 1
        if cfg.enable_bundle and not cfg.linear_tree and ds.num_features:
            from ..efb import build_plan, bundle_matrix, make_device_tables
            plan_bins = np.asarray(ds.bins)
            if nproc_now > 1:
                # the greedy plan must be IDENTICAL on every rank or the
                # SPMD programs diverge. Same recipe as distributed bin-
                # mapper construction (dataset_loader.cpp:722-807):
                # deterministic fixed-size local row sample -> allgather
                # -> every rank plans over the identical pooled sample.
                from ..parallel.comm import guarded_allgather
                k_samp = max(1, 20000 // nproc_now)
                rs = np.random.RandomState(13)
                n_loc = plan_bins.shape[0]
                idx = rs.choice(n_loc, k_samp, replace=n_loc < k_samp)
                pooled = guarded_allgather(plan_bins[np.sort(idx)],
                                           label="efb_plan_sample")
                plan_bins = pooled.reshape(-1, plan_bins.shape[1])
            plan = build_plan(plan_bins, ds.num_bins,
                              ds.default_bins,
                              np.asarray(ds.is_categorical),
                              max_bundle_bins=256)
            if plan is not None and plan.effective:
                # feature metadata attaches the segmented-scan tables
                # (split_bundled.py); without them the MXU path falls
                # back to per-pass expansion
                seg = cfg.efb_segmented_scan
                self._efb = make_device_tables(
                    plan, ds.default_bins,
                    num_bins=ds.num_bins if seg else None,
                    missing_is_nan=(ds.missing_types == 2) if seg
                    else None,
                    is_cat=np.asarray(ds.is_categorical) if seg else None)
                self.bins = jnp.asarray(bundle_matrix(
                    np.asarray(ds.bins), plan))
        if self._efb is None:
            self.bins = jnp.asarray(ds.bins)
        k = self.num_tree_per_iteration
        shape = (self.num_data,) if k == 1 else (self.num_data, k)
        self.train_score = jnp.zeros(shape, jnp.float32)
        if ds.metadata.init_score is not None:
            init = np.asarray(ds.metadata.init_score, np.float32)
            self.train_score = jnp.asarray(init.reshape(shape))
            self._has_init_score = True
        else:
            self._has_init_score = False
        # monotone constraints (original-feature order -> used-feature order)
        self._monotone = None
        has_monotone = False
        if cfg.monotone_constraints:
            mc = np.zeros(ds.num_total_features, np.int32)
            arr = np.asarray(cfg.monotone_constraints, np.int32)
            mc[:len(arr)] = arr
            used = np.asarray(ds.used_features, np.int64)
            if np.any(mc[used] != 0):
                self._monotone = jnp.asarray(mc[used])
                has_monotone = True
            if cfg.monotone_constraints_method != "basic":
                Log.warning("monotone_constraints_method=%s approximated by "
                            "'basic' on TPU",
                            cfg.monotone_constraints_method)
        # interaction constraints (groups of original feature indices)
        self._interaction_groups = None
        if cfg.interaction_constraints:
            orig2used = {int(o): j
                         for j, o in enumerate(ds.used_features)}
            groups = []
            for grp in cfg.interaction_constraints:
                if not isinstance(grp, (list, tuple)):
                    grp = [grp]
                groups.append(tuple(sorted(
                    orig2used[int(fi)] for fi in grp
                    if int(fi) in orig2used)))
            self._interaction_groups = tuple(g for g in groups if g)
        self._forced = self._load_forced_splits(cfg, ds)
        self._setup_cegb(cfg, ds)
        self.hp = SplitHyperParams(
            lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
            min_gain_to_split=cfg.min_gain_to_split,
            min_data_in_leaf=cfg.min_data_in_leaf,
            min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
            max_delta_step=cfg.max_delta_step,
            path_smooth=cfg.path_smooth, cat_l2=cfg.cat_l2,
            cat_smooth=cfg.cat_smooth,
            max_cat_threshold=cfg.max_cat_threshold,
            max_cat_to_onehot=cfg.max_cat_to_onehot,
            min_data_per_group=cfg.min_data_per_group,
            has_monotone=has_monotone,
            monotone_penalty=cfg.monotone_penalty,
            extra_trees=cfg.extra_trees,
            has_categorical=bool(np.any(ds.is_categorical)))
        # intermediate/advanced monotone methods need leaf-wise growth
        # with per-pass bound recomputation — portable grower only
        self._mono_nonbasic = (
            cfg.monotone_constraints is not None and
            cfg.monotone_constraints_method != "basic")
        self._mono_method = (cfg.monotone_constraints_method
                             if self._mono_nonbasic else "basic")
        self._setup_parallel(cfg)
        # TPU kernel choice (serial learner; the data-parallel sharded
        # path picks mxu in _setup_parallel, other modes keep the
        # portable scatter grower): "mxu" = sort/gather-free
        # one-hot-matmul growth (grower_mxu.py), "pallas" = grouped-rows
        # histogram kernel, "scatter" = pure-XLA segment adds
        backend = jax.default_backend()
        if cfg.use_pallas and self._grower is None and backend != "cpu":
            # the mxu kernels carry bin values through bf16 matmul
            # operands, exact only for max_bin <= 256. EFB rides the mxu
            # path too (bundle-space histograms + per-pass expansion)
            # when the bundle bins fit bf16 exactness and the expanded
            # scan tensor fits a device-memory budget.
            excl = self._mxu_exclusions(cfg)
            if not excl:
                self._hist_impl = "mxu"
            else:
                self._hist_impl = "pallas" if self._efb is None \
                    else "scatter"
                # the EFB exclusion is the MEASURED-best default (the
                # portable grower wins on bundled data, PerfNotes r4)
                # — only the genuine perf cliffs warn
                hard = [r for r in excl if r != "efb config"]
                if hard:
                    Log.warning(
                        "training runs on the portable %s grower (MXU "
                        "path excluded by: %s) — expect ~10x lower "
                        "throughput on TPU", self._hist_impl,
                        ", ".join(hard))
        else:
            self._hist_impl = "scatter"
        Log.debug("Tree kernel path: %s (backend=%s)", self._hist_impl,
                  backend)
        # histogram backend for the MXU growth path (config.hist_backend)
        # — resolved lazily in _resolved_hist_backend() because "auto"
        # autotunes on the device bin matrix, which must happen after
        # objective binding and 4-bit packing are final
        self._hist_backend = None
        self._hist_autotune = None
        if cfg.use_quantized_grad and self._hist_impl != "mxu" and \
                not getattr(self, "_sharded_mxu", False):
            Log.warning("use_quantized_grad only accelerates the MXU "
                        "growth path (active: %s); training runs "
                        "full-precision", self._hist_impl)
        # 4-bit packed bin storage (reference dense_bin.hpp:42): when
        # every feature fits a nibble, re-upload the bin matrix packed
        # two-features-per-byte; the MXU kernels unpack in VMEM. Exact.
        self._packed4 = False
        if (self._hist_impl == "mxu" and cfg.bin_pack_4bit and
                self.bmax <= 16 and not cfg.linear_tree and
                self._efb is None):
            from ..learner.histogram_mxu import (fits_v2, pack_bins_4bit)
            # packing only pays when every growth pass stays on the
            # fused/v2 kernels (VMEM-resident histograms); the v1
            # wide-feature fallback would unpack the whole matrix per
            # call — worse than unpacked storage
            L_g = int(np.ceil(cfg.num_leaves * cfg.growth_overshoot)) \
                if cfg.growth_overshoot >= 1.0 else cfg.num_leaves
            if fits_v2(L_g + 1, ds.num_features, self.bmax,
                       cfg.gpu_use_dp, cfg.use_quantized_grad):
                # pack_bins_4bit refuses (None + warning) if any bin id
                # exceeds 15 — keep uint8 storage rather than truncate
                packed = pack_bins_4bit(ds.bins)
                if packed is not None:
                    self.bins = None  # free the unpacked copy first
                    self.bins = jnp.asarray(packed)
                    self._packed4 = True
                    Log.debug("bin matrix packed 4-bit: [%d, %d] bytes",
                              ds.num_data, self.bins.shape[1])
        # linear trees (reference LinearTreeLearner; raw values required,
        # dataset.cpp:418-420)
        self._linear = bool(cfg.linear_tree)
        self.raw = None
        self.valid_raws: List = []
        if self._linear:
            # config validation already forces tree_learner=serial for
            # linear trees, so self._grower is always None here
            if ds.raw is None:
                raise ValueError(
                    "linear_tree=true requires raw feature values; "
                    "reconstruct the dataset with linear_tree in params")
            else:
                self.raw = jnp.asarray(ds.raw)
                depth_cap = cfg.max_depth if cfg.max_depth > 0 else 31
                self._lin_dmax = max(1, min(ds.num_features, depth_cap, 31))
        self._bag_mask = jnp.ones(self.num_data, jnp.float32)
        self._boosted_from_average = [False] * k
        if self.objective is not None:
            self.objective.init(ds.metadata, ds.num_data)

    def _setup_cegb(self, cfg, ds) -> None:
        """Cost-effective gradient boosting penalties (reference
        cost_effective_gradient_boosting.hpp:23)."""
        self._cegb_cfg = None
        self._cegb_state = None
        lazy = cfg.cegb_penalty_feature_lazy
        coupled = cfg.cegb_penalty_feature_coupled
        has_lazy = bool(lazy)
        has_coupled = bool(coupled)
        if cfg.cegb_penalty_split <= 0 and not has_lazy and not has_coupled:
            return
        from ..learner.grower import CegbParams
        f = ds.num_features
        used = np.asarray(ds.used_features, np.int64)

        def _per_used(pen):
            pen = np.asarray(pen, np.float32)
            if len(pen) != ds.num_total_features:
                # the reference requires one penalty per feature
                # (config check on cegb_penalty_feature_* size)
                raise ValueError(
                    f"cegb per-feature penalty has {len(pen)} entries but "
                    f"the dataset has {ds.num_total_features} features")
            return jnp.asarray(pen[used])

        self._cegb_cfg = CegbParams(
            tradeoff=float(cfg.cegb_tradeoff),
            penalty_split=float(cfg.cegb_penalty_split),
            has_coupled=has_coupled, has_lazy=has_lazy)
        self._cegb_state = (
            _per_used(coupled) if has_coupled else jnp.zeros(f, jnp.float32),
            _per_used(lazy) if has_lazy else jnp.zeros(f, jnp.float32),
            jnp.zeros(f, bool),
            jnp.zeros((ds.num_data, f) if has_lazy else (1, 1), bool))

    @staticmethod
    def _load_forced_splits(cfg, ds):
        """Flatten the forced-splits JSON tree (reference ForceSplits,
        serial_tree_learner.cpp:459; JSON read at serial_tree_learner.cpp:53)
        into spec arrays (feature, threshold bin, left/right spec idx)."""
        fname = getattr(cfg, "forcedsplits_filename", "")
        if not fname:
            return None
        import json
        with open_file(fname) as fh:
            root = json.load(fh)
        if not root:
            return None
        orig2used = {int(o): j for j, o in enumerate(ds.used_features)}
        feat, tbin, left, right = [], [], [], []
        nodes = [root]          # BFS; spec idx = position in this list
        i = 0
        while i < len(nodes):
            nd = nodes[i]
            fo = int(nd["feature"])
            if fo not in orig2used:
                Log.warning("forced split on unused feature %d ignored", fo)
                feat.append(-1)
                tbin.append(0)
                left.append(-1)
                right.append(-1)
                i += 1
                continue
            fu = orig2used[fo]
            mapper = ds.mappers[fu]
            if mapper.is_categorical:
                Log.warning("forced split on categorical feature %d ignored "
                            "(numerical thresholds only)", fo)
                feat.append(-1)
                tbin.append(0)
                left.append(-1)
                right.append(-1)
                i += 1
                continue
            feat.append(fu)
            tbin.append(int(mapper._value_to_bin_scalar(
                float(nd["threshold"]))))
            for key, out in (("left", left), ("right", right)):
                child = nd.get(key)
                if child:
                    nodes.append(child)
                    out.append(len(nodes) - 1)
                else:
                    out.append(-1)
            i += 1
        if not feat or all(f < 0 for f in feat):
            return None
        return (jnp.asarray(feat, jnp.int32), jnp.asarray(tbin, jnp.int32),
                jnp.asarray(left, jnp.int32), jnp.asarray(right, jnp.int32))

    def _setup_parallel(self, cfg) -> None:
        """Distributed learner setup (reference CreateTreeLearner crossbar,
        tree_learner.cpp:16-64, + Network::Init)."""
        self.comm = None
        self.mesh = None
        self._grower = None
        self._row_pad = 0
        self._bins_ft = None
        if cfg.tree_learner == "serial":
            return
        if cfg.num_machines > 1:
            # reference Network::Init from the machine list
            # (application.cpp:165); here a jax.distributed rendezvous —
            # afterwards jax.devices() spans all hosts and the mesh
            # collectives ride DCN between them
            from ..parallel.mesh import setup_multihost
            setup_multihost(cfg.num_machines, cfg.machines,
                            cfg.machine_list_filename,
                            cfg.local_listen_port)
        _setup_t0 = time.time()
        ndev = cfg.num_devices if cfg.num_devices > 0 else len(jax.devices())
        ndev = min(ndev, len(jax.devices()))
        if ndev <= 1:
            Log.warning("tree_learner=%s requested but only one device "
                        "visible; falling back to serial", cfg.tree_learner)
            return
        from ..parallel import CommSpec, make_mesh
        from ..distributed.crossbar import (create_tree_learner,
                                            resolve_learner)
        from jax.sharding import NamedSharding, PartitionSpec as P
        self._nproc = jax.process_count()
        if self._nproc > 1:
            from ..reliability.watchdog import maybe_start_watchdog
            maybe_start_watchdog(cfg)
        if self._nproc > 1 and cfg.tree_learner != "data":
            raise ValueError(
                "multi-machine training supports tree_learner=data "
                "(rows pre-partitioned per machine, reference "
                "dataset_loader.cpp:560-592); got %r" % cfg.tree_learner)
        self.mesh = make_mesh(ndev)
        # crossbar resolution (distributed/crossbar.py, the reference
        # CreateTreeLearner factory): the MXU gate picks the device row,
        # cfg.distributed_hist_agg the histogram-merge column — with the
        # safety downgrades to psum applied in ONE place
        excl = self._mxu_exclusions(cfg)
        use_mxu = (cfg.use_pallas and jax.default_backend() != "cpu" and
                   cfg.tree_learner == "data" and not excl)
        spec = resolve_learner(
            cfg.tree_learner, device="mxu" if use_mxu else "scatter",
            hist_agg=cfg.distributed_hist_agg,
            num_features=int(self.bins.shape[1]), top_k=cfg.top_k,
            nproc=self._nproc, has_efb=self._efb is not None,
            mono_rescan=self._mono_nonbasic)
        self.comm = CommSpec(axis="data", mode=spec.mode,
                             num_devices=ndev, top_k=cfg.top_k,
                             hist_agg=spec.hist_agg)
        if self.comm.mode in ("data", "voting"):
            ndev_local = max(1, ndev // self._nproc)
            if self._nproc > 1:
                # global shape is inferred from the local shard, so all
                # machines pad to the LARGEST partition (padded rows
                # carry zero grad/hess/count — they contribute nothing)
                from ..parallel.comm import guarded_allgather
                sizes = guarded_allgather(
                    np.asarray(self.num_data, np.int64),
                    label="row_pad_sizes")
                target = int(-(-int(sizes.max()) // ndev_local)
                             * ndev_local)
                self._row_pad = target - self.num_data
            else:
                self._row_pad = (-self.num_data) % ndev_local
            if self._row_pad:
                self.bins = jnp.pad(self.bins,
                                    ((0, self._row_pad), (0, 0)))
            if self._nproc > 1:
                # keep this machine's rows for local score updates /
                # metrics (reference ranks evaluate on their partition)
                self._local_bins = self.bins
            self.bins = self._shard_rows(self.bins)
            if self.comm.hist_agg == "reduce_scatter":
                # one-time all_to_all feature-shard transpose: enables
                # the exact reduce-scatter histogram flavor in grow_tree
                from ..distributed.hist_agg import build_feature_shards
                self._bins_ft = build_feature_shards(
                    self.mesh, self.comm, self.bins)
        else:  # feature-parallel replicates rows (docs/Features.rst:109)
            self.bins = jax.device_put(
                self.bins, NamedSharding(self.mesh, P()))
        hard = [r for r in excl if r != "efb config"]
        if hard and cfg.use_pallas and jax.default_backend() != "cpu" \
                and self.comm.mode == "data":
            Log.warning(
                "data-parallel training runs on the portable grower "
                "inside shard_map (MXU path excluded by: %s) — expect "
                "~10x lower throughput on TPU", ", ".join(hard))
        self._sharded_mxu = use_mxu
        # per-node sampling / extra_trees / quantized rounding need a
        # per-iteration key; it rides into shard_map replicated so every
        # shard samples identically (the reference's cross-machine seed
        # sync, application.cpp:170-175)
        self._sharded_rng = (cfg.feature_fraction_bynode < 1.0 or
                             cfg.extra_trees or cfg.use_quantized_grad)
        if self._cegb_state is not None and \
                self.comm.mode in ("data", "voting"):
            # per-row lazy-charge flags shard with the rows; pad to the
            # sharded row count like bins (padded rows never charge)
            c, l, fu, rfu = self._cegb_state
            if self._row_pad and rfu.shape[0] > 1:
                rfu = jnp.pad(rfu, ((0, self._row_pad), (0, 0)))
            if rfu.shape[0] > 1:
                rfu = self._shard_rows(rfu)
            self._cegb_state = (c, l, fu, rfu)
        self._grower = create_tree_learner(
            spec, self.mesh, self.comm, num_leaves=cfg.num_leaves,
            max_depth=cfg.max_depth, hp=self.hp,
            leafwise=self._mono_nonbasic,
            bmax=self.bmax, monotone=self._monotone,
            monotone_method=self._mono_method,
            interaction_groups=self._interaction_groups,
            feature_fraction_bynode=cfg.feature_fraction_bynode,
            with_rng=self._sharded_rng,
            forced=self._forced, cegb_cfg=self._cegb_cfg,
            with_cegb_state=self._cegb_cfg is not None,
            efb=self._efb, with_bins_ft=self._bins_ft is not None,
            mxu_kwargs=dict(
                hist_double_prec=cfg.gpu_use_dp,
                tail_split_cap=cfg.tail_split_cap,
                hist_subtraction=cfg.hist_subtraction,
                overshoot=cfg.growth_overshoot,
                bridge_gate=cfg.growth_bridge_gate,
                quantized_grad=cfg.use_quantized_grad,
                # const-hessian stays OFF for the sharded learner: its
                # kwargs are baked here, BEFORE objective.init() binds
                # sample weights, so the _const_hessian() gate cannot
                # be evaluated safely yet (a weighted dataset would get
                # the fast path wrongly enabled and train silently
                # wrong hessians)
                const_hessian=0.0))
        Log.info("Distributed learner: %s-parallel over %d devices%s "
                 "(hist_agg=%s)", self.comm.mode, ndev,
                 " (mxu)" if use_mxu else "", self.comm.hist_agg)
        _obs.record_distributed_setup(
            world=ndev * max(1, self._nproc),
            feature_shard_width=(int(self._bins_ft.shape[1]) // ndev
                                 if self._bins_ft is not None else 0),
            wall_seconds=time.time() - _setup_t0)

    def _shard_rows(self, arr):
        """Row-sharded global array over the mesh. Single-process: a
        device_put; multi-process: this process's rows become its shard
        of the global array (each machine holds its own partition, the
        reference's pre-partitioned load, dataset_loader.cpp:560-592)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(self.mesh, P("data"))
        if getattr(self, "_nproc", 1) > 1:
            return jax.make_array_from_process_local_data(
                sh, np.asarray(arr))
        return jax.device_put(arr, sh)

    def _local_rows(self, arr) -> jax.Array:
        """This process's rows of a row-sharded global array (index
        order), for the host-local score/metric bookkeeping."""
        if getattr(self, "_nproc", 1) <= 1:
            return arr
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        # shards live on different local devices; hop through host
        return jnp.asarray(np.concatenate(
            [np.asarray(s.data) for s in shards]))

    def _mxu_exclusions(self, cfg) -> List[str]:
        """Why the MXU growth path cannot be used (empty = usable).
        Single source for the serial kernel choice and the sharded
        use_mxu gate so the two growers can never drift apart. Forced
        splits and coupled/split CEGB ride the MXU path (round 4); only
        the lazy per-row CEGB penalty, non-basic monotone methods, wide
        bins, and unsuited EFB configs stay portable."""
        # the expanded-tensor budget only binds on the expansion
        # fallback; the segmented scan never materializes it
        efb_ok = self._efb is None or (
            cfg.efb_use_mxu and self._efb.bundle_bmax <= 256 and
            (self._efb.scan is not None or
             self._mxu_expand_bytes(cfg) <= 1 << 30))
        return [r for r, hit in [
            ("max_bin > 256", self.bmax > 256),
            ("monotone_constraints_method", self._mono_nonbasic),
            ("cegb_penalty_feature_lazy",
             self._cegb_cfg is not None and self._cegb_cfg.has_lazy),
            ("efb config", not efb_ok)] if hit]

    def _mxu_expand_bytes(self, cfg) -> int:
        """Per-pass expanded scan tensor size under EFB on the MXU path
        ([s_max, F, bmax, 3] f32)."""
        import math as _math
        over = cfg.growth_overshoot if cfg.growth_overshoot >= 1.0 else 1.0
        s_max = int(_math.ceil(cfg.num_leaves * over)) + 1
        f = int(self.num_bins_d.shape[0])
        return s_max * f * self.bmax * 3 * 4

    def _const_hessian(self) -> float:
        """Constant-hessian fast-path gate (reference IsConstantHessian,
        objective_function.h:42): per-row hessians are exactly 1 x the
        count weight, so the kernels can drop the hessian channel and
        reconstruct it as the count — one fewer histogram dot channel
        and exact hessian sums. GOSS re-weights hessians independently
        of the count channel (amplified rows count 1), and user weights
        ride the hessian but not cnt_weight — both break the
        h == const x cnt identity, so they gate it off. Bagging keeps
        it (the mask scales hessian AND count identically). Must be
        evaluated AFTER objective.init() has bound weights.

        A custom objective (Booster.update(fobj=...)) supplies
        arbitrary per-row hessians, so the bound objective's
        is_constant_hessian promise no longer describes the gradients
        actually trained on — the reference neutralizes this by
        resetting objective to "none" in engine.train; the direct
        update(fobj) path flips `_custom_objective` instead (see
        set_custom_objective)."""
        if getattr(self, "_custom_objective", False):
            return 0.0
        if (self.objective is not None and
                getattr(self.objective, "is_constant_hessian", False) and
                getattr(self.objective, "weight", None) is None and
                self.config.boosting != "goss"):
            # the objective owns the actual constant (1.0 for the L1/L2
            # family, but e.g. a scaled-L2 objective declares its own) —
            # the kernels reconstruct hessian sums as const x count, so
            # a hardcoded 1.0 here would silently mis-train any
            # non-unit constant-hessian objective on the fast path
            return float(getattr(self.objective,
                                 "constant_hessian_value", 1.0))
        return 0.0

    def set_custom_objective(self) -> None:
        """Mark this booster as trained (at least once) on user-supplied
        gradients. Drops the constant-hessian fast path — the kernels
        would otherwise reconstruct hessian sums from row counts and
        silently mis-train on any fobj whose hessian isn't exactly the
        count weight — and invalidates caches that baked the old gate
        (the fused scan closure and the analytic MAC estimate)."""
        if not getattr(self, "_custom_objective", False):
            self._custom_objective = True
            self._fused_run = None
            self._obs_tree_macs = None

    def _resolved_hist_backend(self) -> str:
        """Resolve config.hist_backend to a concrete kernel for
        grow_tree_mxu. The backend is a static (jit) argument, so
        resolution happens host-side before the first dispatch and the
        answer is pinned for the run.

        "auto" considers the Pallas scatter kernel only in the
        quantized posture — there integer histogram sums make the two
        backends bit-identical (byte-equal model.txt either way), so
        the autotuned choice is purely a speed knob. Exact mode differs
        in last-ulp summation order, so auto pins mxu and switching
        requires an explicit hist_backend. EFB growth has no scatter
        wiring (bundle-space routing stays on the mxu sweep), and on
        CPU hosts there is nothing real to time — both pin mxu."""
        if self._hist_backend is not None:
            return self._hist_backend
        cfg = self.config
        hb = cfg.hist_backend
        timings: dict = {}
        autotuned = False
        if self._efb is not None and hb not in ("auto", "mxu"):
            Log.warning("hist_backend=%s has no EFB bundle-space "
                        "wiring; using mxu", hb)
            hb = "mxu"
        elif hb == "auto":
            if (self._efb is not None or
                    jax.default_backend() == "cpu" or
                    not cfg.hist_autotune or
                    not cfg.use_quantized_grad):
                hb = "mxu"
            else:
                import math as _math
                from ..learner.grower_mxu import (_kernel_cap,
                                                  autotune_hist_backend)
                over = cfg.growth_overshoot \
                    if cfg.growth_overshoot >= 1.0 else 1.0
                s_max = int(_math.ceil(cfg.num_leaves * over)) + 1
                s_rep = max(2, _kernel_cap(s_max)
                            if cfg.hist_subtraction else s_max)
                hb, timings = autotune_hist_backend(
                    self.bins, num_slots=s_rep, bmax=self.bmax,
                    num_features=(int(self.num_bins_d.shape[0])
                                  if self._packed4 else 0),
                    double_prec=cfg.gpu_use_dp, quantized=True,
                    const_hess=self._const_hessian())
                autotuned = True
                Log.info("hist_backend=auto picked %s (%s)", hb,
                         ", ".join("%s=%.2fms" % kv
                                   for kv in sorted(timings.items())))
        self._hist_backend = hb
        self._hist_autotune = {"choice": hb, "autotuned": autotuned,
                               "timings_ms": dict(timings)}
        _obs.record_hist_autotune(hb, timings, autotuned)
        return hb

    def _mxu_grow_kwargs(self):
        """Static grow_tree_mxu settings — single source shared by the
        per-iteration path (_grow) and the fused scan (_build_fused) so
        the two cannot drift apart."""
        cfg = self.config
        return dict(
            efb=self._efb, forced=self._forced, cegb_cfg=self._cegb_cfg,
            const_hessian=self._const_hessian(),
            num_leaves=cfg.num_leaves, max_depth=cfg.max_depth,
            hp=self.hp, bmax=self.bmax, monotone=self._monotone,
            interaction_groups=self._interaction_groups,
            feature_fraction_bynode=cfg.feature_fraction_bynode,
            hist_double_prec=cfg.gpu_use_dp,
            tail_split_cap=cfg.tail_split_cap,
            hist_subtraction=cfg.hist_subtraction,
            overshoot=cfg.growth_overshoot,
            bridge_gate=cfg.growth_bridge_gate,
            quantized_grad=cfg.use_quantized_grad,
            packed4=self._packed4,
            hist_backend=self._resolved_hist_backend(),
            partition_impl=cfg.partition_impl,
            interpret=getattr(self, "_mxu_interpret", False))

    def _grow(self, g, h, cnt, feature_mask):
        """Growth dispatch with fault injection + retry (sites
        "histogram_build" and, for sharded growth, "collective_psum").
        Injection is host-side: inside the traced grower a raise would
        bake into the compiled program. Retrying `_grow_impl` is safe
        because it only mutates state (CEGB feat_used) after the
        dispatch returns."""
        cfg = self.config

        def _attempt():
            faults.inject("histogram_build")
            if self._grower is None:
                # device-profiler bracket (profile_spans=grow_tree): a
                # live capture forces a block_until_ready so the trace
                # window covers the async device work; otherwise the
                # dispatch stays fully async
                with _profiler.capture("grow_tree") as capturing:
                    out = self._grow_impl(g, h, cnt, feature_mask)
                    if capturing:
                        jax.block_until_ready(out)
                return out
            from ..parallel.comm import check_collective_fault
            from ..reliability.watchdog import active_guard
            check_collective_fault()
            guard = active_guard()
            if guard is None:
                with _profiler.capture("sharded_grow") as capturing:
                    out = self._grow_impl(g, h, cnt, feature_mask)
                    if capturing:
                        jax.block_until_ready(out)
                return out
            # JAX dispatch is async: a peer dying mid-psum hangs the
            # host at the first result *read*, not the launch — so the
            # deadline bracket must cover block_until_ready, or the
            # watchdog would never see the stall
            with guard.guard("sharded_grow"):
                with _profiler.capture("sharded_grow"):
                    out = self._grow_impl(g, h, cnt, feature_mask)
                    jax.block_until_ready(out)
            return out

        return retry_call(_attempt, attempts=cfg.retry_max_attempts,
                          backoff_ms=cfg.retry_backoff_ms,
                          backoff_max_ms=cfg.retry_backoff_max_ms,
                          site="histogram_build")

    def _grow_impl(self, g, h, cnt, feature_mask):
        """Dispatch serial vs sharded growth; returns (tree, row_node[:N])."""
        cfg = self.config
        needs_rng = (self.hp.extra_trees or
                     cfg.feature_fraction_bynode < 1.0 or
                     cfg.use_quantized_grad)
        rng_key = jax.random.fold_in(
            jax.random.PRNGKey(cfg.extra_seed), self.iter_) \
            if needs_rng else None
        if self._grower is None and self._hist_impl == "mxu":
            if cfg.level_pipeline:
                # staged per-level dispatch (byte-identical to the
                # monolith; grower_pipeline.py falls back on its own
                # ineligible configs)
                from ..learner.grower_pipeline import grow_tree_pipelined
                out = grow_tree_pipelined(
                    self.bins, g, h, cnt, feature_mask, self.num_bins_d,
                    self.missing_is_nan_d, self.is_cat_d,
                    lookahead=cfg.level_pipeline_lookahead,
                    iteration=self.iter_,
                    rng_key=rng_key, cegb_state=self._cegb_state,
                    **self._mxu_grow_kwargs())
            else:
                from ..learner.grower_mxu import grow_tree_mxu
                out = grow_tree_mxu(
                    self.bins, g, h, cnt, feature_mask, self.num_bins_d,
                    self.missing_is_nan_d, self.is_cat_d,
                    rng_key=rng_key, cegb_state=self._cegb_state,
                    **self._mxu_grow_kwargs())
            if self._cegb_cfg is not None:
                tree, row_node, (fu, rfu) = out
                self._cegb_state = (self._cegb_state[0],
                                    self._cegb_state[1], fu, rfu)
                return tree, row_node
            return out
        if self._grower is None:
            out = grow_tree(
                self.bins, g, h, cnt, feature_mask, self.num_bins_d,
                self.missing_is_nan_d, self.is_cat_d,
                num_leaves=cfg.num_leaves,
                max_depth=cfg.max_depth, hp=self.hp,
                leafwise=self._mono_nonbasic, bmax=self.bmax,
                monotone=self._monotone,
                interaction_groups=self._interaction_groups,
                feature_fraction_bynode=cfg.feature_fraction_bynode,
                rng_key=rng_key, hist_impl=self._hist_impl,
                partition_impl=cfg.partition_impl,
                forced=self._forced, cegb_cfg=self._cegb_cfg,
                cegb_state=self._cegb_state,
                monotone_method=self._mono_method, efb=self._efb)
            if self._cegb_cfg is not None:
                tree, row_node, (fu, rfu) = out
                # feature-used flags persist across the whole model
                # (is_feature_used_in_split_ / is_feature_used_)
                self._cegb_state = (self._cegb_state[0],
                                    self._cegb_state[1], fu, rfu)
                return tree, row_node
            return out
        if self._row_pad:
            g = jnp.pad(g, (0, self._row_pad))
            h = jnp.pad(h, (0, self._row_pad))
            cnt = jnp.pad(cnt, (0, self._row_pad))
        if self.comm.mode in ("data", "voting") and \
                getattr(self, "_nproc", 1) > 1:
            g, h, cnt = (self._shard_rows(a) for a in (g, h, cnt))
        extra = ()
        if getattr(self, "_sharded_rng", False):
            extra = (jax.random.fold_in(
                jax.random.PRNGKey(cfg.extra_seed), self.iter_),)
        if self._cegb_cfg is not None:
            extra = extra + (self._cegb_state,)
        if getattr(self, "_bins_ft", None) is not None:
            extra = extra + (self._bins_ft,)
        with self.mesh:
            out = self._grower(
                self.bins, g, h, cnt, feature_mask, self.num_bins_d,
                self.missing_is_nan_d, self.is_cat_d, *extra)
        if self._cegb_cfg is not None:
            tree, row_node, (fu, rfu) = out
            self._cegb_state = (self._cegb_state[0], self._cegb_state[1],
                                fu, rfu)
        else:
            tree, row_node = out
        return tree, self._local_rows(row_node)[:self.num_data]

    def _sync_renewed_leaves(self, tree: TreeArrays, row_node, rw
                             ) -> TreeArrays:
        """Multi-machine L1-family leaf renewal sync (reference
        serial_tree_learner.cpp:747-757): each rank renews from its
        local percentiles; the final leaf value is the mean of the
        per-rank values over ranks that hold in-bag rows in the leaf."""
        m1 = tree.leaf_value.shape[0]
        cnts = np.zeros(m1, np.float64)
        np.add.at(cnts, np.asarray(row_node),
                  (np.asarray(rw[:len(row_node)]) > 0).astype(np.float64))
        lv = np.asarray(tree.leaf_value, np.float64)
        has = (cnts > 0).astype(np.float64)
        contrib = np.stack([np.where(has > 0, lv, 0.0), has])
        from ..parallel.comm import guarded_allgather
        total = guarded_allgather(
            contrib, label="leaf_renewal_sync").sum(axis=0)
        nz = np.maximum(total[1], 1.0)
        synced = np.where(total[1] > 0, total[0] / nz, lv)
        is_leaf = np.asarray(tree.is_leaf)
        new_lv = np.where(is_leaf, synced, lv).astype(np.float32)
        return tree._replace(leaf_value=jnp.asarray(new_lv))

    def _train_bins_unpacked(self) -> jax.Array:
        """Training bin matrix in unpacked [N, F] form for cold paths
        (rollback, DART drops) — transient device unpack when packed."""
        if not getattr(self, "_packed4", False):
            return self.bins
        from ..learner.histogram_mxu import unpack_bins_4bit
        return unpack_bins_4bit(self.bins, int(self.num_bins_d.shape[0]))

    def _predict_train_rows(self, tree: TreeArrays) -> jax.Array:
        """Tree outputs for the (unpadded) training rows."""
        bins = self._local_bins if getattr(self, "_nproc", 1) > 1 \
            else self._train_bins_unpacked()
        vals = predict_binned_tree(tree, bins, self.num_bins_d,
                                   self.missing_is_nan_d, self._efb)
        return vals[:self.num_data] if self._row_pad else vals

    def add_valid(self, ds: BinnedDataset, name: str,
                  metrics: List[Metric]) -> None:
        self.valid_sets.append(ds)
        self.valid_names.append(name)
        self.valid_metrics.append(metrics)
        k = self.num_tree_per_iteration
        shape = (ds.num_data,) if k == 1 else (ds.num_data, k)
        score = jnp.zeros(shape, jnp.float32)
        if ds.metadata.init_score is not None:
            score = jnp.asarray(
                np.asarray(ds.metadata.init_score, np.float32).reshape(shape))
        if not hasattr(self, "valid_scores"):
            self.valid_scores: List[jax.Array] = []
            self.valid_bins: List[jax.Array] = []
        self.valid_scores.append(score)
        self.valid_bins.append(jnp.asarray(ds.bins))
        if self._linear:
            if ds.raw is None:
                raise ValueError(
                    "linear_tree model needs raw values on validation "
                    "sets; construct them with linear_tree in params")
            self.valid_raws.append(jnp.asarray(ds.raw))
        else:
            self.valid_raws.append(None)
        # replay existing model on the new valid set
        for ti, (t, cls) in enumerate(zip(self.trees, self.tree_class)):
            vals = self._tree_values(t, self._lin(ti), self.valid_bins[-1],
                                     self.valid_raws[-1])
            vi = len(self.valid_scores) - 1
            if k == 1:
                self.valid_scores[vi] = self.valid_scores[vi] + vals
            else:
                self.valid_scores[vi] = \
                    self.valid_scores[vi].at[:, cls].add(vals)

    # ------------------------------------------------------------------
    # bagging (gbdt.cpp:183-264; GOSS goss.hpp:25-95)
    def _next_key(self) -> jax.Array:
        self._rng_key, sub = jax.random.split(self._rng_key)
        return sub

    def _bagging(self, grad: jax.Array, hess: jax.Array
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        cfg = self.config
        if cfg.boosting == "goss":
            return self._goss(grad, hess)
        if self._needs_bagging() and self.iter_ % cfg.bagging_freq == 0:
            key = jax.random.fold_in(
                jax.random.PRNGKey(cfg.bagging_seed), self.iter_)
            u = jax.random.uniform(key, (self.num_data,))
            if cfg.pos_bagging_fraction < 1.0 or \
                    cfg.neg_bagging_fraction < 1.0:
                pos = self.objective.label > 0
                frac = jnp.where(pos, cfg.pos_bagging_fraction,
                                 cfg.neg_bagging_fraction)
                self._bag_mask = (u < frac).astype(jnp.float32)
            else:
                self._bag_mask = (u < cfg.bagging_fraction) \
                    .astype(jnp.float32)
        mask = self._bag_mask
        if grad.ndim == 2:
            return grad * mask[:, None], hess * mask[:, None], mask
        return grad * mask, hess * mask, mask

    def _goss(self, grad, hess):
        """Gradient-based one-side sampling (goss.hpp:76-95)."""
        cfg = self.config
        top_rate, other_rate = cfg.top_rate, cfg.other_rate
        score_abs = jnp.abs(grad) * hess
        if score_abs.ndim == 2:
            score_abs = score_abs.sum(axis=1)
        n = self.num_data
        top_k = max(1, int(n * top_rate))
        other_k = max(1, int(n * other_rate))
        thresh = jax.lax.top_k(score_abs, top_k)[0][-1]
        is_top = score_abs >= thresh
        key = self._next_key()
        u = jax.random.uniform(key, (n,))
        rest_frac = other_rate / max(1.0 - top_rate, 1e-9)
        is_other = (~is_top) & (u < rest_frac)
        amplify = (1.0 - top_rate) / other_rate
        w = jnp.where(is_top, 1.0, jnp.where(is_other, amplify, 0.0)) \
            .astype(jnp.float32)
        cnt = jnp.where(is_top | is_other, 1.0, 0.0).astype(jnp.float32)
        del other_k
        if grad.ndim == 2:
            return grad * w[:, None], hess * w[:, None], cnt
        return grad * w, hess * w, cnt

    # ------------------------------------------------------------------
    def train_one_iter(self, gradients: Optional[jax.Array] = None,
                       hessians: Optional[jax.Array] = None) -> bool:
        """One boosting iteration (reference TrainOneIter gbdt.cpp:371-449).
        Returns True if training cannot continue (no splits made)."""
        cfg = self.config
        k = self.num_tree_per_iteration
        init_scores = [0.0] * k
        # observability: off path is this one branch; the guard
        # skip-iteration early return below goes unrecorded (rare,
        # and its counters surface in the next record's deltas)
        _orec = _obs.enabled
        if _orec:
            _obs_iter = self.iter_
            _obs_ph0 = global_timer.totals()
            _obs_t0 = time.perf_counter()

        with global_timer.timeit("boosting"):
            if gradients is None or hessians is None:
                for cls in range(k):
                    init_scores[cls] = self._boost_from_average(cls)
                gradients, hessians = self.objective.get_gradients(
                    self.train_score)

        guard = cfg.guard_nonfinite
        prev_scores = None
        if guard != "off":
            # pre-growth rail: non-finite gradients (exploding custom
            # objective, corrupted scores) poison every later iteration
            if not guards.all_finite(gradients, hessians):
                gradients, hessians = self._guard_gradients(
                    guard, gradients, hessians)
                if gradients is None:      # skip_iteration consumed it
                    return False
            # reference for the post-growth rail: scores are immutable
            # JAX arrays, so stashing them is a pair of references, and
            # restoring beats arithmetic rollback (subtracting a NaN
            # tree cannot un-NaN a score)
            prev_scores = (self.train_score,
                           list(getattr(self, "valid_scores", []) or []))

        with global_timer.timeit("bagging"):
            grad, hess, cnt = self._bagging(gradients, hessians)

        should_continue = False
        for cls in range(k):
            g = grad if k == 1 else grad[:, cls]
            h = hess if k == 1 else hess[:, cls]
            with global_timer.timeit("tree_train"):
                feature_mask = self._feature_mask()
                tree, row_node = self._grow(g, h, cnt, feature_mask)
            # a host pull of num_leaves costs a full device round-trip
            # (~hundreds of ms through a remoted accelerator, ready or
            # not). Instead of syncing on the fresh tree, the stop
            # decision reads a PREVIOUS iteration's count, and even that
            # only every _stop_poll_every iterations — each stored count
            # starts an async D2H copy so the eventual int() finds the
            # value already on the host. The fresh tree always takes the
            # normal processing branch — shrinkage, score update, and the
            # device-side `ok` zeroing make a genuine no-split tree a
            # harmless all-zero tree, while a real tree (possible after a
            # dry iteration when bagging resamples) stays fully applied.
            # Stall detection is therefore delayed by up to
            # _stop_poll_every iterations (the extra trees are all-zero —
            # predictions unaffected). Subclasses that average over
            # iteration count (RF) set _exact_stop_poll to keep the
            # reference's immediate stop.
            if (self.iter_ == 0 and len(self.trees) < k) or \
                    self._exact_stop_poll:
                nleaves = int(tree.num_leaves)
                stop_hint = nleaves <= 1
            else:
                prev = self._pending_nleaves
                stop_hint = (prev is not None and
                             self.iter_ % self._stop_poll_every == 0 and
                             int(prev) <= 1)
                nleaves = 2
            pending = tree.num_leaves
            try:
                pending.copy_to_host_async()
            except Exception:
                pass
            self._pending_nleaves = pending
            lin = None
            if nleaves > 1:
                if not stop_hint:
                    should_continue = True
                if self.objective is not None and \
                        self.objective.need_renew_tree_output:
                    rw = cnt if self.objective.weight is None \
                        else cnt * self.objective.weight
                    tree = renew_tree_output(
                        tree, row_node, self.train_score if k == 1
                        else self.train_score[:, cls],
                        jnp.asarray(self.objective.label), rw,
                        self.objective.renew_percentile, cfg.num_leaves)
                    if getattr(self, "_nproc", 1) > 1:
                        tree = self._sync_renewed_leaves(tree, row_node,
                                                         rw)
                if self._linear:
                    from ..learner.linear import fit_linear_leaves
                    with global_timer.timeit("linear_fit"):
                        lin = fit_linear_leaves(
                            tree, row_node, self.raw, g, h, cnt,
                            self.is_cat_d,
                            jnp.float32(cfg.linear_lambda),
                            dmax=self._lin_dmax)
                # shrinkage (tree.cpp Shrinkage): scale leaf outputs and,
                # for linear leaves, consts + coefficients. The `ok`
                # factor zeroes trees that made no split (device-side
                # stand-in for the reference's "no further splits" break)
                ok = (tree.num_leaves > 1).astype(jnp.float32)
                tree = tree._replace(
                    leaf_value=tree.leaf_value * self.shrinkage_rate * ok)
                if lin is not None:
                    lin = lin._replace(
                        const=lin.const * self.shrinkage_rate * ok,
                        coeff=lin.coeff * self.shrinkage_rate * ok)
                with global_timer.timeit("update_score"):
                    self._update_score(tree, row_node, cls, lin)
                if abs(init_scores[cls]) > 1e-35:
                    # AddBias (gbdt.cpp:416-417): fold init into tree 0
                    tree = tree._replace(
                        leaf_value=jnp.where(
                            tree.split_feature < 0,
                            tree.leaf_value + init_scores[cls],
                            tree.leaf_value))
                    if lin is not None:
                        lin = lin._replace(const=jnp.where(
                            tree.split_feature < 0,
                            lin.const + init_scores[cls], lin.const))
            else:
                if self.iter_ == 0 and len(self.trees) < k:
                    if self.objective is not None and \
                            not cfg.boost_from_average and \
                            not self._has_init_score:
                        init_scores[cls] = self.objective.boost_from_score(cls)
                        self._add_const_score(init_scores[cls], cls)
                    tree = self._constant_tree(init_scores[cls])
            self.trees.append(tree)
            self.tree_class.append(cls)
            self.linear_models.append(lin)
        self.iter_ += 1
        if guard != "off" and not guards.all_finite(
                self.train_score,
                *[self._guarded_tree_values(t) for t in self.trees[-k:]]):
            guards.trip("split gains/scores", guard, self.iter_ - 1)
            if guard in ("skip_iteration", "rollback"):
                # discard the offending iteration by exact restoration
                for _ in range(k):
                    self.trees.pop()
                    self.tree_class.pop()
                    self.linear_models.pop()
                self.train_score = prev_scores[0]
                for i, s in enumerate(prev_scores[1]):
                    self.valid_scores[i] = s
                self.iter_ -= 1
                if guard == "skip_iteration":
                    # keep the iteration slot (constant zero trees) so
                    # tree counts stay aligned with the boosting round
                    for cls in range(k):
                        self.trees.append(self._constant_tree(0.0))
                        self.tree_class.append(cls)
                        self.linear_models.append(None)
                    self.iter_ += 1
        if _orec:
            _obs.record_train_iteration(
                self, _obs_iter, _obs_t0, time.perf_counter() - _obs_t0,
                phases=_obs.phase_deltas(_obs_ph0),
                gradients=gradients, hessians=hessians,
                tree=self.trees[-1] if self.trees else None)
        return not should_continue

    @staticmethod
    def _guarded_tree_values(tree):
        """Leaf outputs of `tree`'s *valid* nodes only: slots past
        num_nodes and internal-node slots hold uninitialised padding
        (legitimately non-finite), so the guard must not read them."""
        idx = jnp.arange(tree.leaf_value.shape[0])
        valid = (idx < tree.num_nodes) & tree.is_leaf
        return jnp.where(valid, tree.leaf_value, 0.0)

    def _guard_gradients(self, guard, gradients, hessians):
        """Pre-growth non-finite rail (guard_nonfinite policies).
        Returns usable (gradients, hessians), or (None, None) when the
        skip_iteration policy consumed the whole iteration."""
        guards.trip("gradients/hessians", guard, self.iter_)
        k = self.num_tree_per_iteration
        if guard == "rollback" and self.iter_ > self._iter_offset and \
                self.objective is not None:
            # the bad gradients were computed from the current scores:
            # drop the iteration that produced them (reference
            # Boosting::RollbackOneIter) and recompute
            self.rollback_one_iter()
            gradients, hessians = self.objective.get_gradients(
                self.train_score)
            if guards.all_finite(gradients, hessians):
                return gradients, hessians
            guards.trip("gradients/hessians after rollback", guard,
                        self.iter_)
        if guard == "skip_iteration":
            # keep the iteration slot: constant zero trees contribute
            # nothing but keep tree counts aligned with boosting rounds
            for cls in range(k):
                self.trees.append(self._constant_tree(0.0))
                self.tree_class.append(cls)
                self.linear_models.append(None)
            self.iter_ += 1
            return None, None
        return (jnp.nan_to_num(gradients, nan=0.0, posinf=0.0, neginf=0.0),
                jnp.nan_to_num(hessians, nan=0.0, posinf=0.0, neginf=0.0))

    def _feature_mask(self) -> jax.Array:
        return self._feature_mask_at(self.iter_)

    def _feature_mask_at(self, it) -> jax.Array:
        """Per-iteration feature_fraction mask; `it` may be a traced
        iteration index (the fused multi-tree scan)."""
        cfg = self.config
        f = int(self.num_bins_d.shape[0])  # original features (not Fb)
        if cfg.feature_fraction >= 1.0:
            return jnp.ones(f, jnp.float32)
        key = jax.random.fold_in(
            jax.random.PRNGKey(cfg.feature_fraction_seed), it)
        kf = max(1, int(round(f * cfg.feature_fraction)))
        perm = jax.random.permutation(key, f)
        mask = jnp.zeros(f, jnp.float32).at[perm[:kf]].set(1.0)
        return mask

    # ------------------------------------------------------------------
    # fused multi-tree training (TPU pipelining; boosting/fused.py)
    def _needs_bagging(self) -> bool:
        cfg = self.config
        return cfg.bagging_freq > 0 and (
            cfg.bagging_fraction < 1.0 or cfg.pos_bagging_fraction < 1.0
            or cfg.neg_bagging_fraction < 1.0)

    def _fused_eligible(self) -> bool:
        """Whether K iterations can run as one on-device scan with
        behavior identical to K train_one_iter calls. Round 4 widened
        the ring: bagging masks are recomputed statelessly in-scan, GOSS
        consumes pre-drawn keys, and multiclass grows one tree per class
        per step (fused.py)."""
        cfg = self.config
        # guard rails need per-iteration host checks; the fused scan has
        # no host boundary to interpose on (docs/Reliability.md)
        serial_ok = self._grower is None and self._hist_impl == "mxu"
        return (type(self) is GBDT and cfg.boosting in ("gbdt", "goss")
                and cfg.guard_nonfinite == "off"
                and (serial_ok or self._sharded_fused_ok())
                and not self._linear
                and self.objective is not None
                and not self.objective.need_renew_tree_output
                and self._cegb_cfg is None)  # feat_used carries across
        #       trees (a scan-carry the fused body doesn't thread);
        #       forced splits are per-tree static and ride along.
        #       valid_sets ride along too (round 5): the stacked trees
        #       are replayed over each valid set AFTER the dispatch
        #       (_stacked_score_traj), reproducing the per-iteration
        #       score updates exactly

    def _sharded_fused_ok(self) -> bool:
        """Whether the distributed crossbar's data-parallel row can run
        the fused multi-tree scan (distributed/fused.py): the boosting
        loop moves inside shard_map, so the pipelined executor
        double-buffers multi-device training exactly like the serial MXU
        path. Single-host, single-class, plain gbdt on the portable
        grower — GOSS (global top-k over all rows) and EFB/CEGB/rescan
        monotone (per-iteration host state) stay per-iteration."""
        cfg = self.config
        return (self._grower is not None
                and not getattr(self, "_sharded_mxu", False)
                and getattr(self, "_nproc", 1) <= 1
                and self.comm.mode == "data"
                and cfg.boosting == "gbdt"
                and self.num_tree_per_iteration == 1
                and self._efb is None
                and not self._mono_nonbasic)

    def _fused_sample_fn(self):
        """In-scan bagging/GOSS (fused.py contract): returns
        (sample_fn | None, needs_keys). Both reproduce the per-iteration
        path exactly — bagging is stateless on (seed, resample
        iteration); GOSS consumes the same _next_key draws."""
        cfg = self.config
        n = self.num_data
        if cfg.boosting == "goss":
            top_rate, other_rate = cfg.top_rate, cfg.other_rate
            top_k = max(1, int(n * top_rate))

            def goss_fn(grad, hess, it, key):
                score_abs = jnp.abs(grad) * hess
                if score_abs.ndim == 2:
                    score_abs = score_abs.sum(axis=1)
                thresh = jax.lax.top_k(score_abs, top_k)[0][-1]
                is_top = score_abs >= thresh
                u = jax.random.uniform(key, (n,))
                rest_frac = other_rate / max(1.0 - top_rate, 1e-9)
                is_other = (~is_top) & (u < rest_frac)
                amplify = (1.0 - top_rate) / other_rate
                w = jnp.where(is_top, 1.0,
                              jnp.where(is_other, amplify, 0.0)) \
                    .astype(jnp.float32)
                cnt = (is_top | is_other).astype(jnp.float32)
                if grad.ndim == 2:
                    return grad * w[:, None], hess * w[:, None], cnt
                return grad * w, hess * w, cnt

            return goss_fn, True
        if self._needs_bagging():
            use_posneg = (cfg.pos_bagging_fraction < 1.0 or
                          cfg.neg_bagging_fraction < 1.0)
            label = jnp.asarray(self.objective.label) if use_posneg \
                else None

            def bag_fn(grad, hess, it, key):
                # the mask the per-iteration path STORED at the last
                # resample boundary, recomputed statelessly
                it_rs = it - it % cfg.bagging_freq
                k2 = jax.random.fold_in(
                    jax.random.PRNGKey(cfg.bagging_seed), it_rs)
                u = jax.random.uniform(k2, (n,))
                if use_posneg:
                    frac = jnp.where(label > 0, cfg.pos_bagging_fraction,
                                     cfg.neg_bagging_fraction)
                    mask = (u < frac).astype(jnp.float32)
                else:
                    mask = (u < cfg.bagging_fraction).astype(jnp.float32)
                if grad.ndim == 2:
                    return grad * mask[:, None], hess * mask[:, None], mask
                return grad * mask, hess * mask, mask

            return bag_fn, False
        return None, False

    def _build_sharded_fused(self):
        """Fused-scan builder for the sharded data-parallel grower
        (distributed/fused.py) — the _build_fused analogue when the
        crossbar resolved a row-sharded learner."""
        from ..distributed.fused import build_sharded_fused_train
        cfg = self.config
        self._fused_needs_keys = False
        bagging = None
        if self._needs_bagging():
            bagging = dict(
                freq=cfg.bagging_freq, seed=cfg.bagging_seed,
                fraction=cfg.bagging_fraction,
                pos_fraction=cfg.pos_bagging_fraction,
                neg_fraction=cfg.neg_bagging_fraction,
                use_posneg=(cfg.pos_bagging_fraction < 1.0 or
                            cfg.neg_bagging_fraction < 1.0))
        # the exact static settings create_tree_learner bakes into the
        # per-iteration sharded grower — same partial, same compiled
        # growth body, so fused blocks match per-iteration training
        grow_kwargs = dict(
            num_leaves=cfg.num_leaves, max_depth=cfg.max_depth,
            hp=self.hp, leafwise=self._mono_nonbasic, bmax=self.bmax,
            monotone=self._monotone, monotone_method=self._mono_method,
            interaction_groups=self._interaction_groups,
            feature_fraction_bynode=cfg.feature_fraction_bynode,
            forced=self._forced)
        return build_sharded_fused_train(
            mesh=self.mesh, comm=self.comm, objective=self.objective,
            bins=self.bins, bins_ft=self._bins_ft,
            num_data=self.num_data, row_pad=self._row_pad,
            feature_mask_fn=self._feature_mask_at,
            num_bins=self.num_bins_d,
            missing_is_nan=self.missing_is_nan_d, is_cat=self.is_cat_d,
            grow_kwargs=grow_kwargs, shrinkage=self.shrinkage_rate,
            extra_seed=cfg.extra_seed, needs_rng=self._sharded_rng,
            bagging=bagging)

    def _build_fused(self, debug: bool = False):
        from .fused import build_fused_train
        cfg = self.config
        if self._grower is not None:
            return self._build_sharded_fused()
        needs_rng = (cfg.feature_fraction_bynode < 1.0 or cfg.extra_trees
                     or cfg.use_quantized_grad)
        sample_fn, needs_keys = self._fused_sample_fn()
        self._fused_needs_keys = needs_keys
        return build_fused_train(debug=debug,
            objective=self.objective, bins=self.bins,
            cnt_weight=jnp.ones(self.num_data, jnp.float32),
            feature_mask_fn=self._feature_mask_at,
            num_bins=self.num_bins_d, missing_is_nan=self.missing_is_nan_d,
            is_cat=self.is_cat_d, grower_kwargs=self._mxu_grow_kwargs(),
            shrinkage=self.shrinkage_rate, extra_seed=cfg.extra_seed,
            needs_rng=needs_rng, sample_fn=sample_fn,
            num_class=self.num_tree_per_iteration)

    def train_many(self, k: int) -> bool:
        """K boosting iterations with one device dispatch (and at most
        one amortized host sync) — behavior-identical to K
        train_one_iter calls when eligible, else a plain loop. Returns
        True when training cannot continue (lagged stall detection, as
        in train_one_iter).

        Resilience: a runtime/compile failure inside the fused dispatch
        (remoted-accelerator tunnels can drop mid-request) falls back to
        the per-iteration path for this batch instead of propagating;
        after two consecutive fused failures the fused path is disabled
        for the rest of this booster's life."""
        return self.finalize_block(self.train_many_dispatch(k))

    def finalize_block(self, handle: dict) -> bool:
        """Second half of train_many: unpack the dispatched block's
        stacked trees into per-tree views on self.trees. Pure host work
        (tree_map slicing; no device sync) whose only effect is the
        tree list — scores, RNG, iter_, valid trajectories and the
        stall poll were already advanced by train_many_dispatch, so the
        pipelined executor defers this call into the window where the
        NEXT block is running on device."""
        if handle["mode"] == "fused":
            stacked, kcls = handle["stacked"], handle["kcls"]
            for i in range(handle["k"]):
                for c in range(kcls):
                    self.trees.append(jax.tree_util.tree_map(
                        (lambda a: a[i, c]) if kcls > 1
                        else (lambda a: a[i]), stacked))
                    self.tree_class.append(c if kcls > 1 else 0)
                    self.linear_models.append(None)
        return handle["stop"]

    @staticmethod
    def _buffer_deleted(arr) -> bool:
        """True when a donated jax.Array's buffer is gone (TPU donation
        consumes the input; CPU ignores donation so this stays False)."""
        fn = getattr(arr, "is_deleted", None)
        try:
            return bool(fn()) if fn is not None else False
        except Exception:
            return False

    def train_many_dispatch(self, k: int) -> dict:
        """First half of train_many: run the k iterations (fused
        dispatch when eligible, else the per-iteration loop) and leave
        everything EXCEPT the per-tree unpacking done. Returns an
        opaque handle for finalize_block; until finalize_block runs,
        self.trees lags self.iter_ by the fused block.

        The split exists for the pipelined executor
        (pipeline/executor.py): unpacking stacked trees into Tree
        objects is host-only work with no effect on the next dispatch's
        inputs, so the executor overlaps it with the next block's
        device compute."""
        # per-iteration valid-score trajectory of this batch (engine
        # block dispatch evaluates/early-stops from it). EVERY path
        # through this method — fused, per-iteration fallback, stalled —
        # completes the full k iterations and leaves a k-point
        # trajectory, so block size and eval cadence never depend on
        # eligibility or faults.
        self._fused_valid_traj = None
        traj_pts = [[] for _ in self.valid_sets] if self.valid_sets \
            else None

        def _snap():
            if traj_pts is not None:
                for i in range(len(traj_pts)):
                    traj_pts[i].append(self.valid_scores[i])

        def _seal():
            if traj_pts is not None and traj_pts[0]:
                self._fused_valid_traj = [jnp.stack(p) for p in traj_pts]

        stop = False
        if self.iter_ == 0 and k > 0:
            # the first iteration owns boost_from_average / init-score
            # plumbing (host-side floats); run it on the normal path
            stop = self.train_one_iter()
            k -= 1
            _snap()
            if stop:
                # stalled at iteration 0: still complete the batch
                # (constant trees), like every other path here
                for _ in range(k):
                    self.train_one_iter()
                    _snap()
                _seal()
                return {"mode": "done", "stop": True}
        if k <= 0:
            _seal()
            return {"mode": "done", "stop": stop}
        if not self._fused_eligible() or getattr(
                self, "_fused_disabled", False):
            for _ in range(k):
                stop = self.train_one_iter() or stop
                _snap()
            _seal()
            return {"mode": "done", "stop": stop}
        saved_rng = self._rng_key
        cfg = self.config

        def _attempt():
            # every attempt rewinds the RNG stream first: whether the
            # dispatch succeeds on attempt 1 or 3, it must consume the
            # IDENTICAL key sequence — a transient fault must not
            # change the trained model
            self._rng_key = saved_rng
            if self._buffer_deleted(self.train_score):
                # a previous attempt donated the score buffer to a
                # dispatch that failed after consuming it; retrying
                # would feed XLA a dead buffer — fail with a clear
                # diagnosis instead
                raise LightGBMError(
                    "train-score buffer was donated to a failed fused "
                    "dispatch and deleted by the runtime; cannot retry")
            try:
                _maybe_inject_fused_fault()
                if getattr(self, "_fused_run", None) is None:
                    self._fused_run = self._build_fused()
                keys = None
                if getattr(self, "_fused_needs_keys", False):
                    # the same _next_key sequence the per-iteration GOSS
                    # path would draw, pre-drawn as scan inputs
                    keys = jnp.stack([self._next_key() for _ in range(k)])
                with global_timer.timeit("tree_train"):
                    return self._fused_run(
                        self.train_score,
                        jnp.asarray(self.iter_, jnp.int32),
                        k=k, sample_keys=keys)
            except Exception:
                self._fused_run = None  # closure may hold dead executables
                raise

        _orec = _obs.enabled
        if _orec:
            _obs_iter0 = self.iter_
            _obs_was_built = getattr(self, "_fused_run", None) is None
            _obs_t0 = time.perf_counter()
        try:
            # capped-exponential-backoff retries before degrading: a
            # transient launch failure should not cost the fused path
            score, stacked = retry_call(
                _attempt, attempts=cfg.retry_max_attempts,
                backoff_ms=cfg.retry_backoff_ms,
                backoff_max_ms=cfg.retry_backoff_max_ms,
                site="fused_dispatch")
        except Exception as exc:  # device/compile faults must not kill
            # rewind the RNG stream so the per-iteration fallback draws
            # the IDENTICAL key sequence the fused dispatch consumed —
            # a transient fault must not change the trained model
            self._rng_key = saved_rng
            if self._buffer_deleted(self.train_score):
                # donation consumed the score carry before the fault
                # landed: the per-iteration fallback would read a dead
                # buffer, so surface the truth instead of degrading
                raise LightGBMError(
                    "fused dispatch failed after its donated train-score "
                    "buffer was consumed; per-iteration fallback is "
                    "impossible — restart from the last checkpoint"
                ) from exc
            self._fused_failures = getattr(self, "_fused_failures", 0) + 1
            self._fused_run = None  # closure may hold dead executables
            counters.inc("fallbacks")
            if self._fused_failures >= 2:
                self._fused_disabled = True
            Log.warning(
                "fused multi-tree dispatch failed (%s: %s); falling back "
                "to per-iteration training for this batch%s"
                % (type(exc).__name__, exc,
                   " and disabling the fused path" if
                   getattr(self, "_fused_disabled", False) else ""))
            for _ in range(k):
                stop = self.train_one_iter() or stop
                _snap()
            _seal()
            return {"mode": "done", "stop": stop}
        self._fused_failures = 0
        if _orec:
            # the fused scan is lazy: force completion so the recorded
            # wall covers device work, then record the whole block as
            # one telemetry record (no host boundary inside it)
            jax.block_until_ready(score)
            _obs.record_fused_block(
                self, _obs_iter0, k, _obs_t0,
                time.perf_counter() - _obs_t0, _obs_was_built)
        self.train_score = score
        kcls = self.num_tree_per_iteration
        if self.valid_sets:
            # replay the stacked block over each valid set — one scanned
            # dispatch per set yields the exact per-iteration valid-score
            # trajectory (the engine's block path evaluates metrics /
            # early stopping at every inner iteration from it); any
            # normal-path points already snapped (iteration 0) lead it
            from .fused import stacked_score_traj
            trajs = []
            for i in range(len(self.valid_sets)):
                # any snapped lead points alias the very buffer donated
                # below as score0 — stack them into a fresh array FIRST
                # (on TPU the dispatch deletes the donated input)
                lead = jnp.stack(traj_pts[i]) \
                    if traj_pts is not None and traj_pts[i] else None
                fin, traj = stacked_score_traj(
                    stacked, self.valid_scores[i], self.valid_bins[i],
                    self.num_bins_d, self.missing_is_nan_d,
                    num_class=kcls)
                if lead is not None:
                    traj = jnp.concatenate([lead, traj])
                self.valid_scores[i] = fin
                trajs.append(traj)
            self._fused_valid_traj = trajs
        self.iter_ += k
        # lagged stall poll (see train_one_iter): a stalled model keeps
        # producing all-zero trees, so checking the batch's last tree
        # roughly every _stop_poll_every ITERATIONS is enough — poll
        # when this batch crossed a poll boundary, whatever its size
        prev = self._pending_nleaves
        crossed = (self.iter_ // self._stop_poll_every !=
                   (self.iter_ - k) // self._stop_poll_every)
        stop_hint = (prev is not None and not self._exact_stop_poll and
                     crossed and int(prev) <= 1)
        pending = stacked.num_leaves[k - 1]
        if kcls > 1:
            pending = jnp.max(pending)  # stalled only if EVERY class is
        try:
            pending.copy_to_host_async()
        except Exception:
            pass
        self._pending_nleaves = pending
        return {"mode": "fused", "stacked": stacked, "k": k,
                "kcls": kcls, "stop": stop_hint}

    def _constant_tree(self, value: float) -> TreeArrays:
        m1 = 2 * self.config.num_leaves - 1 + 1
        zf = jnp.zeros(m1, jnp.float32)
        zi = jnp.zeros(m1, jnp.int32)
        zb = jnp.zeros(m1, bool)
        return TreeArrays(
            split_feature=jnp.full(m1, -1, jnp.int32), threshold_bin=zi,
            default_left=zb, is_cat=zb,
            cat_bitset=jnp.zeros((m1, (self.bmax + 31) // 32), jnp.uint32),
            left=jnp.full(m1, -1, jnp.int32),
            right=jnp.full(m1, -1, jnp.int32),
            parent=jnp.full(m1, -1, jnp.int32),
            leaf_value=zf.at[0].set(value), sum_grad=zf, sum_hess=zf,
            count=zf, gain=zf, depth=zi, is_leaf=zb.at[0].set(True),
            num_nodes=jnp.asarray(1, jnp.int32),
            num_leaves=jnp.asarray(1, jnp.int32))

    def _boost_from_average(self, cls: int) -> float:
        cfg = self.config
        if (self.trees or self._boosted_from_average[cls] or
                self._has_init_score or self.objective is None or
                not cfg.boost_from_average):
            return 0.0
        init = self.objective.boost_from_score(cls)
        if getattr(self, "_nproc", 1) > 1:
            # reference gbdt.cpp:335-344: init scores are averaged across
            # machines (GlobalSyncUpByMean), each rank having computed
            # from its local partition
            from ..parallel.comm import guarded_allgather
            init = float(np.mean(guarded_allgather(
                np.float32(init), label="boost_from_average")))
        if abs(init) > 1e-35:
            self._add_const_score(init, cls)
            Log.info("Start training from score %f", init)
            self._boosted_from_average[cls] = True
            return init
        return 0.0

    def _add_const_score(self, value: float, cls: int) -> None:
        k = self.num_tree_per_iteration
        if k == 1:
            self.train_score = self.train_score + value
            for i in range(len(self.valid_sets)):
                self.valid_scores[i] = self.valid_scores[i] + value
        else:
            self.train_score = self.train_score.at[:, cls].add(value)
            for i in range(len(self.valid_sets)):
                self.valid_scores[i] = \
                    self.valid_scores[i].at[:, cls].add(value)

    def _lin(self, idx: int):
        """Linear leaf model of tree idx (None for constant leaves)."""
        return self.linear_models[idx] \
            if idx < len(self.linear_models) else None

    def _tree_values(self, tree: TreeArrays, lin, bins: jax.Array,
                     raw, efb=None) -> jax.Array:
        """Per-row outputs of one tree on a binned matrix (linear-aware).
        `efb` must be passed iff `bins` is the bundled training matrix
        (validation matrices stay unbundled)."""
        if lin is None:
            return predict_binned_tree(tree, bins, self.num_bins_d,
                                       self.missing_is_nan_d, efb)
        from ..learner.linear import linear_leaf_values
        from ..learner.predict import leaf_node_tree
        leaf = leaf_node_tree(tree, bins, self.num_bins_d,
                              self.missing_is_nan_d, efb)
        return linear_leaf_values(tree, lin, leaf, raw)

    def _update_score(self, tree: TreeArrays, row_node: jax.Array,
                      cls: int, lin=None) -> None:
        """Learner-side score update: leaf value via row->node gather
        (score_updater.hpp:21-110 AddScore(tree_learner) equivalent)."""
        if lin is None:
            if self._hist_impl == "mxu":
                # per-row gathers are ~10M rows/s on remoted TPUs; the
                # one-hot matmul lookup kernel is ~50x faster
                from ..learner.histogram_mxu import node_values_mxu
                vals = node_values_mxu(
                    row_node, tree.leaf_value,
                    interpret=getattr(self, "_mxu_interpret", False))
            else:
                vals = tree.leaf_value[row_node]
        else:
            from ..learner.linear import linear_leaf_values
            vals = linear_leaf_values(tree, lin, row_node, self.raw)
        k = self.num_tree_per_iteration
        if k == 1:
            self.train_score = self.train_score + vals
        else:
            self.train_score = self.train_score.at[:, cls].add(vals)
        for i in range(len(self.valid_sets)):
            vvals = self._tree_values(tree, lin, self.valid_bins[i],
                                      self.valid_raws[i]
                                      if self.valid_raws else None)
            if k == 1:
                self.valid_scores[i] = self.valid_scores[i] + vvals
            else:
                self.valid_scores[i] = \
                    self.valid_scores[i].at[:, cls].add(vvals)

    # ------------------------------------------------------------------
    def rollback_one_iter(self) -> None:
        """Drop the last iteration (gbdt.cpp:451-467)."""
        if self.iter_ <= self._iter_offset:
            # nothing of this instance's own to roll back (checkpointed
            # base iterations are immutable)
            return
        k = self.num_tree_per_iteration
        for cls in range(k):
            tree = self.trees.pop()
            cls_id = self.tree_class.pop()
            lin = self.linear_models.pop() if self.linear_models else None
            if lin is None:
                vals = self._predict_train_rows(tree)
            else:
                vals = self._tree_values(tree, lin,
                                         self._train_bins_unpacked(),
                                         self.raw, self._efb)[:self.num_data]
            if k == 1:
                self.train_score = self.train_score - vals
            else:
                self.train_score = self.train_score.at[:, cls_id].add(-vals)
            for i in range(len(self.valid_sets)):
                vv = self._tree_values(tree, lin, self.valid_bins[i],
                                       self.valid_raws[i])
                if k == 1:
                    self.valid_scores[i] = self.valid_scores[i] - vv
                else:
                    self.valid_scores[i] = \
                        self.valid_scores[i].at[:, cls_id].add(-vv)
        self.iter_ -= 1

    # ------------------------------------------------------------------
    def eval_train(self) -> Dict[str, float]:
        return self._eval(self.train_score, self.train_metrics,
                          self.train_set)

    def eval_valid(self, i: int) -> Dict[str, float]:
        return self._eval(self.valid_scores[i], self.valid_metrics[i],
                          self.valid_sets[i])

    def _eval(self, score: jax.Array, metrics: List[Metric],
              ds: BinnedDataset) -> Dict[str, float]:
        out: Dict[str, float] = {}
        if not metrics:
            return out
        score_np = np.asarray(score)
        convert = (lambda s: np.asarray(
            self.objective.convert_output(jnp.asarray(s)))) \
            if self.objective is not None else None
        for m in metrics:
            if hasattr(m, "evaluate_multi"):
                out.update(m.evaluate_multi(score_np))
            else:
                out[m.name] = m.evaluate(score_np, convert)
        return out

    # ------------------------------------------------------------------
    @property
    def num_iterations_trained(self) -> int:
        return self.iter_

    def current_iteration(self) -> int:
        return self.iter_ - self._iter_offset

    # ------------------------------------------------------------------
    # checkpoint/resume (reliability/checkpoint.py bundles)
    def training_state(self):
        """(json-state, arrays) beyond what the model text carries:
        exact f32 scores, RNG stream position, mid-period bagging mask,
        boost-from-average flags and the lagged stop-poll hint. With
        these restored, replaying iterations k..N reproduces an
        uninterrupted run bit-for-bit (fold-in RNG draws key off the
        absolute iter_, which resume preserves)."""
        state = {
            "boosting": self.config.boosting,
            "num_class": self.num_class,
            "shrinkage_rate": float(self.shrinkage_rate),
            "boosted_from_average": [bool(b) for b in
                                     self._boosted_from_average],
            "has_init_score": bool(self._has_init_score),
            "num_valid": len(getattr(self, "valid_scores", []) or []),
        }
        if self._pending_nleaves is not None:
            # host sync is fine here — checkpointing is already IO-bound
            state["pending_nleaves"] = int(self._pending_nleaves)
        arrays = {
            "train_score": np.asarray(self.train_score),
            "rng_key": np.asarray(self._rng_key),
            "bag_mask": np.asarray(self._bag_mask),
        }
        for i, s in enumerate(getattr(self, "valid_scores", []) or []):
            arrays[f"valid_score_{i}"] = np.asarray(s)
        return state, arrays

    def restore_training_state(self, iteration: int, state: Dict,
                               arrays: Dict) -> None:
        """Continue a checkpointed run: `iteration` boosting rounds live
        in the attached base model; this instance trains the rest from
        the exact device state the killed run held."""
        cfg = self.config
        if int(state.get("num_class", self.num_class)) != self.num_class:
            raise LightGBMError(
                "checkpoint num_class=%s does not match num_class=%d" %
                (state.get("num_class"), self.num_class))
        if state.get("boosting", cfg.boosting) != cfg.boosting:
            raise LightGBMError(
                "checkpoint boosting=%r does not match boosting=%r" %
                (state.get("boosting"), cfg.boosting))
        if cfg.boosting not in ("gbdt", "goss"):
            Log.warning(
                "resume is exact for gbdt/goss boosting; %r resumes "
                "best-effort (sampling state beyond the RNG key is "
                "rebuilt)" % cfg.boosting)
        if getattr(self, "_cegb_cfg", None) is not None:
            Log.warning(
                "cegb feature-used state is not checkpointed; resumed "
                "CEGB penalties restart from a clean slate")
        if state.get("reshard_total_rows") is not None:
            arrays = self._reshard_restore_arrays(
                int(state["reshard_total_rows"]), arrays)
        score = jnp.asarray(arrays["train_score"])
        if score.shape != self.train_score.shape:
            raise LightGBMError(
                "checkpoint train_score shape %s does not match the "
                "training set (%s) — resume needs the same dataset" %
                (score.shape, self.train_score.shape))
        self.iter_ = int(iteration)
        self._iter_offset = int(iteration)
        self.train_score = score
        self._rng_key = jnp.asarray(arrays["rng_key"])
        if "bag_mask" in arrays:
            self._bag_mask = jnp.asarray(arrays["bag_mask"])
        self.shrinkage_rate = float(
            state.get("shrinkage_rate", self.shrinkage_rate))
        bfa = state.get("boosted_from_average")
        if bfa is not None:
            self._boosted_from_average = [bool(b) for b in bfa]
        if state.get("pending_nleaves") is not None:
            self._pending_nleaves = jnp.asarray(
                int(state["pending_nleaves"]), jnp.int32)
        for i in range(len(getattr(self, "valid_scores", []) or [])):
            key = f"valid_score_{i}"
            if key in arrays:
                self.valid_scores[i] = jnp.asarray(arrays[key])

    def _reshard_restore_arrays(self, total_rows: int,
                                arrays: Dict) -> Dict:
        """Elastic resume (distributed/elastic.py): the resharded
        loader handed every rank the GLOBAL row-order arrays of a
        bundle written by a DIFFERENT world size; slice this rank's
        contiguous row block so the shape check below sees the same
        local arrays an uninterrupted run at this world would hold.
        Valid sets are row-partitioned too but on their own totals, so
        each gets its own offset exchange."""
        from ..distributed.elastic import reshard_offsets, reshard_slice
        local = int(self.num_data)
        offset, tot = reshard_offsets(local, label="elastic_reshard")
        if tot != int(total_rows):
            raise LightGBMError(
                "elastic reshard: checkpoint holds %d global training "
                "rows but the new world's partitions sum to %d — the "
                "reincarnated run loaded a different dataset" %
                (int(total_rows), tot))
        valid = {k: v for k, v in arrays.items()
                 if k.startswith("valid_score_")}
        out = reshard_slice(
            {k: v for k, v in arrays.items() if k not in valid},
            offset, local, tot)
        for i, s in enumerate(getattr(self, "valid_scores", []) or []):
            key = f"valid_score_{i}"
            if key not in valid:
                continue
            varr = np.asarray(valid[key])
            vlocal = int(np.asarray(s).shape[0])
            voff, vtot = reshard_offsets(
                vlocal, label="elastic_reshard_valid")
            if varr.ndim and varr.shape[0] == vtot:
                varr = varr[voff:voff + vlocal]
            out[key] = varr
        return out


def create_boosting(config: Config, train_set, objective, metrics):
    """Factory (reference Boosting::CreateBoosting, boosting.cpp:38-58)."""
    from .dart import DART
    from .rf import RF
    if config.boosting in ("gbdt", "goss"):
        return GBDT(config, train_set, objective, metrics)
    if config.boosting == "dart":
        return DART(config, train_set, objective, metrics)
    if config.boosting == "rf":
        return RF(config, train_set, objective, metrics)
    Log.fatal("Unknown boosting type %s", config.boosting)
