"""Random-forest mode: bagging-only, no shrinkage, averaged trees.

Reference: src/boosting/rf.hpp:25-217 — gradients always computed at the
initial score, each tree's output averaged (1/num_iterations at predict is
emulated by scaling scores incrementally), bagging required.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..utils.log import Log
from .gbdt import GBDT

__all__ = ["RF"]


class RF(GBDT):
    def __init__(self, config, train_set, objective, metrics):
        if config.bagging_freq <= 0 or config.bagging_fraction >= 1.0:
            Log.fatal("Random forest needs bagging_freq > 0 and "
                      "bagging_fraction < 1.0")
        super().__init__(config, train_set, objective, metrics)
        self.shrinkage_rate = 1.0
        self._init_scores = [0.0] * self.num_tree_per_iteration
        # RF averages scores over iteration count, so late-appended
        # zero trees would bias every prediction — poll exactly
        self._exact_stop_poll = True

    def _boost_from_average(self, cls: int) -> float:
        # RF boosts from the average ONCE and keeps gradients at that point
        # (rf.hpp:49-70); returns 0 so no bias is folded into trees.
        if not self._boosted_from_average[cls] and self.config.boost_from_average:
            init = self.objective.boost_from_score(cls)
            self._init_scores[cls] = init
            self._boosted_from_average[cls] = True
        return 0.0

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        # gradients at the CONSTANT init score (rf.hpp:89-108)
        if gradients is None or hessians is None:
            for cls in range(self.num_tree_per_iteration):
                self._boost_from_average(cls)
            const = jnp.broadcast_to(
                jnp.asarray(self._init_scores, jnp.float32),
                self.train_score.shape[-1:]) if self.train_score.ndim == 2 \
                else jnp.full_like(self.train_score, self._init_scores[0])
            base = jnp.broadcast_to(const, self.train_score.shape) \
                .astype(jnp.float32)
            gradients, hessians = self.objective.get_gradients(base)
        # average: scale scores so train_score = mean of trees + init
        prev_iter = self.iter_
        stop = super().train_one_iter(gradients, hessians)
        del prev_iter
        return stop

    def _update_score(self, tree, row_node, cls, lin=None):
        # RF averages trees: score = init + sum(tree)/iter. We keep raw sum
        # during training and divide at evaluation time.
        super()._update_score(tree, row_node, cls, lin)

    def _eval(self, score, metrics, ds):
        # average the accumulated sum over trees and add init score
        it = max(self.iter_, 1)
        k = self.num_tree_per_iteration
        init = jnp.asarray(self._init_scores, jnp.float32)
        if k == 1:
            avg = score / it + float(self._init_scores[0])
        else:
            avg = score / it + init[None, :]
        return super()._eval(avg, metrics, ds)
