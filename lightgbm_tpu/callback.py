"""Training callbacks (reference python-package/lightgbm/callback.py:73-356).

Same protocol as the reference: callables taking a CallbackEnv namedtuple,
with `before_iteration` attribute controlling ordering, and
EarlyStopException for control flow.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List

from .utils.log import Log

__all__ = ["EarlyStopException", "CallbackEnv", "print_evaluation",
           "log_evaluation", "record_evaluation", "reset_parameter",
           "early_stopping", "checkpoint"]


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and \
                (env.iteration + 1) % period == 0:
            result = "\t".join(
                _format_eval_result(x, show_stdv)
                for x in env.evaluation_result_list)
            Log.info("[%d]\t%s", env.iteration + 1, result)
    _callback.order = 10
    # reads only evaluation results; safe under engine block dispatch
    _callback.block_safe = True
    return _callback


print_evaluation = log_evaluation  # deprecated alias (reference keeps both)


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]
                      ) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")

    def _init(env: CallbackEnv) -> None:
        eval_result.clear()
        for item in env.evaluation_result_list:
            data_name, eval_name = item[0], item[1]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for item in env.evaluation_result_list:
            data_name, eval_name, result = item[0], item[1], item[2]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(result)
    _callback.order = 20
    _callback.block_safe = True
    return _callback


def reset_parameter(**kwargs: Any) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal to "
                        "'num_boost_round'")
                new_param = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_param = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values are "
                                 "supported as a mapping from boosting round "
                                 "index to new parameter value")
            if new_param != env.params.get(key, None):
                new_parameters[key] = new_param
        if new_parameters:
            if "learning_rate" in new_parameters:
                env.model.reset_parameter(
                    {"learning_rate": new_parameters["learning_rate"]})
            else:
                env.model.reset_parameter(new_parameters)
            env.params.update(new_parameters)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def checkpoint(period: int, directory: str, keep_last: int = 3) -> Callable:
    """Save an atomic training-state bundle every `period` iterations
    (docs/Reliability.md). A bundle written at iteration k lets
    ``train(..., resume_from=directory)`` continue a killed run to a
    model byte-identical to an uninterrupted one.

    A failed save (full disk, injected ``checkpoint_io`` fault) is a
    warning, not a training failure: the run continues and the next
    period retries — losing a snapshot is strictly better than losing
    the run.

    Not ``block_safe``: under engine block dispatch the booster already
    holds the whole block's trees at inner iterations, so a mid-block
    snapshot would capture future state; enabling checkpointing keeps
    the per-iteration training cadence."""
    if period <= 0:
        raise ValueError("checkpoint period must be > 0")
    if not directory:
        raise ValueError("checkpoint directory must be non-empty")
    # eval history accumulated across iterations (and, on resume, seeded
    # from the bundle) so every snapshot carries the full run's curves
    history: Dict[str, Dict[str, List[float]]] = {}

    def _callback(env: CallbackEnv) -> None:
        for item in env.evaluation_result_list or []:
            data_name, eval_name, result = item[0], item[1], item[2]
            history.setdefault(data_name, collections.OrderedDict())
            history[data_name].setdefault(eval_name, [])
            history[data_name][eval_name].append(result)
        done = env.iteration + 1
        if done % period != 0 and done != env.end_iteration:
            return
        from .reliability.checkpoint import save_checkpoint
        from .reliability.counters import counters
        booster = env.model
        try:
            state, arrays = booster._training_state()
            state["eval_history"] = history
            # multihost runs checkpoint through the coordinated commit
            # protocol: every rank reaches this callback at the same
            # iteration (same data cadence), agrees on it, and writes
            # its own shard — rank 0 cuts the COMMIT marker last
            coord = None
            gb = getattr(booster, "gbdt", None)
            cfg = getattr(gb, "config", None)
            if gb is not None and getattr(gb, "_nproc", 1) > 1 and \
                    getattr(cfg, "checkpoint_coordinated", True):
                from .parallel.comm import checkpoint_coordinator
                coord = checkpoint_coordinator()
            save_checkpoint(directory, done, booster.model_to_string(),
                            state, arrays, keep_last=keep_last,
                            coordinator=coord)
        except Exception as exc:
            counters.inc("checkpoint_failures")
            Log.warning(
                "checkpoint save failed at iteration %d (%s: %s); "
                "training continues", done, type(exc).__name__, exc)

    def _seed_history(h) -> None:
        history.clear()
        for data_name, metrics in (h or {}).items():
            history[data_name] = collections.OrderedDict(
                (k, list(v)) for k, v in metrics.items())

    _callback.order = 40          # after eval/early-stop bookkeeping
    _callback.is_checkpoint = True
    _callback._seed_history = _seed_history
    return _callback


class _BestTracker:
    """Best-so-far state for one (dataset, metric) pair.

    ``update`` applies the min_delta-thresholded improvement rule for the
    metric's direction and snapshots the full evaluation list at the best
    iteration (what EarlyStopException carries, per the reference
    callback protocol)."""

    __slots__ = ("sign", "delta", "best", "iteration", "snapshot")

    def __init__(self, higher_better: bool, delta: float):
        # compare in "higher is better" space: flip sign for loss metrics
        self.sign = 1.0 if higher_better else -1.0
        self.delta = float(delta)
        self.best = float("-inf")
        self.iteration = 0
        self.snapshot: Any = None

    def update(self, score: float, iteration: int, eval_list) -> None:
        oriented = self.sign * score
        if self.snapshot is None or oriented > self.best + self.delta:
            self.best = oriented
            self.iteration = iteration
            self.snapshot = eval_list


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True, min_delta: float = 0.0) -> Callable:
    """Stop training when no tracked validation metric improved for
    ``stopping_rounds`` consecutive iterations (reference
    callback.py _EarlyStoppingCallback protocol: raises
    EarlyStopException carrying the best iteration + its eval list)."""
    state: Dict[str, Any] = {"trackers": None, "enabled": True,
                             "first_name": None}

    def _start(env: CallbackEnv) -> None:
        if any(env.params.get(k, "") == "dart"
               for k in ("boosting", "boosting_type", "boost")):
            state["enabled"] = False
            Log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        if verbose:
            Log.info("Training until validation scores don't improve for "
                     "%d rounds", stopping_rounds)
        n = len(env.evaluation_result_list)
        deltas = list(min_delta) if isinstance(min_delta, list) \
            else [min_delta] * n
        state["trackers"] = [
            _BestTracker(higher_better=entry[3], delta=d)
            for entry, d in zip(env.evaluation_result_list, deltas)]
        # "first metric" = the metric name of the first eval entry
        state["first_name"] = env.evaluation_result_list[0][1]

    def _stop(trk: _BestTracker, reason: str, metric_name: str) -> None:
        if verbose:
            summary = "\t".join(_format_eval_result(x)
                                for x in trk.snapshot)
            Log.info("%s Best iteration is:\n[%d]\t%s",
                     reason, trk.iteration + 1, summary)
            if first_metric_only:
                Log.info("Evaluated only: %s", metric_name.split(" ")[-1])
        raise EarlyStopException(trk.iteration, trk.snapshot)

    def _callback(env: CallbackEnv) -> None:
        if env.iteration == env.begin_iteration:
            _start(env)
        if not state["enabled"]:
            return
        last_round = env.iteration == env.end_iteration - 1
        for trk, entry in zip(state["trackers"],
                              env.evaluation_result_list):
            data_name, metric_name, score = entry[0], entry[1], entry[2]
            trk.update(score, env.iteration, env.evaluation_result_list)
            if first_metric_only and metric_name != state["first_name"]:
                continue
            # training-set and cv-aggregate scores never trigger a stop
            # mid-run; they only terminate cleanly at the last round
            counts = data_name not in ("cv_agg", "training")
            if counts and env.iteration - trk.iteration >= stopping_rounds:
                _stop(trk, "Early stopping.", metric_name)
            if last_round:
                _stop(trk, "Did not meet early stopping.", metric_name)
    _callback.order = 30
    _callback.block_safe = True
    return _callback
