"""ctypes bridge to the native host runtime (lightgbm_tpu/cext/binning.cpp).

Reference analog: the C++ data layer (DatasetLoader/Parser/BinMapper hot
paths). The library builds lazily on first import with the system compiler
(g++ -O3 -shared); everything degrades gracefully to the NumPy
implementations when a compiler is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "binning.cpp")
_LIB_PATH = os.path.join(_DIR, "libbinning.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
             "-o", _LIB_PATH],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) or \
            os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    c_dp = ctypes.POINTER(ctypes.c_double)
    c_ip = ctypes.POINTER(ctypes.c_int)
    lib.lgbt_greedy_find_bin.restype = ctypes.c_int
    lib.lgbt_greedy_find_bin.argtypes = [
        c_dp, c_ip, ctypes.c_int, ctypes.c_int, ctypes.c_long,
        ctypes.c_int, c_dp]
    lib.lgbt_distinct.restype = ctypes.c_int
    lib.lgbt_distinct.argtypes = [c_dp, ctypes.c_int, c_dp, c_ip]
    lib.lgbt_parse_delimited.restype = ctypes.c_long
    lib.lgbt_parse_delimited.argtypes = [
        ctypes.c_char_p, ctypes.c_char, ctypes.c_int, c_dp, ctypes.c_long,
        ctypes.c_int, c_ip]
    lib.lgbt_count_rows.restype = ctypes.c_long
    lib.lgbt_count_rows.argtypes = [ctypes.c_char_p, ctypes.c_char, c_ip]
    lib.lgbt_values_to_bins.restype = None
    lib.lgbt_values_to_bins.argtypes = [
        c_dp, ctypes.c_long, c_dp, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8)]
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def greedy_find_bin(distinct: np.ndarray, counts: np.ndarray, max_bin: int,
                    total_cnt: int, min_data_in_bin: int) -> np.ndarray:
    """Native GreedyFindBin; returns bin upper bounds (last = +inf)."""
    lib = get_lib()
    assert lib is not None
    distinct = np.ascontiguousarray(distinct, np.float64)
    counts = np.ascontiguousarray(counts, np.int32)
    out = np.empty(max_bin + 2, np.float64)
    n = lib.lgbt_greedy_find_bin(
        distinct.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        len(distinct), max_bin, total_cnt, min_data_in_bin,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out[:n]


def distinct_values(sorted_values: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray]:
    lib = get_lib()
    assert lib is not None
    sorted_values = np.ascontiguousarray(sorted_values, np.float64)
    vals = np.empty(len(sorted_values), np.float64)
    cnts = np.empty(len(sorted_values), np.int32)
    k = lib.lgbt_distinct(
        sorted_values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(sorted_values),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        cnts.ctypes.data_as(ctypes.POINTER(ctypes.c_int)))
    return vals[:k], cnts[:k].astype(np.int64)


def parse_delimited(path: str, delim: str = ",",
                    skip_rows: int = 0) -> Optional[np.ndarray]:
    """Native text parse to a dense [rows, cols] float64 matrix."""
    lib = get_lib()
    if lib is None:
        return None
    cols = ctypes.c_int(0)
    rows = lib.lgbt_count_rows(path.encode(), delim.encode(),
                               ctypes.byref(cols))
    if rows <= 0 or cols.value <= 0:
        return None
    rows -= skip_rows
    out = np.zeros((rows, cols.value), np.float64)
    got_cols = ctypes.c_int(0)
    got = lib.lgbt_parse_delimited(
        path.encode(), delim.encode(), skip_rows,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        rows, cols.value, ctypes.byref(got_cols))
    if got < 0:
        return None
    return out[:got, :got_cols.value]


def values_to_bins_u8(values: np.ndarray, bounds: np.ndarray,
                      num_search: int, nan_bin: int) -> np.ndarray:
    lib = get_lib()
    assert lib is not None
    values = np.ascontiguousarray(values, np.float64)
    bounds = np.ascontiguousarray(bounds, np.float64)
    out = np.empty(len(values), np.uint8)
    lib.lgbt_values_to_bins(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(values),
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        num_search, nan_bin,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out
