"""ctypes bridge to the native host runtime (lightgbm_tpu/cext/binning.cpp).

Reference analog: the C++ data layer (DatasetLoader/Parser/BinMapper hot
paths). The library builds lazily on first import with the system compiler
(g++ -O3 -shared); everything degrades gracefully to the NumPy
implementations when a compiler is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "binning.cpp")
_LIB_PATH = os.path.join(_DIR, "libbinning.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load_or_build(src: str, lib_path: str,
                   flag_sets=((),)) -> Optional[ctypes.CDLL]:
    """Load lib_path, rebuilding from src when stale; None on failure.

    Degrades gracefully: a missing source next to a prebuilt .so loads
    the .so; no compiler at all returns None (NumPy fallbacks take over).
    """
    have_src = os.path.exists(src)
    stale = have_src and (
        not os.path.exists(lib_path) or
        os.path.getmtime(lib_path) < os.path.getmtime(src))
    if stale:
        built = False
        for flags in flag_sets:
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
                    + list(flags) + [src, "-o", lib_path],
                    check=True, capture_output=True, timeout=120)
                built = True
                break
            except Exception:
                continue
        if not built:
            return None
    if not os.path.exists(lib_path):
        return None
    try:
        return ctypes.CDLL(lib_path)
    except OSError:
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    lib = _load_or_build(_SRC, _LIB_PATH, flag_sets=(("-fopenmp",), ()))
    if lib is None:
        return None
    c_dp = ctypes.POINTER(ctypes.c_double)
    c_ip = ctypes.POINTER(ctypes.c_int)
    lib.lgbt_greedy_find_bin.restype = ctypes.c_int
    lib.lgbt_greedy_find_bin.argtypes = [
        c_dp, c_ip, ctypes.c_int, ctypes.c_int, ctypes.c_long,
        ctypes.c_int, c_dp]
    lib.lgbt_distinct.restype = ctypes.c_int
    lib.lgbt_distinct.argtypes = [c_dp, ctypes.c_int, c_dp, c_ip]
    lib.lgbt_parse_delimited.restype = ctypes.c_long
    lib.lgbt_parse_delimited.argtypes = [
        ctypes.c_char_p, ctypes.c_char, ctypes.c_int, c_dp, ctypes.c_long,
        ctypes.c_int, c_ip]
    lib.lgbt_count_rows.restype = ctypes.c_long
    lib.lgbt_count_rows.argtypes = [ctypes.c_char_p, ctypes.c_char, c_ip]
    lib.lgbt_values_to_bins.restype = None
    lib.lgbt_values_to_bins.argtypes = [
        c_dp, ctypes.c_long, c_dp, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8)]
    lib.lgbt_bin_matrix.restype = None
    lib.lgbt_bin_matrix.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_long, ctypes.c_int,
        c_ip, ctypes.c_int,
        c_dp, ctypes.POINTER(ctypes.c_long), c_ip, c_ip,
        ctypes.c_int, ctypes.c_void_p]
    lib.lgbt_sample_transpose.restype = None
    lib.lgbt_sample_transpose.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_long), ctypes.c_long, c_dp]
    lib.lgbt_find_numeric_bounds.restype = ctypes.c_int
    lib.lgbt_find_numeric_bounds.argtypes = [
        c_dp, ctypes.c_int, ctypes.c_long, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
        c_dp, c_ip, c_ip, c_dp, ctypes.POINTER(ctypes.c_long)]
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def greedy_find_bin(distinct: np.ndarray, counts: np.ndarray, max_bin: int,
                    total_cnt: int, min_data_in_bin: int) -> np.ndarray:
    """Native GreedyFindBin; returns bin upper bounds (last = +inf)."""
    lib = get_lib()
    assert lib is not None
    distinct = np.ascontiguousarray(distinct, np.float64)
    counts = np.ascontiguousarray(counts, np.int32)
    out = np.empty(max_bin + 2, np.float64)
    n = lib.lgbt_greedy_find_bin(
        distinct.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        len(distinct), max_bin, total_cnt, min_data_in_bin,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out[:n]


def distinct_values(sorted_values: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray]:
    lib = get_lib()
    assert lib is not None
    sorted_values = np.ascontiguousarray(sorted_values, np.float64)
    vals = np.empty(len(sorted_values), np.float64)
    cnts = np.empty(len(sorted_values), np.int32)
    k = lib.lgbt_distinct(
        sorted_values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(sorted_values),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        cnts.ctypes.data_as(ctypes.POINTER(ctypes.c_int)))
    return vals[:k], cnts[:k].astype(np.int64)


def parse_delimited(path: str, delim: str = ",",
                    skip_rows: int = 0) -> Optional[np.ndarray]:
    """Native text parse to a dense [rows, cols] float64 matrix."""
    lib = get_lib()
    if lib is None:
        return None
    cols = ctypes.c_int(0)
    rows = lib.lgbt_count_rows(path.encode(), delim.encode(),
                               ctypes.byref(cols))
    if rows <= 0 or cols.value <= 0:
        return None
    rows -= skip_rows
    out = np.zeros((rows, cols.value), np.float64)
    got_cols = ctypes.c_int(0)
    got = lib.lgbt_parse_delimited(
        path.encode(), delim.encode(), skip_rows,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        rows, cols.value, ctypes.byref(got_cols))
    if got < 0:
        return None
    return out[:got, :got_cols.value]


def values_to_bins_u8(values: np.ndarray, bounds: np.ndarray,
                      num_search: int, nan_bin: int) -> np.ndarray:
    lib = get_lib()
    assert lib is not None
    values = np.ascontiguousarray(values, np.float64)
    bounds = np.ascontiguousarray(bounds, np.float64)
    out = np.empty(len(values), np.uint8)
    lib.lgbt_values_to_bins(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(values),
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        num_search, nan_bin,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out


def sample_transpose(X: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Fused X[idx].T + float64 cast: one native streaming pass instead of
    the gather / transpose / cast NumPy chain. X must be C-contiguous
    [N, F] float32 or float64; idx sorted int64 row indices. Returns a
    contiguous [F, len(idx)] float64 sample, bit-identical to
    np.ascontiguousarray(X[idx].T, dtype=np.float64)."""
    lib = get_lib()
    assert lib is not None
    is_f32 = 1 if X.dtype == np.float32 else 0
    idx = np.ascontiguousarray(idx, np.int64)
    n_rows, f_total = X.shape
    out = np.empty((f_total, len(idx)), np.float64)
    lib.lgbt_sample_transpose(
        X.ctypes.data_as(ctypes.c_void_p), is_f32, f_total,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_long)), len(idx),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out


def find_numeric_bounds(sample_t: np.ndarray, max_bin: int,
                        min_data_in_bin: int, use_missing: bool,
                        zero_as_missing: bool):
    """Whole-matrix numeric boundary search (native FindBin loop over
    features, OpenMP). sample_t: [F, S] contiguous f64 raw sample.
    Returns (bounds_list[F], missing_type[F], minmax[F,2],
    zero_na[F,2])."""
    lib = get_lib()
    assert lib is not None
    sample_t = np.ascontiguousarray(sample_t, np.float64)
    n_feat, s = sample_t.shape
    stride = max_bin + 2
    bounds = np.empty(n_feat * stride, np.float64)
    nb = np.empty(n_feat, np.int32)
    mtype = np.empty(n_feat, np.int32)
    minmax = np.empty((n_feat, 2), np.float64)
    zero_na = np.empty((n_feat, 2), np.int64)
    lib.lgbt_find_numeric_bounds(
        sample_t.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_feat, s, max_bin, min_data_in_bin, int(use_missing),
        int(zero_as_missing),
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        nb.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        mtype.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        minmax.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        zero_na.ctypes.data_as(ctypes.POINTER(ctypes.c_long)))
    blist = [bounds[j * stride: j * stride + nb[j]].copy()
             for j in range(n_feat)]
    return blist, mtype, minmax, zero_na


def bin_matrix(X: np.ndarray, feat_idx: np.ndarray, bounds_flat: np.ndarray,
               bounds_off: np.ndarray, num_search: np.ndarray,
               nan_bin: np.ndarray, dtype) -> np.ndarray:
    """Quantize every listed numeric column of row-major X in one OpenMP
    pass (DatasetLoader's parallel bin construction analog)."""
    lib = get_lib()
    assert lib is not None
    # float32 is read natively: no whole-matrix float64 copy on the main
    # dense-ingestion path (a 10M x 100 f32 input would transiently
    # double its footprint otherwise)
    if X.dtype == np.float32:
        X = np.ascontiguousarray(X)
        is_f32 = 1
    else:
        X = np.ascontiguousarray(X, np.float64)
        is_f32 = 0
    n, f_total = X.shape
    feat_idx = np.ascontiguousarray(feat_idx, np.int32)
    bounds_flat = np.ascontiguousarray(bounds_flat, np.float64)
    bounds_off = np.ascontiguousarray(bounds_off, np.int64)
    num_search = np.ascontiguousarray(num_search, np.int32)
    nan_bin = np.ascontiguousarray(nan_bin, np.int32)
    out = np.empty((n, len(feat_idx)), dtype)
    lib.lgbt_bin_matrix(
        X.ctypes.data_as(ctypes.c_void_p), is_f32, n, f_total,
        feat_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        len(feat_idx),
        bounds_flat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        bounds_off.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        num_search.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        nan_bin.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        out.dtype.itemsize, out.ctypes.data_as(ctypes.c_void_p))
    return out


# ---------------------------------------------------------------------------
# native forest predictor (predict.cpp; reference predictor.hpp:30)
# ---------------------------------------------------------------------------

_PSRC = os.path.join(_DIR, "predict.cpp")
_PLIB_PATH = os.path.join(_DIR, "libpredict.so")
_plib: Optional[ctypes.CDLL] = None
_ptried = False


def get_predict_lib() -> Optional[ctypes.CDLL]:
    global _plib, _ptried
    if _plib is not None or _ptried:
        return _plib
    _ptried = True
    lib = _load_or_build(_PSRC, _PLIB_PATH,
                         flag_sets=(("-fopenmp",), ()))
    if lib is None:
        return None
    c_dp = ctypes.POINTER(ctypes.c_double)
    c_ip = ctypes.POINTER(ctypes.c_int)
    c_lp = ctypes.POINTER(ctypes.c_long)
    c_u8 = ctypes.POINTER(ctypes.c_uint8)
    c_u32 = ctypes.POINTER(ctypes.c_uint32)
    lib.lgbt_predict.restype = None
    lib.lgbt_predict.argtypes = [
        c_dp, ctypes.c_long, ctypes.c_int, ctypes.c_int, c_ip, ctypes.c_int,
        c_lp, c_lp, c_ip, c_dp, c_u8, c_ip, c_ip, c_dp,
        c_lp, c_lp, c_u32, c_lp,
        c_u8, c_dp, c_lp, c_ip, c_dp,
        ctypes.c_int, ctypes.c_int, c_dp]
    lib.lgbt_predict_leaf.restype = None
    lib.lgbt_predict_leaf.argtypes = [
        c_dp, ctypes.c_long, ctypes.c_int, ctypes.c_int,
        c_lp, c_lp, c_ip, c_dp, c_u8, c_ip, c_ip,
        c_lp, c_lp, c_u32, c_lp,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    _plib = lib
    return _plib


def predict_available() -> bool:
    return get_predict_lib() is not None


def _ptr(a, ct):
    return a.ctypes.data_as(ctypes.POINTER(ct))


def forest_predict(flat: dict, X: np.ndarray, k: int, start_tree: int,
                   end_tree: int) -> np.ndarray:
    """Run the native predictor over trees [start_tree, end_tree)."""
    lib = get_predict_lib()
    assert lib is not None
    X = np.ascontiguousarray(X, np.float64)
    n, nfeat = X.shape
    out = np.zeros((n, k), np.float64)
    lib.lgbt_predict(
        _ptr(X, ctypes.c_double), n, nfeat, flat["num_trees"],
        _ptr(flat["tree_class"], ctypes.c_int), k,
        _ptr(flat["node_off"], ctypes.c_long),
        _ptr(flat["leaf_off"], ctypes.c_long),
        _ptr(flat["split_feature"], ctypes.c_int),
        _ptr(flat["threshold"], ctypes.c_double),
        _ptr(flat["decision_type"], ctypes.c_uint8),
        _ptr(flat["left"], ctypes.c_int),
        _ptr(flat["right"], ctypes.c_int),
        _ptr(flat["leaf_value"], ctypes.c_double),
        _ptr(flat["catb_off"], ctypes.c_long),
        _ptr(flat["cat_boundaries"], ctypes.c_long),
        _ptr(flat["cat_threshold"], ctypes.c_uint32),
        _ptr(flat["catt_off"], ctypes.c_long),
        _ptr(flat["is_linear"], ctypes.c_uint8),
        _ptr(flat["leaf_const"], ctypes.c_double),
        _ptr(flat["lfeat_off"], ctypes.c_long),
        _ptr(flat["leaf_features"], ctypes.c_int),
        _ptr(flat["leaf_coeff"], ctypes.c_double),
        start_tree, end_tree, _ptr(out, ctypes.c_double))
    return out


def forest_predict_leaf(flat: dict, X: np.ndarray, start_tree: int,
                        end_tree: int) -> np.ndarray:
    lib = get_predict_lib()
    assert lib is not None
    X = np.ascontiguousarray(X, np.float64)
    n, nfeat = X.shape
    out = np.zeros((n, end_tree - start_tree), np.int32)
    lib.lgbt_predict_leaf(
        _ptr(X, ctypes.c_double), n, nfeat, flat["num_trees"],
        _ptr(flat["node_off"], ctypes.c_long),
        _ptr(flat["leaf_off"], ctypes.c_long),
        _ptr(flat["split_feature"], ctypes.c_int),
        _ptr(flat["threshold"], ctypes.c_double),
        _ptr(flat["decision_type"], ctypes.c_uint8),
        _ptr(flat["left"], ctypes.c_int),
        _ptr(flat["right"], ctypes.c_int),
        _ptr(flat["catb_off"], ctypes.c_long),
        _ptr(flat["cat_boundaries"], ctypes.c_long),
        _ptr(flat["cat_threshold"], ctypes.c_uint32),
        _ptr(flat["catt_off"], ctypes.c_long),
        start_tree, end_tree, _ptr(out, ctypes.c_int))
    return out
