// Native host-side data layer: greedy bin finding + text parsing.
//
// TPU-native equivalent of the reference's C++ data-ingestion hot paths:
// GreedyFindBin (src/io/bin.cpp:78), the CSV/TSV/LibSVM parsers
// (src/io/parser.cpp) and the buffered TextReader (utils/text_reader.h).
// The TPU compute path needs none of this on-device; these routines feed
// the host-side quantization pipeline at C++ speed and are reached from
// Python via ctypes (lightgbm_tpu/cext/__init__.py).
//
// Build: cc -O3 -shared -fPIC -fopenmp binning.cpp -o libbinning.so

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Greedy bin finding over distinct values (behavior of bin.cpp:78-150):
// values with counts >= mean bin size get dedicated bins; the rest are
// packed greedily to equalize bin populations. Returns number of bounds
// written to out_bounds (last is +inf).
// ---------------------------------------------------------------------------
int lgbt_greedy_find_bin(const double* distinct, const int* counts,
                         int num_distinct, int max_bin, long total_cnt,
                         int min_data_in_bin, double* out_bounds) {
  int nb = 0;
  if (num_distinct == 0) {
    out_bounds[nb++] = std::numeric_limits<double>::infinity();
    return nb;
  }
  auto check_eq = [](double a, double b) {
    double tol = 1e-9 * std::max(std::fabs(a), std::fabs(b));
    return a <= b + tol && a >= b - tol;
  };
  if (num_distinct <= max_bin) {
    int cur = 0;
    for (int i = 0; i < num_distinct - 1; ++i) {
      cur += counts[i];
      if (cur >= min_data_in_bin) {
        double v = (distinct[i] + distinct[i + 1]) / 2.0;
        if (nb == 0 || !check_eq(out_bounds[nb - 1], v)) {
          out_bounds[nb++] = v;
          cur = 0;
        }
      }
    }
    out_bounds[nb++] = std::numeric_limits<double>::infinity();
    return nb;
  }
  if (min_data_in_bin > 0) {
    long capped = std::min<long>(max_bin, total_cnt / min_data_in_bin);
    max_bin = static_cast<int>(std::max<long>(1, capped));
  }
  double mean_size = static_cast<double>(total_cnt) / max_bin;
  std::vector<char> is_big(num_distinct, 0);
  int rest_bins = max_bin;
  long rest_cnt = total_cnt;
  for (int i = 0; i < num_distinct; ++i) {
    if (counts[i] >= mean_size) {
      is_big[i] = 1;
      --rest_bins;
      rest_cnt -= counts[i];
    }
  }
  mean_size = static_cast<double>(rest_cnt) / std::max(rest_bins, 1);
  std::vector<double> uppers, lowers;
  lowers.push_back(distinct[0]);
  int cur = 0;
  for (int i = 0; i < num_distinct - 1; ++i) {
    if (!is_big[i]) rest_cnt -= counts[i];
    cur += counts[i];
    if (is_big[i] || cur >= mean_size ||
        (is_big[i + 1] && cur >= std::max(1.0, mean_size * 0.5))) {
      uppers.push_back(distinct[i]);
      lowers.push_back(distinct[i + 1]);
      if (static_cast<int>(uppers.size()) >= max_bin - 1) break;
      cur = 0;
      if (!is_big[i]) {
        --rest_bins;
        mean_size = rest_cnt / static_cast<double>(std::max(rest_bins, 1));
      }
    }
  }
  for (size_t i = 0; i < uppers.size(); ++i) {
    double v = (uppers[i] + lowers[i + 1]) / 2.0;
    if (nb == 0 || !check_eq(out_bounds[nb - 1], v)) out_bounds[nb++] = v;
  }
  out_bounds[nb++] = std::numeric_limits<double>::infinity();
  return nb;
}

// ---------------------------------------------------------------------------
// Distinct-value extraction from a sorted sample (bin.cpp:355-380 behavior):
// merges near-equal neighbours keeping the larger value. Returns count.
// ---------------------------------------------------------------------------
int lgbt_distinct(const double* sorted_values, int n, double* out_vals,
                  int* out_counts) {
  if (n == 0) return 0;
  int k = 0;
  out_vals[0] = sorted_values[0];
  out_counts[0] = 1;
  for (int i = 1; i < n; ++i) {
    double prev = out_vals[k];
    double tol = 1e-9 * std::max(std::fabs(prev),
                                 std::fabs(sorted_values[i]));
    if (sorted_values[i] > prev + tol) {
      ++k;
      out_vals[k] = sorted_values[i];
      out_counts[k] = 1;
    } else {
      out_vals[k] = sorted_values[i];  // keep larger
      ++out_counts[k];
    }
  }
  return k + 1;
}

// ---------------------------------------------------------------------------
// Buffered delimited-text parser (reference src/io/parser.cpp CSVParser /
// TSVParser + pipeline_reader.h). Parses a whole file of numeric rows into
// a dense row-major buffer. Returns rows parsed, or -1 on error;
// *out_cols reports detected column count.
// ---------------------------------------------------------------------------
long lgbt_parse_delimited(const char* path, char delim, int skip_rows,
                          double* out, long max_rows, int max_cols,
                          int* out_cols) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return -1;
  std::fseek(fp, 0, SEEK_END);
  long fsize = std::ftell(fp);
  std::fseek(fp, 0, SEEK_SET);
  std::vector<char> buf(fsize + 1);
  long rd = static_cast<long>(std::fread(buf.data(), 1, fsize, fp));
  std::fclose(fp);
  buf[rd] = '\0';

  long row = 0;
  int ncols = -1;
  char* p = buf.data();
  char* end = buf.data() + rd;
  for (int s = 0; s < skip_rows && p < end; ++s) {
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
  }
  while (p < end && row < max_rows) {
    if (*p == '\n' || *p == '\r') { ++p; continue; }
    int col = 0;
    while (p < end && *p != '\n') {
      char* q;
      double v = std::strtod(p, &q);
      if (q == p) {  // unparsable token; skip to next delim
        while (p < end && *p != delim && *p != '\n') ++p;
        v = std::nan("");
      } else {
        p = q;
      }
      if (col < max_cols) out[row * max_cols + col] = v;
      ++col;
      if (p < end && *p == delim) ++p;
      else break;
    }
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
    if (ncols < 0) ncols = col;
    for (int c = col; c < max_cols && c < ncols; ++c)
      out[row * max_cols + c] = 0.0;
    ++row;
  }
  *out_cols = ncols < 0 ? 0 : std::min(ncols, max_cols);
  return row;
}

// Count rows/columns for pre-allocation.
long lgbt_count_rows(const char* path, char delim, int* out_cols) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return -1;
  std::vector<char> chunk(1 << 20);
  long rows = 0;
  int cols = 1;
  bool first_line = true;
  bool line_started = false;
  size_t got;
  while ((got = std::fread(chunk.data(), 1, chunk.size(), fp)) > 0) {
    for (size_t i = 0; i < got; ++i) {
      char c = chunk[i];
      if (c == '\n') {
        if (line_started) ++rows;
        first_line = false;
        line_started = false;
      } else if (c != '\r') {
        line_started = true;
        if (first_line && c == delim) ++cols;
      }
    }
  }
  if (line_started) ++rows;
  std::fclose(fp);
  *out_cols = cols;
  return rows;
}

// ---------------------------------------------------------------------------
// Vectorized value->bin mapping (bin.h:149 ValueToBin): branchless binary
// search over upper bounds, NaN -> nan_bin (or default_bin).
// ---------------------------------------------------------------------------
void lgbt_values_to_bins(const double* values, long n, const double* bounds,
                         int num_search_bounds, int nan_bin, uint8_t* out) {
  for (long i = 0; i < n; ++i) {
    double v = values[i];
    if (std::isnan(v)) {
      out[i] = static_cast<uint8_t>(nan_bin);
      continue;
    }
    int lo = 0, hi = num_search_bounds;
    while (lo < hi) {
      int mid = (lo + hi) >> 1;
      if (bounds[mid] < v) lo = mid + 1;
      else hi = mid;
    }
    out[i] = static_cast<uint8_t>(lo);
  }
}

// ---------------------------------------------------------------------------
// Whole-matrix quantization (the DatasetLoader OMP bin-construction analog,
// dataset_loader.cpp): one pass over row-major X binning every used numeric
// feature, parallel over rows so each thread streams X sequentially.
//
// Each feature gets a small uniform grid over its bound range; grid cell c
// stores the insertion point of the cell's lower edge, so a value's binary
// search is confined to [grid[c], grid[c+1]] — typically 0-2 bounds. Never
// slower than a full binary search, ~4-6x fewer compares at max_bin=255.
// bounds_flat/bounds_off: concatenated per-feature search bounds.
// elem_size: 1 (uint8 out) or 2 (uint16 out); out is [n, n_used] row-major.
// ---------------------------------------------------------------------------
void lgbt_bin_matrix(const void* Xv, int x_is_f32, long n, int f_total,
                     const int* feat_idx, int n_used,
                     const double* bounds_flat, const long* bounds_off,
                     const int* num_search, const int* nan_bin,
                     int elem_size, void* out) {
  const double* X64 = static_cast<const double*>(Xv);
  const float* X32 = static_cast<const float*>(Xv);
  uint8_t* out8 = static_cast<uint8_t*>(out);
  uint16_t* out16 = static_cast<uint16_t*>(out);
  // grid cells per feature. Quantile-derived bounds cluster where the
  // data mass is (center cells of a randn feature hold many bounds at
  // coarse G, re-growing the per-value search); 2048 cells keep the
  // common cell at 0-1 candidates while the whole table stays
  // L2-resident (u16 x 2049 x n_used: ~115 KB at 28 features).
  const int G = 2048;
  std::vector<uint16_t> grid(static_cast<size_t>(n_used) * (G + 1));
  std::vector<double> glo(n_used), ginv(n_used);
  for (int j = 0; j < n_used; ++j) {
    const double* bnd = bounds_flat + bounds_off[j];
    int ns = num_search[j];
    uint16_t* gj = grid.data() + static_cast<size_t>(j) * (G + 1);
    if (ns <= 0) {
      glo[j] = 0.0; ginv[j] = 0.0;
      for (int c = 0; c <= G; ++c) gj[c] = 0;
      continue;
    }
    double lo_v = bnd[0], hi_v = bnd[ns - 1];
    double span = hi_v - lo_v;
    if (!(span > 0)) span = 1.0;
    glo[j] = lo_v;
    ginv[j] = G / span;
    for (int c = 0; c <= G; ++c) {
      double edge = lo_v + span * c / G;
      int s = 0, e = ns;
      while (s < e) {
        int mid = (s + e) >> 1;
        if (bnd[mid] < edge) s = mid + 1;
        else e = mid;
      }
      gj[c] = static_cast<uint16_t>(s);
    }
    gj[G] = static_cast<uint16_t>(ns);
  }
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (long i = 0; i < n; ++i) {
    const long row0 = i * f_total;
    for (int j = 0; j < n_used; ++j) {
      double v = x_is_f32
          ? static_cast<double>(X32[row0 + feat_idx[j]])
          : X64[row0 + feat_idx[j]];
      int b;
      if (std::isnan(v)) {
        b = nan_bin[j];
      } else {
        const double* bnd = bounds_flat + bounds_off[j];
        const uint16_t* gj = grid.data() + static_cast<size_t>(j) * (G + 1);
        double t = (v - glo[j]) * ginv[j];
        // !(t > 0) also catches NaN t (0*inf from degenerate spans /
        // infinite values) — casting NaN to int is UB and would index
        // the grid out of bounds
        int c = !(t > 0) ? 0 : (t >= G ? G - 1 : static_cast<int>(t));
        int lo = gj[c], hi = gj[c + 1];
        while (lo < hi) {
          int mid = (lo + hi) >> 1;
          if (bnd[mid] < v) lo = mid + 1;
          else hi = mid;
        }
        b = lo;
        // exactness fix-up: grid edges are recomputed in floating point,
        // so the narrowed range can miss by one bound at a cell edge
        while (b > 0 && bnd[b - 1] >= v) --b;
        while (b < num_search[j] && bnd[b] < v) ++b;
      }
      if (elem_size == 1) out8[i * n_used + j] = static_cast<uint8_t>(b);
      else out16[i * n_used + j] = static_cast<uint16_t>(b);
    }
  }
}

// ---------------------------------------------------------------------------
// Fused sample gather + transpose + float64 cast for mapper construction:
// out[f, i] = (double) X[idx[i], f], out row-major [f_total, n_idx].
// Replaces the NumPy chain X[idx] (row gather) -> .T -> ascontiguousarray
// (strided transpose-cast) — two full passes over the sample — with one
// streaming pass: idx is sorted, so row reads walk X forward, and for a
// fixed thread the writes advance f_total sequential column streams.
// ---------------------------------------------------------------------------
void lgbt_sample_transpose(const void* Xv, int x_is_f32, int f_total,
                           const long* idx, long n_idx, double* out) {
  const double* X64 = static_cast<const double*>(Xv);
  const float* X32 = static_cast<const float*>(Xv);
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (long i = 0; i < n_idx; ++i) {
    const long row0 = idx[i] * static_cast<long>(f_total);
    for (int f = 0; f < f_total; ++f) {
      out[static_cast<long>(f) * n_idx + i] =
          x_is_f32 ? static_cast<double>(X32[row0 + f]) : X64[row0 + f];
    }
  }
}

// ---------------------------------------------------------------------------
// Whole-matrix numeric bin-boundary search (the per-feature FindBin loop of
// DatasetLoader::ConstructBinMappersFromTextData, dataset_loader.cpp:~690,
// with bin.cpp:325-404 FindBin + :256 FindBinWithZeroAsOneBin semantics).
// Behavior-exact mirror of binning.py from_sample's numeric path so the
// native and NumPy pipelines produce identical mappers.
//
// sample_t: [n_feat, s] feature-major contiguous sample (raw values incl.
// zeros and NaNs). Per feature writes <= max_bin+1 bounds at stride
// (max_bin + 2) into bounds_out plus the mapper metadata scalars.
// ---------------------------------------------------------------------------
static int zero_as_one_bin(const double* distinct, const int* counts,
                           int n, int max_bin, long total_cnt,
                           int min_data_in_bin, double* out) {
  // mirror of binning.py _find_bin_zero_as_one
  const double kZero = 1e-35;
  const double kInf = std::numeric_limits<double>::infinity();
  if (n == 0) {
    out[0] = kInf;
    return 1;
  }
  long left_cnt_data = 0, right_cnt_data = 0;
  int left_cnt = n, right_start = -1;
  for (int i = 0; i < n; ++i) {
    if (distinct[i] <= -kZero) {
      left_cnt_data += counts[i];
    } else if (distinct[i] > kZero) {
      right_cnt_data += counts[i];
      if (right_start < 0) right_start = i;
    }
    if (distinct[i] > -kZero && left_cnt == n) left_cnt = i;
  }
  int nb = 0;
  if (left_cnt > 0) {
    int left_max_bin = std::max(
        1, static_cast<int>(static_cast<double>(left_cnt_data) /
                            std::max<long>(total_cnt, 1) / 2.0 *
                            (max_bin - 1)));
    nb = lgbt_greedy_find_bin(distinct, counts, left_cnt, left_max_bin,
                              left_cnt_data, min_data_in_bin, out);
    out[nb - 1] = -kZero;
  }
  if (right_start >= 0) {
    int right_max_bin = max_bin - 1 - nb;
    if (right_max_bin > 0) {
      out[nb++] = kZero;
      nb += lgbt_greedy_find_bin(distinct + right_start,
                                 counts + right_start, n - right_start,
                                 right_max_bin, right_cnt_data,
                                 min_data_in_bin, out + nb);
    } else {
      out[nb++] = kInf;
    }
  } else {
    out[nb++] = kInf;
  }
  return nb;
}

int lgbt_find_numeric_bounds(const double* sample_t, int n_feat, long s,
                             int max_bin, int min_data_in_bin,
                             int use_missing, int zero_as_missing,
                             double* bounds_out, int* nb_out,
                             int* mtype_out, double* minmax_out,
                             long* zero_na_out) {
  const double kZero = 1e-35;
  const int stride = max_bin + 2;
#if defined(_OPENMP)
#pragma omp parallel
#endif
  {
    std::vector<double> vals(s), dvals(s + 1);
    std::vector<int> dcnts(s + 1);
#if defined(_OPENMP)
#pragma omp for schedule(dynamic)
#endif
    for (int fj = 0; fj < n_feat; ++fj) {
      const double* col = sample_t + static_cast<long>(fj) * s;
      long nv = 0, na = 0;
      for (long i = 0; i < s; ++i) {
        double v = col[i];
        if (std::isnan(v)) {
          ++na;
        } else if (std::fabs(v) > kZero) {
          vals[nv++] = v;
        }
      }
      long zero_cnt = s - nv - na;
      int mtype = 0;  // NONE
      if (use_missing) {
        if (zero_as_missing) mtype = 1;       // ZERO
        else if (na > 0) mtype = 2;           // NAN
      }
      std::sort(vals.begin(), vals.begin() + nv);
      int nd = lgbt_distinct(vals.data(), static_cast<int>(nv),
                             dvals.data(), dcnts.data());
      if (zero_cnt > 0 || nd == 0) {
        // splice zero at its sorted position (binning.py:205-209)
        int pos = static_cast<int>(
            std::lower_bound(dvals.data(), dvals.data() + nd, 0.0) -
            dvals.data());
        if (pos >= nd || std::fabs(dvals[pos]) > kZero) {
          for (int i = nd; i > pos; --i) {
            dvals[i] = dvals[i - 1];
            dcnts[i] = dcnts[i - 1];
          }
          dvals[pos] = 0.0;
          dcnts[pos] = static_cast<int>(std::max<long>(zero_cnt, 0));
          ++nd;
        }
      }
      minmax_out[2 * fj] = nd ? dvals[0] : 0.0;
      minmax_out[2 * fj + 1] = nd ? dvals[nd - 1] : 0.0;
      double* bout = bounds_out + static_cast<long>(fj) * stride;
      int nb;
      if (mtype == 2) {
        nb = zero_as_one_bin(dvals.data(), dcnts.data(), nd, max_bin - 1,
                             s - na, min_data_in_bin, bout);
        bout[nb++] = std::numeric_limits<double>::quiet_NaN();
      } else {
        nb = zero_as_one_bin(dvals.data(), dcnts.data(), nd, max_bin,
                             s, min_data_in_bin, bout);
        if (mtype == 1 && nb == 2) mtype = 0;  // ZERO w/o split -> NONE
      }
      nb_out[fj] = nb;
      mtype_out[fj] = mtype;
      zero_na_out[2 * fj] = zero_cnt;
      zero_na_out[2 * fj + 1] = na;
    }
  }
  return 0;
}

}  // extern "C"
