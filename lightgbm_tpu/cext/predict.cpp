// Native forest predictor: OMP over rows, per-tree traversal.
//
// TPU-native equivalent of the reference prediction hot path
// (src/application/predictor.hpp:30 OMP row loop over
// Tree::Predict / NumericalDecision / CategoricalDecision,
// include/LightGBM/tree.h:335-412, with linear-leaf output
// src/io/tree.cpp:120-152). Device prediction uses the binned traversal
// kernels; THIS path serves host-side Booster.predict on raw matrices,
// where Python-level tree loops dominate for big forests.
//
// Decision-type byte layout matches the model format (tree.py):
//   bit0 = categorical, bit1 = default_left, bits2-3 = missing type
//   (0=None, 1=Zero, 2=NaN).
//
// Build: g++ -O3 -shared -fPIC -fopenmp predict.cpp -o libpredict.so

#include <cmath>
#include <cstdint>

extern "C" {

static const double kZeroThreshold = 1e-35;

void lgbt_predict(
    const double* X, long n, int nfeat, int num_trees,
    const int* tree_class, int k,
    const long* node_off,        // [T+1] internal-node offsets
    const long* leaf_off,        // [T+1] leaf offsets
    const int* split_feature, const double* threshold,
    const uint8_t* decision_type, const int* left, const int* right,
    const double* leaf_value,
    const long* catb_off,        // [T+1] cat_boundaries offsets
    const long* cat_boundaries,  // flattened per tree
    const uint32_t* cat_threshold,
    const long* catt_off,        // [T+1] cat_threshold offsets
    const uint8_t* is_linear,    // [T]
    const double* leaf_const,    // [sum nl]
    const long* lfeat_off,       // [sum nl + 1] per-leaf coeff offsets
    const int* leaf_features, const double* leaf_coeff,
    int start_tree, int end_tree,
    double* out)                 // [n, k], pre-initialized by caller
{
#pragma omp parallel for schedule(static)
  for (long i = 0; i < n; ++i) {
    const double* row = X + (size_t)i * nfeat;
    for (int t = start_tree; t < end_tree; ++t) {
      const long no = node_off[t];
      const long lo = leaf_off[t];
      const long nl = leaf_off[t + 1] - lo;
      int leaf;
      if (nl <= 1) {
        leaf = 0;
      } else {
        int node = 0;
        while (node >= 0) {
          const long j = no + node;
          const uint8_t dt = decision_type[j];
          double v = row[split_feature[j]];
          const int missing_t = (dt >> 2) & 3;
          // NaN maps to 0 unless the split's missing type is NaN
          // (reference CategoricalDecision/NumericalDecision preamble)
          if (std::isnan(v) && missing_t != 2) v = 0.0;
          bool go_left;
          if (dt & 1) {  // categorical (bitset membership -> left)
            go_left = false;
            if (std::isfinite(v) && v >= 0) {
              const long c = catb_off[t] + (long)threshold[j];
              const long wlo = cat_boundaries[c];
              const long whi = cat_boundaries[c + 1];
              // range-check in double BEFORE the int cast: huge category
              // values would overflow (int)v into a negative index
              if (v < (double)(whi - wlo) * 32.0) {
                const int iv = (int)v;
                go_left = (cat_threshold[catt_off[t] + wlo + iv / 32] >>
                           (iv % 32)) & 1u;
              }
            }
          } else {
            const bool defleft = (dt >> 1) & 1;
            if (missing_t == 1 && std::fabs(v) <= kZeroThreshold) {
              go_left = defleft;
            } else if (missing_t == 2 && std::isnan(v)) {
              go_left = defleft;
            } else {
              go_left = v <= threshold[j];
            }
          }
          node = go_left ? left[j] : right[j];
        }
        leaf = ~node;
      }
      double add;
      if (is_linear[t]) {
        const long li = lo + leaf;
        add = leaf_const[li];
        bool nan_found = false;
        for (long p = lfeat_off[li]; p < lfeat_off[li + 1]; ++p) {
          const double fv = row[leaf_features[p]];
          if (std::isnan(fv)) { nan_found = true; break; }
          add += leaf_coeff[p] * fv;
        }
        if (nan_found) add = leaf_value[lo + leaf];
      } else {
        add = leaf_value[lo + leaf];
      }
      out[(size_t)i * k + tree_class[t]] += add;
    }
  }
}

// leaf index per (row, tree) — predict_leaf_index support
void lgbt_predict_leaf(
    const double* X, long n, int nfeat, int num_trees,
    const long* node_off, const long* leaf_off,
    const int* split_feature, const double* threshold,
    const uint8_t* decision_type, const int* left, const int* right,
    const long* catb_off, const long* cat_boundaries,
    const uint32_t* cat_threshold, const long* catt_off,
    int start_tree, int end_tree,
    int* out)  // [n, end_tree - start_tree]
{
  const int span = end_tree - start_tree;
#pragma omp parallel for schedule(static)
  for (long i = 0; i < n; ++i) {
    const double* row = X + (size_t)i * nfeat;
    for (int t = start_tree; t < end_tree; ++t) {
      const long no = node_off[t];
      const long nl = leaf_off[t + 1] - leaf_off[t];
      int leaf = 0;
      if (nl > 1) {
        int node = 0;
        while (node >= 0) {
          const long j = no + node;
          const uint8_t dt = decision_type[j];
          double v = row[split_feature[j]];
          const int missing_t = (dt >> 2) & 3;
          // NaN maps to 0 unless the split's missing type is NaN
          // (reference CategoricalDecision/NumericalDecision preamble)
          if (std::isnan(v) && missing_t != 2) v = 0.0;
          bool go_left;
          if (dt & 1) {
            go_left = false;
            if (std::isfinite(v) && v >= 0) {
              const long c = catb_off[t] + (long)threshold[j];
              const long wlo = cat_boundaries[c];
              const long whi = cat_boundaries[c + 1];
              // range-check in double BEFORE the int cast: huge category
              // values would overflow (int)v into a negative index
              if (v < (double)(whi - wlo) * 32.0) {
                const int iv = (int)v;
                go_left = (cat_threshold[catt_off[t] + wlo + iv / 32] >>
                           (iv % 32)) & 1u;
              }
            }
          } else {
            const bool defleft = (dt >> 1) & 1;
            if (missing_t == 1 && std::fabs(v) <= kZeroThreshold) {
              go_left = defleft;
            } else if (missing_t == 2 && std::isnan(v)) {
              go_left = defleft;
            } else {
              go_left = v <= threshold[j];
            }
          }
          node = go_left ? left[j] : right[j];
        }
        leaf = ~node;
      }
      out[(size_t)i * span + (t - start_tree)] = leaf;
    }
  }
}

}  // extern "C"
