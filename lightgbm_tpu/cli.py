"""Command-line application (reference src/application/, src/main.cpp).

Same invocation contract as the reference CLI:
    lightgbm-tpu config=train.conf [key=value ...]
with tasks train / predict / refit / save_binary / convert_model
(application.cpp:85-269) and `key=value` config files ('#' comments,
CLI overrides file — application.cpp:50-83).
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .callback import log_evaluation
from .config import Config, parse_config_file
from .engine import train as train_fn
from .utils.log import Log
from .utils.file_io import open_file, _scheme_of

__all__ = ["main", "Application"]


def _parse_argv(argv: List[str]) -> Dict[str, str]:
    params: Dict[str, str] = {}
    for arg in argv:
        if "=" not in arg:
            Log.warning("Unknown CLI argument %s (expected key=value)", arg)
            continue
        key, value = arg.split("=", 1)
        params[key.strip()] = value.strip()
    file_params: Dict[str, str] = {}
    if "config" in params or "config_file" in params:
        path = params.get("config") or params.get("config_file")
        file_params = parse_config_file(path)
    # CLI overrides config file (application.cpp:75-80)
    file_params.update(params)
    return file_params


def _load_text_data(path: str, cfg: Config):
    """Load CSV/TSV/LibSVM training file.

    Reference Parser auto-detection (src/io/parser.cpp): tab/comma sniffing,
    label in column `label_column` (default 0).
    """
    with open_file(path) as fh:
        first = fh.readline().strip()
    if ":" in first.split(" ")[-1] and "," not in first:
        # LibSVM format: label idx:val idx:val ...
        return _load_libsvm(path)
    delim = "\t" if "\t" in first else ","
    skip = 1 if cfg.header else 0
    from . import cext
    # the native parser mmaps local files; URI paths use the virtual FS
    data = None if _scheme_of(path) else \
        cext.parse_delimited(path, delim, skip)
    if data is None:
        with open_file(path) as fh:
            data = np.loadtxt(fh, delimiter=delim, skiprows=skip, ndmin=2)
    label_col = 0
    if cfg.label_column.startswith("name:"):
        Log.fatal("label_column=name: requires header parsing; use index")
    elif cfg.label_column:
        label_col = int(cfg.label_column)
    y = data[:, label_col].astype(np.float32)
    X = np.delete(data, label_col, axis=1)
    return X, y


def _load_libsvm(path: str):
    rows = []
    labels = []
    max_idx = -1
    with open_file(path) as fh:
        for line in fh:
            parts = line.strip().split()
            if not parts:
                continue
            labels.append(float(parts[0]))
            feats = {}
            for tok in parts[1:]:
                i, v = tok.split(":")
                feats[int(i)] = float(v)
                max_idx = max(max_idx, int(i))
            rows.append(feats)
    X = np.zeros((len(rows), max_idx + 1))
    for r, feats in enumerate(rows):
        for i, v in feats.items():
            X[r, i] = v
    return X, np.asarray(labels, np.float32)


def _maybe_load_group(data_path: str) -> Optional[np.ndarray]:
    """LightGBM reads <data>.query / <data>.group side files."""
    import os
    for ext in (".query", ".group"):
        p = data_path + ext
        if os.path.exists(p):
            return np.loadtxt(p, dtype=np.int64, ndmin=1)
    return None


def _maybe_load_weight(data_path: str) -> Optional[np.ndarray]:
    import os
    p = data_path + ".weight"
    if os.path.exists(p):
        return np.loadtxt(p, dtype=np.float32, ndmin=1)
    return None


class Application:
    """Task dispatcher (reference application.cpp:31-269)."""

    def __init__(self, argv: List[str]):
        self.params = _parse_argv(argv)
        self.config = Config(self.params)
        Log.set_verbosity(self.config.verbosity)
        # arm the flight recorder as soon as the config exists — a
        # failure before any Booster is built (bad data path, schema
        # error) must still honor flightrec_dir= for its bundle
        from .observability.registry import registry
        registry.configure_from_config(self.config)

    def run(self) -> None:
        task = self.config.task
        if task == "train" and self.config.num_machines > 1:
            # before any data/backend work, like the reference's
            # Network::Init at InitTrain start (application.cpp:165)
            from .parallel import setup_multihost
            setup_multihost(self.config.num_machines, self.config.machines,
                            self.config.machine_list_filename,
                            self.config.local_listen_port)
        if task == "train":
            self.train()
        elif task in ("predict", "prediction", "test"):
            self.predict()
        elif task == "refit":
            self.refit()
        elif task == "convert_model":
            self.convert_model()
        elif task == "save_binary":
            self.save_binary()
        elif task == "serve":
            self.serve()
        elif task == "loop":
            self.loop()
        else:
            Log.fatal("Unknown task %s", task)

    # ------------------------------------------------------------------
    def _load_train_dataset(self) -> Dataset:
        cfg = self.config
        from .data import BinnedDataset
        if BinnedDataset.is_binary_file(cfg.data):
            return Dataset(cfg.data, params=dict(self.params))
        if cfg.stream_input:
            # out-of-core ingestion (docs/Streaming.md): the text/npy
            # file is never materialized — Dataset.construct streams it
            # through the two-pass loader. Row partitioning happens at
            # file granularity (pre_partition), so the shared-file
            # auto-split path falls back to in-memory loading.
            if cfg.num_machines > 1 and not cfg.pre_partition:
                Log.warning(
                    "stream_input with num_machines > 1 requires "
                    "pre_partition=true (each machine streams its own "
                    "file); falling back to in-memory loading")
            else:
                return Dataset(
                    cfg.data, group=_maybe_load_group(cfg.data),
                    weight=_maybe_load_weight(cfg.data),
                    params=dict(self.params))
        X, y = _load_text_data(cfg.data, cfg)
        group = _maybe_load_group(cfg.data)
        weight = _maybe_load_weight(cfg.data)
        X, y, group, weight = self._partition_rows(X, y, group, weight)
        return Dataset(X, label=y, group=group, weight=weight,
                       params=dict(self.params))

    def _partition_rows(self, X, y, group, weight):
        """Multi-machine row assignment (reference
        dataset_loader.cpp:560-592): with pre_partition=false every
        machine reads the shared file and keeps its contiguous block —
        query-granular when ranking groups exist, so no query spans
        machines (dataset_loader.cpp:569-590). pre_partition=true means
        each machine's file already IS its partition."""
        cfg = self.config
        if cfg.num_machines <= 1 or cfg.pre_partition:
            return X, y, group, weight
        import jax
        nproc, rank = jax.process_count(), jax.process_index()
        if nproc <= 1:
            return X, y, group, weight
        n = len(y)
        if group is not None:
            bounds = np.concatenate([[0], np.cumsum(group)])
            qlo = len(group) * rank // nproc
            qhi = len(group) * (rank + 1) // nproc
            lo, hi = int(bounds[qlo]), int(bounds[qhi])
            group = group[qlo:qhi]
        else:
            lo, hi = n * rank // nproc, n * (rank + 1) // nproc
        X, y = X[lo:hi], y[lo:hi]
        if weight is not None:
            weight = weight[lo:hi]
        return X, y, group, weight

    def train(self) -> None:
        cfg = self.config
        if not cfg.data:
            Log.fatal("No training data: set data=<file>")
        dtrain = self._load_train_dataset()
        valid_sets, valid_names = [], []
        if cfg.valid:
            for i, vpath in enumerate(str(cfg.valid).split(",")):
                vgroup = _maybe_load_group(vpath)
                if cfg.stream_input:
                    # stream the valid file too, aligned with the
                    # training dataset's frozen bin mappers
                    valid_sets.append(Dataset(vpath, group=vgroup,
                                              reference=dtrain,
                                              params=dict(self.params)))
                else:
                    vX, vy = _load_text_data(vpath, cfg)
                    valid_sets.append(Dataset(vX, label=vy, group=vgroup,
                                              reference=dtrain))
                valid_names.append(f"valid_{i + 1}")
        callbacks = [log_evaluation(cfg.metric_freq)]
        if cfg.snapshot_freq > 0:
            # periodic model snapshots (reference gbdt.cpp:279-283:
            # "snapshot_iter_<n>" files every snapshot_freq iterations)
            out_model = cfg.output_model

            def _snapshot(env):
                it = env.iteration + 1
                if it % cfg.snapshot_freq == 0:
                    path = f"{out_model}.snapshot_iter_{it}"
                    env.model.save_model(path)
                    Log.info("Saved snapshot to %s", path)

            callbacks.append(_snapshot)
        init_model = cfg.input_model if cfg.input_model else None
        resume_from = None
        if cfg.checkpoint_period > 0 and cfg.checkpoint_dir:
            # auto-resume (docs/Reliability.md): a killed task=train run
            # rerun with the same conf picks up at its last checkpoint;
            # engine.train adds the periodic checkpoint callback itself
            from .reliability.checkpoint import latest_checkpoint
            found = latest_checkpoint(cfg.checkpoint_dir)
            if found is not None:
                resume_from = found
                init_model = None
                Log.info("Auto-resuming from checkpoint %s", found)
        msrv = None
        if cfg.observe and cfg.observe_metrics_port > 0:
            # live Prometheus scrape surface for the duration of the run
            from .observability import MetricsHTTPServer
            from .observability import registry as _obs
            msrv = MetricsHTTPServer(_obs.prometheus_text, _obs.snapshot,
                                     port=cfg.observe_metrics_port)
            Log.info("observability metrics at %s", msrv.url)
        try:
            booster = train_fn(dict(self.params), dtrain,
                               num_boost_round=cfg.num_iterations,
                               valid_sets=valid_sets or None,
                               valid_names=valid_names or None,
                               callbacks=callbacks,
                               init_model=init_model,
                               resume_from=resume_from)
        finally:
            if msrv is not None:
                msrv.close()
        st = getattr(getattr(dtrain, "_binned", None), "stream_stats", None)
        if st is not None and st.chunks and cfg.stream_input:
            Log.info("streamed ingest: %d chunks / %d rows, %.1f%% "
                     "parse/bin overlap, %.0f rows/s",
                     st.chunks, st.rows, 100.0 * st.overlap_frac,
                     st.rows_per_sec)
        stats = getattr(getattr(booster, "gbdt", None),
                        "_pipeline_stats", None)
        if stats is not None and stats.blocks:
            Log.info("pipelined executor: %d blocks / %d iterations, "
                     "%.1f%% host/device overlap",
                     stats.blocks, stats.iterations,
                     100.0 * stats.overlap_frac)
        booster.save_model(cfg.output_model)
        Log.info("Finished training, model saved to %s", cfg.output_model)
        if cfg.observe and cfg.observe_trace_file:
            from .observability import registry as _obs
            fmt = _obs.dump_trace(cfg.observe_trace_file)
            Log.info("Wrote %s span trace to %s", fmt,
                     cfg.observe_trace_file)

    def predict(self) -> None:
        cfg = self.config
        if not cfg.data:
            Log.fatal("No prediction data: set data=<file>")
        if not cfg.input_model:
            Log.fatal("No model file: set input_model=<file>")
        booster = Booster(model_file=cfg.input_model)
        X, _ = _load_text_data(cfg.data, cfg)
        pred = booster.predict(
            X, raw_score=cfg.predict_raw_score,
            pred_leaf=cfg.predict_leaf_index,
            pred_contrib=cfg.predict_contrib,
            start_iteration=cfg.start_iteration_predict,
            num_iteration=cfg.num_iteration_predict,
            pred_early_stop=cfg.pred_early_stop,
            pred_early_stop_freq=cfg.pred_early_stop_freq,
            pred_early_stop_margin=cfg.pred_early_stop_margin)
        out = np.asarray(pred)
        if out.ndim == 1:
            out = out[:, None]
        np.savetxt(cfg.output_result, out, delimiter="\t", fmt="%.18g")
        Log.info("Finished prediction, results saved to %s",
                 cfg.output_result)

    def refit(self) -> None:
        cfg = self.config
        booster = Booster(model_file=cfg.input_model)
        X, y = _load_text_data(cfg.data, cfg)
        new_booster = booster.refit(X, y, decay_rate=cfg.refit_decay_rate)
        new_booster.save_model(cfg.output_model)
        Log.info("Finished refit, model saved to %s", cfg.output_model)

    def save_binary(self) -> None:
        """task=save_binary: quantize the data once, cache to <data>.bin
        (reference application.cpp save_binary task)."""
        cfg = self.config
        if not cfg.data:
            Log.fatal("No training data: set data=<file>")
        dtrain = self._load_train_dataset()
        out = cfg.data + ".bin"
        dtrain.save_binary(out)
        Log.info("Dataset saved to binary file %s", out)

    def serve(self) -> None:
        """task=serve: score a request file through the serving engine.

        Unlike task=predict, rows go through the device-resident
        `serving.Server` — registry load, shape-bucketed compiled
        predictor, micro-batching — as a mixed-size request stream, and
        a metrics snapshot (QPS, latency percentiles, bucket cache
        hits, sheds) lands next to the predictions."""
        import json
        cfg = self.config
        if not cfg.data:
            Log.fatal("No request data: set data=<file>")
        if not cfg.input_model:
            Log.fatal("No model file: set input_model=<file>")
        from .serving import Server
        X, _ = _load_text_data(cfg.data, cfg)
        with Server.from_config(cfg) as server:
            if cfg.observe:
                from .observability import registry as _obs
                _obs.enable(ring=cfg.observe_ring)
                msrv = server.start_metrics_server(
                    port=cfg.observe_metrics_port)
                Log.info("observability metrics at %s", msrv.url)
            server.load_model("default", model_file=cfg.input_model)
            # mixed-size request stream: walk the file in growing chunks
            # so the bucket cache sees many batch shapes, like live
            # traffic would produce
            futures = []
            lo, step = 0, 1
            while lo < len(X):
                hi = min(lo + step, len(X))
                futures.append(server.predict_async(
                    "default", X[lo:hi], raw_score=cfg.predict_raw_score))
                lo = hi
                step = min(step * 2, max(cfg.serve_max_batch_size, 1))
            preds = [np.asarray(f.result()) for f in futures]
            out = np.concatenate(
                [p[:, None] if p.ndim == 1 else p for p in preds], axis=0)
            np.savetxt(cfg.output_result, out, delimiter="\t", fmt="%.18g")
            snapshot = server.metrics_snapshot()
        metrics_path = cfg.serve_metrics_file or \
            cfg.output_result + ".metrics.json"
        with open_file(metrics_path, "w") as fh:
            json.dump(snapshot, fh, indent=2)
            fh.write("\n")
        m = snapshot["models"]["default"]
        Log.info("Finished serving %d requests (%d rows, %d compiled "
                 "buckets), results saved to %s, metrics to %s",
                 m["requests"], m["rows"], m["buckets_compiled"],
                 cfg.output_result, metrics_path)
        if cfg.observe and cfg.observe_trace_file:
            from .observability import registry as _obs
            fmt = _obs.dump_trace(cfg.observe_trace_file)
            Log.info("Wrote %s span trace to %s", fmt,
                     cfg.observe_trace_file)

    def loop(self) -> None:
        """task=loop: the continuous train -> refresh -> serve loop
        (docs/Continuous.md).

        Windows of `loop_window_chunks` stream chunks are pulled from
        `data`, each refresh continues boosting from the live model,
        and every new generation is checkpointed under `loop_dir` and
        hot-swapped into a serving entry under live traffic. The loop
        is kill-survivable at every seam: rerunning the same conf
        resumes from the GENERATION marker."""
        import json
        cfg = self.config
        if not cfg.data:
            Log.fatal("No streaming data: set data=<file>")
        if not cfg.loop_dir:
            Log.fatal("No loop state dir: set loop_dir=<dir>")
        from .continuous import ContinuousTrainer
        from .serving import Server
        from .streaming import source_from_path
        if cfg.label_column.startswith("name:"):
            Log.fatal("label_column=name: requires header parsing; "
                      "use index")
        source = source_from_path(cfg.data,
                                  chunk_rows=cfg.stream_chunk_rows,
                                  label_col=cfg.label_column or 0,
                                  header=cfg.header)
        with Server.from_config(cfg) as server:
            if cfg.observe:
                from .observability import registry as _obs
                _obs.enable(ring=cfg.observe_ring)
                msrv = server.start_metrics_server(
                    port=cfg.observe_metrics_port)
                Log.info("observability metrics at %s", msrv.url)
            trainer = ContinuousTrainer(cfg, source, server,
                                        params=dict(self.params))
            published = trainer.run()
            snapshot = server.metrics_snapshot()
        if trainer._live_model_str is not None:
            with open_file(cfg.output_model, "w") as fh:
                fh.write(trainer._live_model_str)
        from .observability import registry as _obs
        fresh = _obs.freshness_snapshot()
        snapshot["freshness"] = fresh
        metrics_path = cfg.serve_metrics_file or \
            cfg.output_model + ".metrics.json"
        with open_file(metrics_path, "w") as fh:
            json.dump(snapshot, fh, indent=2)
            fh.write("\n")
        Log.info("Finished loop: %d generations published (live "
                 "generation %d, %d quarantined windows, last "
                 "data-to-serve %.3fs), model saved to %s, metrics "
                 "to %s", published, fresh["generation"],
                 fresh["quarantined_windows"], fresh["data_to_serve_s"],
                 cfg.output_model, metrics_path)
        if cfg.observe and cfg.observe_trace_file:
            fmt = _obs.dump_trace(cfg.observe_trace_file)
            Log.info("Wrote %s span trace to %s", fmt,
                     cfg.observe_trace_file)

    def convert_model(self) -> None:
        cfg = self.config
        booster = Booster(model_file=cfg.input_model)
        model = booster._host_model()
        code = _model_to_if_else(model)
        with open_file(cfg.convert_model, "w") as fh:
            fh.write(code)
        Log.info("Model converted to %s", cfg.convert_model)


def _model_to_if_else(model) -> str:
    """C++ if-else codegen (reference SaveModelToIfElse,
    gbdt_model_text.cpp:286 / Tree::ToIfElse tree.cpp)."""
    lines = ["#include <cmath>", "#include <cstdint>", "",
             "// generated by lightgbm_tpu convert_model", ""]
    for ti, t in enumerate(model.trees):
        lines.append(f"double PredictTree{ti}(const double* arr) {{")

        def emit(node, indent):
            pad = "  " * indent
            if node < 0:
                return [f"{pad}return {float(t.leaf_value[~node])!r};"]
            f = int(t.split_feature[node])
            thr = float(t.threshold[node])
            dt = int(t.decision_type[node])
            if dt & 1:
                # categorical: threshold is a cat_boundaries index; decode
                # the category-value bitset into an explicit membership
                # test (reference Tree::ToIfElse CategoricalDecision /
                # FindInBitset, tree.cpp)
                ci = int(thr)
                lo = int(t.cat_boundaries[ci])
                hi = int(t.cat_boundaries[ci + 1])
                vals = [(w - lo) * 32 + b for w in range(lo, hi)
                        for b in range(32)
                        if (int(t.cat_threshold[w]) >> b) & 1]
                in_set = " || ".join(f"v{node} == {v}" for v in vals) \
                    or "false"
                # non-finite / negative / huge values go right like
                # HostTree.predict_rows (tree.py) — also keeps the
                # double->int cast defined
                cond = (f"std::isfinite(arr[{f}]) && arr[{f}] >= 0.0 && "
                        f"arr[{f}] < 2147483647.0 && "
                        f"[&]{{ int v{node} = static_cast<int>(arr[{f}]); "
                        f"return {in_set}; }}()")
            else:
                # numerical; mirror HostTree.predict_rows / reference
                # NumericalDecision (tree.h:335-412): missing_type NAN
                # routes NaN by default_left; NONE/ZERO first map NaN->0,
                # then ZERO routes |v|<=kZeroThreshold by default_left
                mt = (dt >> 2) & 3
                dl = bool(dt & 2)
                if mt == 2:
                    if dl:
                        cond = (f"std::isnan(arr[{f}]) || "
                                f"arr[{f}] <= {thr!r}")
                    else:
                        cond = (f"!std::isnan(arr[{f}]) && "
                                f"arr[{f}] <= {thr!r}")
                elif mt == 1:
                    cond = (f"[&]{{ double u{node} = std::isnan(arr[{f}])"
                            f" ? 0.0 : arr[{f}]; "
                            f"return std::fabs(u{node}) <= 1e-35 ? "
                            f"{str(dl).lower()} : u{node} <= {thr!r}; }}()")
                else:
                    cond = (f"(std::isnan(arr[{f}]) ? 0.0 : arr[{f}])"
                            f" <= {thr!r}")
            out = [f"{pad}if ({cond}) {{"]
            out += emit(int(t.left_child[node]), indent + 1)
            out += [f"{pad}}} else {{"]
            out += emit(int(t.right_child[node]), indent + 1)
            out += [f"{pad}}}"]
            return out

        if t.num_leaves <= 1:
            lines.append(f"  return {float(t.leaf_value[0])!r};")
        else:
            lines.extend(emit(0, 1))
        lines.append("}")
        lines.append("")
    n = len(model.trees)
    lines.append("double Predict(const double* arr) {")
    lines.append("  double sum = 0.0;")
    for ti in range(n):
        lines.append(f"  sum += PredictTree{ti}(arr);")
    lines.append("  return sum;")
    lines.append("}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    try:
        Application(argv).run()
    except Exception as e:  # mirror main.cpp catch-all
        Log.warning("Met Exceptions: %s", str(e))
        from .observability.flightrec import recorder as _flightrec
        _flightrec.record_exception("cli.main", e)
        _flightrec.flush("exception")
        raise
    return 0


if __name__ == "__main__":
    sys.exit(main())
