"""Configuration system: typed parameter registry with alias resolution.

TPU-native re-design of the reference config layer
(reference: include/LightGBM/config.h:34 `struct Config`,
src/io/config_auto.cpp:10 alias table, src/io/config.cpp:261 CheckParamConflict).

Instead of generated C++ getters, parameters are declared once in a registry
(`_PARAMS`) carrying type, default, aliases and constraints; `Config` is a
plain dataclass-like object resolved from a user dict / config file / CLI
key=value pairs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["Config", "ParamSpec", "param_dict_to_config", "PARAM_ALIASES"]


@dataclasses.dataclass
class ParamSpec:
    name: str
    type: type
    default: Any
    aliases: Tuple[str, ...] = ()
    check: Optional[Callable[[Any], bool]] = None
    desc: str = ""


def _p(name, type_, default, aliases=(), check=None, desc=""):
    return ParamSpec(name, type_, default, tuple(aliases), check, desc)


# Registry mirrors reference include/LightGBM/config.h. Grouped as in
# docs/Parameters.rst: core, learning control, IO, objective, metric, network.
_PARAMS: List[ParamSpec] = [
    # ---- Core parameters (config.h:96-226) ----
    _p("config", str, "", ("config_file",)),
    _p("task", str, "train",
       ("task_type",),
       # "prediction"/"test" are reference-CLI spellings of "predict"
       # (application.cpp:85); cli.Application.run routes all three
       lambda v: v in ("train", "predict", "prediction", "test",
                       "convert_model", "refit", "save_binary", "serve",
                       "loop")),
    _p("objective", str, "regression",
       ("objective_type", "app", "application", "loss")),
    _p("boosting", str, "gbdt",
       ("boosting_type", "boost"),
       lambda v: v in ("gbdt", "rf", "dart", "goss")),
    _p("data", str, "", ("train", "train_data", "train_data_file", "data_filename")),
    _p("valid", str, "", ("test", "valid_data", "valid_data_file", "test_data",
                          "test_data_file", "valid_filenames")),
    _p("num_iterations", int, 100,
       ("num_iteration", "n_iter", "num_tree", "num_trees", "num_round",
        "num_rounds", "nrounds", "num_boost_round", "n_estimators",
        "max_iter")),
    _p("learning_rate", float, 0.1, ("shrinkage_rate", "eta"),
       lambda v: v > 0.0),
    _p("num_leaves", int, 31, ("num_leaf", "max_leaves", "max_leaf",
                               "max_leaf_nodes"),
       lambda v: 1 < v <= 131072),
    _p("tree_learner", str, "serial",
       ("tree", "tree_type", "tree_learner_type"),
       lambda v: v in ("serial", "feature", "data", "voting")),
    _p("num_threads", int, 0, ("num_thread", "nthread", "nthreads", "n_jobs")),
    _p("device_type", str, "tpu", ("device",),
       lambda v: v in ("cpu", "gpu", "cuda", "cuda_exp", "tpu")),
    _p("seed", int, 0, ("random_seed", "random_state")),
    _p("deterministic", bool, False),
    # ---- Learning control (config.h:229-680) ----
    _p("force_col_wise", bool, False),
    _p("force_row_wise", bool, False),
    _p("histogram_pool_size", float, -1.0, ("hist_pool_size",)),
    _p("max_depth", int, -1),
    _p("min_data_in_leaf", int, 20,
       ("min_data_per_leaf", "min_data", "min_child_samples", "min_samples_leaf"),
       lambda v: v >= 0),
    _p("min_sum_hessian_in_leaf", float, 1e-3,
       ("min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian",
        "min_child_weight"),
       lambda v: v >= 0.0),
    _p("bagging_fraction", float, 1.0,
       ("sub_row", "subsample", "bagging"),
       lambda v: 0.0 < v <= 1.0),
    _p("pos_bagging_fraction", float, 1.0,
       ("pos_sub_row", "pos_subsample", "pos_bagging"),
       lambda v: 0.0 < v <= 1.0),
    _p("neg_bagging_fraction", float, 1.0,
       ("neg_sub_row", "neg_subsample", "neg_bagging"),
       lambda v: 0.0 < v <= 1.0),
    _p("bagging_freq", int, 0, ("subsample_freq",)),
    _p("bagging_seed", int, 3, ("bagging_fraction_seed",)),
    _p("feature_fraction", float, 1.0,
       ("sub_feature", "colsample_bytree"), lambda v: 0.0 < v <= 1.0),
    _p("feature_fraction_bynode", float, 1.0,
       ("sub_feature_bynode", "colsample_bynode"), lambda v: 0.0 < v <= 1.0),
    _p("feature_fraction_seed", int, 2),
    _p("extra_trees", bool, False, ("extra_tree",)),
    _p("extra_seed", int, 6),
    _p("early_stopping_round", int, 0,
       ("early_stopping_rounds", "early_stopping", "n_iter_no_change")),
    _p("first_metric_only", bool, False),
    _p("max_delta_step", float, 0.0, ("max_tree_output", "max_leaf_output")),
    _p("lambda_l1", float, 0.0, ("reg_alpha", "l1_regularization"),
       lambda v: v >= 0.0),
    _p("lambda_l2", float, 0.0, ("reg_lambda", "lambda", "l2_regularization"),
       lambda v: v >= 0.0),
    _p("linear_lambda", float, 0.0, (), lambda v: v >= 0.0),
    _p("min_gain_to_split", float, 0.0, ("min_split_gain",),
       lambda v: v >= 0.0),
    _p("drop_rate", float, 0.1, ("rate_drop",), lambda v: 0.0 <= v <= 1.0),
    _p("max_drop", int, 50),
    _p("skip_drop", float, 0.5, (), lambda v: 0.0 <= v <= 1.0),
    _p("xgboost_dart_mode", bool, False),
    _p("uniform_drop", bool, False),
    _p("drop_seed", int, 4),
    _p("top_rate", float, 0.2, (), lambda v: 0.0 <= v <= 1.0),
    _p("other_rate", float, 0.1, (), lambda v: 0.0 <= v <= 1.0),
    _p("min_data_per_group", int, 100, (), lambda v: v > 0),
    _p("max_cat_threshold", int, 32, (), lambda v: v > 0),
    _p("cat_l2", float, 10.0, (), lambda v: v >= 0.0),
    _p("cat_smooth", float, 10.0, (), lambda v: v >= 0.0),
    _p("max_cat_to_onehot", int, 4, (), lambda v: v > 0),
    _p("top_k", int, 20, ("topk",), lambda v: v > 0),
    _p("monotone_constraints", list, None, ("mc", "monotone_constraint",
                                            "monotonic_cst")),
    _p("monotone_constraints_method", str, "basic",
       ("monotone_constraining_method", "mc_method"),
       lambda v: v in ("basic", "intermediate", "advanced")),
    _p("monotone_penalty", float, 0.0, ("monotone_splits_penalty",
                                        "ms_penalty", "mc_penalty"),
       lambda v: v >= 0.0),
    _p("feature_contri", list, None, ("feature_contrib", "fc", "fp",
                                      "feature_penalty")),
    _p("forcedsplits_filename", str, "", ("fs", "forced_splits_filename",
                                          "forced_splits_file", "forced_splits")),
    _p("refit_decay_rate", float, 0.9, (), lambda v: 0.0 <= v <= 1.0),
    _p("cegb_tradeoff", float, 1.0, (), lambda v: v >= 0.0),
    _p("cegb_penalty_split", float, 0.0, (), lambda v: v >= 0.0),
    _p("cegb_penalty_feature_lazy", list, None),
    _p("cegb_penalty_feature_coupled", list, None),
    _p("path_smooth", float, 0.0, (), lambda v: v >= 0.0),
    _p("interaction_constraints", list, None),
    _p("verbosity", int, 1, ("verbose",)),
    _p("input_model", str, "", ("model_input", "model_in")),
    _p("output_model", str, "LightGBM_model.txt",
       ("model_output", "model_out")),
    _p("saved_feature_importance_type", int, 0),
    _p("snapshot_freq", int, -1, ("save_period",)),
    # ---- IO / dataset (config.h:683-940) ----
    _p("max_bin", int, 255, ("max_bins",), lambda v: v > 1),
    _p("max_bin_by_feature", list, None),
    _p("min_data_in_bin", int, 3, (), lambda v: v > 0),
    _p("bin_construct_sample_cnt", int, 200000, ("subsample_for_bin",),
       lambda v: v > 0),
    _p("data_random_seed", int, 1, ("data_seed",)),
    _p("is_enable_sparse", bool, True, ("is_sparse", "enable_sparse", "sparse")),
    _p("enable_bundle", bool, True, ("is_enable_bundle", "bundle")),
    _p("use_missing", bool, True),
    _p("zero_as_missing", bool, False),
    _p("feature_pre_filter", bool, True),
    _p("pre_partition", bool, False, ("is_pre_partition",)),
    _p("two_round", bool, False, ("two_round_loading", "use_two_round_loading")),
    _p("header", bool, False, ("has_header",)),
    _p("label_column", str, "", ("label",)),
    _p("weight_column", str, "", ("weight",)),
    _p("group_column", str, "", ("group", "group_id", "query_column", "query",
                                 "query_id")),
    _p("ignore_column", str, "", ("ignore_feature", "blacklist")),
    _p("categorical_feature", str, "", ("cat_feature", "categorical_column",
                                        "cat_column")),
    _p("forcedbins_filename", str, ""),
    _p("save_binary", bool, False, ("is_save_binary", "is_save_binary_file")),
    _p("precise_float_parser", bool, False),
    # ---- Predict (config.h:943-1003) ----
    _p("start_iteration_predict", int, 0),
    _p("num_iteration_predict", int, -1),
    _p("predict_raw_score", bool, False, ("is_predict_raw_score",
                                          "predict_rawscore", "raw_score")),
    _p("predict_leaf_index", bool, False, ("is_predict_leaf_index",
                                           "leaf_index")),
    _p("predict_contrib", bool, False, ("is_predict_contrib", "contrib")),
    _p("predict_disable_shape_check", bool, False),
    _p("pred_early_stop", bool, False),
    _p("pred_early_stop_freq", int, 10),
    _p("pred_early_stop_margin", float, 10.0),
    _p("output_result", str, "LightGBM_predict_result.txt",
       ("predict_result", "prediction_result", "predict_name",
        "prediction_name", "pred_name", "name_pred")),
    # ---- Serving (lightgbm_tpu/serving/, task=serve) ----
    _p("serve_max_batch_size", int, 1024, ("max_batch_size",),
       lambda v: v > 0),
    _p("serve_max_wait_ms", float, 2.0,
       ("max_wait_ms", "batch_timeout_ms"), lambda v: v >= 0),
    _p("serve_max_queue", int, 128, ("max_queue_depth",), lambda v: v > 0),
    _p("serve_min_bucket", int, 16, ("min_bucket",), lambda v: v > 0),
    _p("serve_max_bucket", int, 1024, ("max_bucket",), lambda v: v > 0),
    _p("serve_max_models", int, 8, (), lambda v: v > 0),
    _p("serve_metrics_file", str, "", ("metrics_file",)),
    _p("serve_slo_ms", float, 0.0, ("slo_ms", "serve_deadline_ms"),
       lambda v: v >= 0,
       desc="per-request SLO budget in milliseconds: the micro-batcher "
            "sheds a request at admission when its projected queue wait "
            "exceeds the remaining budget, and expires requests still "
            "queued past their deadline. 0 (default) disables deadlines"),
    _p("serve_deadline_policy", str, "fallback", ("deadline_policy",),
       lambda v: v in ("fallback", "fail"),
       desc="what a deadline-missed request gets: 'fallback' (default) "
            "answers it via host predict and counts a deadline miss; "
            "'fail' raises DeadlineExceeded to the caller fast"),
    _p("serve_replicas", int, 1, ("num_replicas",), lambda v: v >= 0,
       desc="device replicas per served model, with least-loaded "
            "routing gated on per-replica circuit breakers; 0 means one "
            "replica per local device"),
    _p("serve_breaker_threshold", int, 3, ("breaker_threshold",),
       lambda v: v >= 1,
       desc="consecutive device-dispatch failures that open a "
            "replica's circuit breaker (traffic fails over until the "
            "cooldown's half-open probe closes it again)"),
    _p("serve_breaker_cooldown_ms", float, 250.0, ("breaker_cooldown_ms",),
       lambda v: v >= 0,
       desc="how long an open breaker refuses dispatches before "
            "granting one half-open probe; a clean probe re-closes the "
            "breaker (self-healing)"),
    _p("serve_scheduler", str, "slo", ("batch_scheduler",),
       lambda v: v in ("fifo", "slo"),
       desc="micro-batch scheduling policy: 'slo' (default, continuous "
            "batching) orders the queue by remaining deadline budget "
            "with skip-and-fill packing so small requests interleave "
            "around large ones (a starvation guard bounds reordering); "
            "'fifo' keeps strict arrival order"),
    _p("serve_pack_size", int, 8, ("pack_size",), lambda v: v >= 1,
       desc="max members per fused multi-model ForestPack loaded via "
            "Server.load_pack; more members than this split into "
            "multiple packs. Each pack answers its whole member set "
            "with one device dispatch per coalescing round"),
    # ---- Observability (lightgbm_tpu/observability/,
    #      docs/Observability.md) ----
    _p("observe", bool, False, ("observability",),
       desc="enable the unified observability registry: per-iteration "
            "training telemetry, structured spans, compile accounting "
            "and device-utilization (MFU) accounting. Off by default; "
            "the disabled path costs one branch per site"),
    _p("observe_ring", int, 4096, (), lambda v: v >= 16,
       desc="ring-buffer capacity for buffered spans and per-iteration "
            "telemetry records (oldest evicted; aggregates unaffected)"),
    _p("observe_norms", bool, False, (),
       desc="also record per-iteration gradient/hessian norms and "
            "leaves grown. These force a host sync per iteration — "
            "diagnostic posture, not benchmarking. Implies observe"),
    _p("observe_trace_file", str, "", ("trace_file",),
       desc="write the span trace here after training: .jsonl for "
            "JSON-lines, anything else for Chrome/Perfetto trace_event "
            "JSON (chrome://tracing, ui.perfetto.dev). Implies observe"),
    _p("observe_metrics_port", int, 0, ("metrics_port",), lambda v: v >= 0,
       desc="serve Prometheus text-format metrics on this localhost "
            "port during task=train or task=serve (0 = off; serving "
            "picks an ephemeral port when 0 and observe is on)"),
    _p("profile_spans", str, "", (),
       desc="comma-separated fnmatch globs of span names to bracket "
            "with a jax.profiler device trace (e.g. "
            "'pipeline_block,sharded_grow'). Empty (default) disables "
            "device capture; degrades to a logged no-op where the "
            "profiler is unavailable. Implies observe"),
    _p("profile_dir", str, "", (),
       desc="directory for device-profiler captures (one subdirectory "
            "per capture); defaults to ./jax_profile when profile_spans "
            "is set"),
    _p("profile_max_captures", int, 4, (), lambda v: v >= 1,
       desc="hard budget of device-profiler captures per process — a "
            "long run collects a handful of representative windows "
            "instead of gigabytes"),
    _p("flightrec", bool, True, ("flight_recorder",),
       desc="crash flight recorder: keep a bounded ring of recent "
            "spans, collective brackets, fault hits and guard trips, "
            "flushed as postmortem_<rank>.json on watchdog abort, "
            "injected rank death, non-finite guard trips and unhandled "
            "training exceptions. Always on (even with observe=false); "
            "the ring costs one dict append per recorded event"),
    _p("flightrec_ring", int, 256, (), lambda v: v >= 16,
       desc="flight-recorder ring capacity (recent events retained for "
            "the post-mortem bundle; oldest evicted)"),
    _p("flightrec_dir", str, "", (),
       desc="directory for postmortem_<rank>.json bundles; defaults to "
            "checkpoint_dir when set (shared storage in a multihost "
            "run), else the working directory on fatal flushes only"),
    # ---- Reliability (lightgbm_tpu/reliability/, docs/Reliability.md) ----
    _p("checkpoint_period", int, 0, ("checkpoint_freq", "snapshot_period"),
       lambda v: v >= 0),
    _p("checkpoint_dir", str, "", ("checkpoint_path",)),
    _p("checkpoint_keep", int, 3, ("checkpoint_keep_last",
                                   "keep_last_checkpoints"),
       lambda v: v >= 1),
    _p("guard_nonfinite", str, "off", ("guard_policy", "nonfinite_policy"),
       lambda v: v in ("off", "warn", "skip_iteration", "rollback", "raise")),
    _p("retry_max_attempts", int, 3, ("device_retry_attempts",),
       lambda v: v >= 1),
    _p("retry_backoff_ms", float, 50.0, ("retry_base_backoff_ms",),
       lambda v: v >= 0),
    _p("retry_backoff_max_ms", float, 2000.0, (), lambda v: v >= 0),
    _p("collective_timeout_s", float, 0.0, ("collective_deadline_s",),
       lambda v: v >= 0,
       desc="collective-watchdog deadline: a multihost run whose "
            "host-boundary collective (allgather, sharded growth psum) "
            "blocks longer than this aborts the local process with a "
            "'rank k last seen Ns ago' diagnostic instead of hanging "
            "forever on a dead peer. 0 (default) disables the watchdog; "
            "it is always off on a single machine. The first collective "
            "of each kind gets 4x this deadline to absorb XLA "
            "compilation (docs/Reliability.md)"),
    _p("heartbeat_interval_s", float, 1.0, (), lambda v: v > 0,
       desc="how often each rank stamps its liveness file while the "
            "collective watchdog is armed; a peer is reported stale "
            "after ~3 missed intervals"),
    _p("heartbeat_dir", str, "", (),
       desc="shared directory for the watchdog's per-rank heartbeat "
            "files; defaults to <checkpoint_dir>/heartbeats when a "
            "checkpoint_dir is set, else heartbeat diagnosis is "
            "disabled (deadline aborts still fire, unnamed)"),
    _p("elastic_resize", bool, False, (),
       desc="when the collective watchdog names a dead rank, survivors "
            "vote a mesh shrink through the heartbeat directory, commit "
            "a new membership epoch, and exit for reincarnation at the "
            "smaller world instead of aborting (exit 75, not 113); the "
            "relaunched ranks re-shard rows from the epoch checkpoint "
            "and finish the run (docs/Distributed.md Elasticity). "
            "Default false preserves the abort-on-death behavior "
            "bit-for-bit. Requires heartbeat_dir (or checkpoint_dir) "
            "and a supervisor that relaunches on exit code 75"),
    _p("elastic_min_world", int, 1, (), lambda v: v >= 1,
       desc="smallest world size an elastic shrink may commit; a "
            "failure that would leave fewer survivors falls back to "
            "the watchdog abort so the supervisor can restart the full "
            "fleet instead of limping on too few chips"),
    _p("elastic_epoch_timeout_s", float, 30.0, (), lambda v: v >= 0,
       desc="how long a survivor waits for all peers' shrink proposals "
            "to agree before giving up on the vote and falling back to "
            "the watchdog abort"),
    _p("checkpoint_coordinated", bool, True, (),
       desc="multihost checkpointing runs the coordinated commit "
            "protocol (iteration agreement, per-rank shards, COMMIT "
            "marker — docs/Reliability.md). Disable to fall back to "
            "rank-independent single-host bundles (not resumable "
            "across ranks)"),
    # ---- Convert (config.h:1006-1020) ----
    _p("convert_model_language", str, ""),
    _p("convert_model", str, "gbdt_prediction.cpp",
       ("convert_model_file",)),
    # ---- Objective (config.h:1023-1130) ----
    _p("num_class", int, 1, ("num_classes",), lambda v: v > 0),
    _p("is_unbalance", bool, False, ("unbalance", "unbalanced_sets")),
    _p("scale_pos_weight", float, 1.0, (), lambda v: v > 0.0),
    _p("sigmoid", float, 1.0, (), lambda v: v > 0.0),
    _p("boost_from_average", bool, True),
    _p("reg_sqrt", bool, False),
    _p("alpha", float, 0.9, (), lambda v: v > 0.0),
    _p("fair_c", float, 1.0, (), lambda v: v > 0.0),
    _p("poisson_max_delta_step", float, 0.7, (), lambda v: v > 0.0),
    _p("tweedie_variance_power", float, 1.5, (), lambda v: 1.0 <= v < 2.0),
    _p("lambdarank_truncation_level", int, 30, (), lambda v: v > 0),
    _p("lambdarank_norm", bool, True),
    _p("label_gain", list, None),
    _p("linear_tree", bool, False, ("linear_trees",)),
    # ---- Metric (config.h:1133-1174) ----
    _p("metric", str, "", ("metrics", "metric_types")),
    _p("metric_freq", int, 1, ("output_freq",), lambda v: v > 0),
    _p("is_provide_training_metric", bool, False,
       ("training_metric", "is_training_metric", "train_metric")),
    _p("eval_at", list, None, ("ndcg_eval_at", "ndcg_at", "map_eval_at",
                               "map_at")),
    _p("multi_error_top_k", int, 1, (), lambda v: v > 0),
    _p("auc_mu_weights", list, None),
    # ---- Network (config.h:1177-1210) ----
    _p("num_machines", int, 1, ("num_machine",), lambda v: v > 0),
    _p("local_listen_port", int, 12400, ("local_port", "port"),
       lambda v: v > 0),
    _p("time_out", int, 120, (), lambda v: v > 0),
    _p("machine_list_filename", str, "", ("machine_list_file", "machine_list",
                                          "mlist")),
    _p("machines", str, "", ("workers", "nodes")),
    # ---- TPU-specific (new; no reference analog) ----
    _p("num_devices", int, 0, (),
       desc="devices in the mesh; 0 = use all visible"),
    _p("distributed_hist_agg", str, "auto", (),
       lambda v: v in ("auto", "psum", "reduce_scatter"),
       "histogram merge for the data/voting tree learners: "
       "'reduce_scatter' gives each device a feature shard of the global "
       "histogram (the reference Reduce-Scatter, "
       "data_parallel_tree_learner.cpp:184-233; O(S*F*B/world) memory "
       "per device), 'psum' replicates the full histogram (the seed "
       "Allreduce). 'auto' picks reduce_scatter wherever it is exact "
       "(single-process data/voting without EFB or rescanning monotone "
       "methods) and psum elsewhere; see distributed/crossbar.py"),
    _p("hist_dtype", str, "float32", (),
       lambda v: v in ("float32", "bfloat16"),
       "accumulation dtype for histograms"),
    _p("growth_passes_per_tree", int, 0, (),
       desc="max frontier passes per tree; 0 = auto from num_leaves/max_depth"),
    _p("use_pallas", bool, True, (),
       desc="use Pallas histogram kernel on TPU when applicable"),
    _p("gpu_use_dp", bool, True, ("hist_double_prec",),
       desc="double-bf16 (~f32) histogram sums on the MXU path. false "
            "keeps gradient sums exact but sums hessians in single bf16 "
            "(~1.3x faster, small AUC cost); unlike the reference GPU "
            "backend (f32 when false) bf16 is coarser, so the default "
            "here is true"),
    _p("hist_subtraction", bool, True, (),
       desc="sibling-histogram subtraction on the TPU grower (reference "
            "serial_tree_learner.cpp:311-326): build only the smaller "
            "child's histogram, derive the larger as parent minus smaller "
            "(~half the kernel slots per pass). false rebuilds every "
            "child's histogram from rows"),
    _p("growth_overshoot", float, 2.0, (),
       lambda v: v == 0.0 or v >= 1.0,
       "overgrow-and-prune on the batched TPU grower: grow toward "
       "overshoot*num_leaves leaves with unthrottled passes, then replay "
       "the reference's exact best-first selection over the recorded "
       "gains and prune (serial_tree_learner.cpp:159). Exact leaf-wise "
       "trees when the overshoot covers every best-first pick (~3x is "
       "ample). 0 = off (tail_split_cap hybrid growth instead)"),
    _p("growth_bridge_gate", float, 0.0, (),
       lambda v: 0.0 <= v <= 1.0,
       "overgrow-and-prune early-exit: skip the full-capacity bridge "
       "pass and fixup sweeps when the doubling schedule already grew "
       "at least this fraction of overshoot*num_leaves leaves (0 = "
       "always chase the full overshoot). The bridge is an s_max-wide "
       "histogram sweep (~65 ms at the Higgs bench shape) that runs "
       "exactly for the mid/late-boosting trees whose throttled last "
       "pass under-commits; 0.93 measured +6% throughput for ~2.4e-4 "
       "AUC@115 (docs/PerfNotes.md round 4)"),
    _p("tail_split_cap", int, 8, (), lambda v: v >= 0,
       "hybrid growth throttle for the batched TPU grower: once fewer "
       "leaves remain than splittable candidates, commit at most this "
       "many splits per pass before re-ranking (approaches the "
       "reference's strict best-first order, serial_tree_learner.cpp:159, "
       "as the cap shrinks). 0 = unthrottled batched growth"),
    _p("efb_use_mxu", bool, False, (),
       desc="route EFB-bundled training through the MXU growth path: "
            "bundle-space histogram kernels, the segmented bundle-space "
            "split scan (split_bundled.py), and bundle-range routing. "
            "Parity-tested, but the portable scatter grower measured "
            "FASTER on every bundled shape tried (docs/PerfNotes.md "
            "round 4: bundling is exactly the transformation that makes "
            "scatter updates cheap, while the one-hot-matmul histogram "
            "still pays per padded lane) — so bundled data defaults to "
            "the portable grower"),
    _p("efb_segmented_scan", bool, True, (),
       desc="scan bundled histograms directly per sub-feature segment "
            "([S, Fb, Bb] stays bundle-sized; split_bundled.py). false "
            "reverts to per-pass expansion to original features "
            "(efb.expand_histograms) — slower at wide F, kept as the "
            "parity baseline"),
    _p("bin_pack_4bit", bool, True, ("four_bit_bins",),
       desc="store the device bin matrix two-features-per-byte when "
            "every feature fits 4 bits (max_bin <= 15; the reference's "
            "4-bit DenseBin, src/io/dense_bin.hpp:42). Kernels unpack "
            "nibbles in VMEM — halves bin-matrix HBM with identical "
            "trees. Serial MXU growth path only"),
    _p("use_quantized_grad", bool, False, ("quantized_grad",),
       desc="stochastically-rounded integer gradients/hessians for the "
            "MXU histogram kernels (3 channels instead of 5, ~1.5x "
            "faster); leaf values are refit exactly afterwards, so "
            "quantization only perturbs the split search"),
    _p("hist_backend", str, "auto", (),
       lambda v: v in ("auto", "mxu", "pallas", "scatter"),
       "histogram kernel for the serial MXU growth path: 'mxu' = "
       "one-hot x MXU matmul (histogram_mxu.py), 'pallas' = "
       "slot-grouped scatter-accumulate kernel (histogram_pallas.py; "
       "per-row cost independent of frontier width), 'scatter' = "
       "pure-XLA segment sums (the parity oracle). 'auto' runs a "
       "one-shot on-device autotune of mxu vs pallas and pins the "
       "winner for the run (quantized posture only — there the "
       "backends are bit-identical, so the choice is byte-neutral on "
       "model.txt; exact mode pins mxu). The decision and per-backend "
       "timings land in observability and the bench JSON"),
    _p("hist_autotune", bool, True, (),
       desc="allow hist_backend='auto' to time both kernels on device "
            "before pinning one; false pins mxu without measuring "
            "(deterministic startup, e.g. for profiling runs)"),
    _p("partition_impl", str, "auto", (),
       lambda v: v in ("auto", "argsort", "scan"),
       "row-partitioning algorithm behind the slot-grouped scatter "
       "kernels (histogram_pallas.py partition_rows): 'scan' = stable "
       "rank via blocked prefix sums over the per-slot counts the "
       "router already emits (O(N), one sweep), 'argsort' = the "
       "original O(N log N) sort, retained as the bit-parity oracle. "
       "'auto' = scan. Both produce the identical slot-contiguous "
       "block layout, so the choice is byte-neutral on model.txt"),
    _p("level_pipeline", bool, False, (),
       desc="stage-dispatched tree growth (learner/grower_pipeline.py): "
            "each doubling-schedule pass, the bridge and speculative "
            "fixup chunks run as separate async dispatches so level "
            "k+1's histogram build is enqueued before level k's "
            "bookkeeping is host-visible, and the host regains a "
            "per-level observation point (the level_pipeline trace "
            "span). Byte-identical models to the default monolithic "
            "one-dispatch-per-tree grower, which stays the parity "
            "oracle and remains the right shape for remoted "
            "accelerators where every dispatch pays a tunnel "
            "round-trip. Serial MXU growth only: the sharded grower "
            "and the fused multi-tree scan ignore it"),
    _p("level_pipeline_lookahead", int, 4, (), lambda v: v >= 1,
       "speculative fixup stages enqueued per chunk before the "
       "level-pipelined grower consults the previous chunk's "
       "(already in flight) done flag. Larger values keep the device "
       "busier past the done boundary at the cost of more identity "
       "no-op dispatches on early-finishing trees"),
    _p("fused_block_size", int, 10, (), lambda v: v >= 1,
       "iterations per fused on-device dispatch in engine.train when "
       "the config is fused-eligible (boosting/fused.py). Metrics, "
       "callbacks, and early stopping still run for EVERY iteration — "
       "valid scores come from the block's per-iteration trajectory, "
       "and an early stop mid-block rolls the extra trees back — so "
       "results match per-iteration training exactly; the win is one "
       "host round-trip per block instead of per tree. 1 = dispatch "
       "per iteration (the reference's cadence, gbdt.cpp:371)"),
    _p("pipeline", bool, True, ("pipelined_training",),
       desc="double-buffered training executor (pipeline/executor.py) "
            "when block dispatch is active (fused_block_size > 1 and "
            "the run is fused-eligible): block k+1 is dispatched "
            "asynchronously while the host unpacks block k's trees and "
            "runs its callbacks, syncing only at early-stop decisions. "
            "Bit-identical models to pipeline=false — the non-pipelined "
            "block path stays available as the parity oracle"),
    _p("pipeline_device_eval", bool, True, (),
       desc="compute valid-set metrics in-graph over the block's score "
            "trajectory (pipeline/device_eval.py), so early stopping "
            "reads one [block, n_metrics] array per dispatch instead of "
            "pulling full per-iteration score matrices to the host. "
            "Engages only when every metric on every valid set has a "
            "device kernel (pointwise families + multiclass "
            "logloss/error); ranking-style metrics (auc, ndcg, map) "
            "fall back to host evaluation for the whole run. Device "
            "metric values are f32 while host evaluation is f64, so "
            "logged metric VALUES may differ in the last digits; split "
            "decisions, scores and models are unaffected"),
    _p("pipeline_adaptive_blocks", bool, True, (),
       desc="let the pipelined executor grow the per-dispatch block "
            "size from the measured steady-state training rate "
            "(pipeline/scheduler.py) instead of using fused_block_size "
            "for every block, targeting pipeline_target_block_ms per "
            "dispatch and never crossing an early_stopping_rounds "
            "boundary. Block partitioning cannot change the trained "
            "model (the fused scan is iteration-exact), only dispatch "
            "cadence"),
    _p("pipeline_target_block_ms", float, 250.0, (), lambda v: v > 0,
       "steady-state device time the adaptive scheduler aims to keep "
       "in flight per dispatch. Larger blocks amortize more host "
       "round-trips but coarsen the early-stop sync cadence"),
    _p("pipeline_max_block", int, 200, (), lambda v: v >= 1,
       "upper bound on the adaptive scheduler's block size, whatever "
       "the measured rate suggests"),
    _p("stream_input", bool, False, ("streaming_input",),
       desc="two-pass out-of-core ingestion (docs/Streaming.md): pass 1 "
            "streams chunks from the source into a per-feature reservoir "
            "sketch that freezes the bin boundaries, pass 2 re-streams "
            "and quantizes each chunk into the bin matrix, overlapping "
            "the next chunk's parse with the current chunk's binning. "
            "The raw [N, F] float matrix never materializes: peak host "
            "memory is one chunk + the sketch + the uint8/16 bin matrix. "
            "On the CLI, task=train data=<file.csv|.npy> streams the "
            "file instead of loading it"),
    _p("stream_chunk_rows", int, 65536, ("stream_batch_rows",),
       lambda v: v >= 1,
       "rows per streamed chunk: the unit of parse/bin overlap and the "
       "peak raw-row materialization during ingestion"),
    _p("stream_sample_rows", int, 200000, ("stream_sketch_rows",),
       lambda v: v >= 1,
       "capacity of the pass-1 reservoir sketch (rows). When it covers "
       "the whole stream the sketch holds every row in order and the "
       "frozen boundaries are bit-identical to in-memory binning; below "
       "that, boundaries come from a uniform row sample "
       "(docs/Streaming.md error envelope)"),
    _p("stream_bin_parity", bool, False, (),
       desc="require exact-parity streamed binning: fail ingestion if "
            "the reservoir sample did not cover every row (i.e. "
            "stream_sample_rows < N), instead of silently accepting "
            "sample-based boundaries"),
    # ---- Continuous train->refresh->serve loop (docs/Continuous.md) ----
    _p("loop_dir", str, "", ("loop_state_dir",),
       desc="state root of task=loop (continuous/trainer.py): the "
            "GENERATION marker, the gens/ bundle history, the work/ "
            "per-cycle scratch (stream state + mid-train checkpoints) "
            "and the postmortems/ flight-recorder bundles all live "
            "under it. Required for task=loop — the loop's whole "
            "crash-survivability story is this directory"),
    _p("loop_rounds", int, 10, ("loop_num_iterations",), lambda v: v >= 1,
       "boosting iterations added per refresh cycle (the per-window "
       "continuation budget, NOT a total)"),
    _p("loop_window_chunks", int, 1, (), lambda v: v >= 1,
       "stream chunks consumed per refresh window: each cycle trains on "
       "WindowSource(base, cursor, loop_window_chunks) and advances the "
       "cursor by that many chunks on publish"),
    _p("loop_windows", int, 0, (), lambda v: v >= 0,
       "maximum refresh cycles before the loop exits (0 = run until the "
       "source is exhausted)"),
    _p("loop_keep", int, 3, (), lambda v: v >= 1,
       "generation bundles retained under <loop_dir>/gens; the bundle "
       "the live generation was published from is pinned and survives "
       "this quota (reliability/checkpoint.py pin_bundle)"),
    _p("loop_poison_retries", int, 3, (), lambda v: v >= 1,
       "crash-loop budget per window: a window whose cycle fails this "
       "many consecutive attempts is quarantined — skipped, logged, "
       "counted in lightgbm_tpu_freshness_quarantined_windows — instead "
       "of wedging the loop forever"),
    _p("loop_backoff_ms", float, 50.0, (), lambda v: v >= 0,
       "base of the capped exponential backoff between failed cycle "
       "attempts (reliability/backoff.py); 0 disables the sleep"),
    _p("loop_backoff_max_ms", float, 2000.0, (), lambda v: v >= 0,
       "cap of the inter-attempt backoff"),
    _p("loop_freshness_slo_s", float, 0.0, (), lambda v: v >= 0,
       "staleness budget for the freshness watchdog: when the "
       "data-to-serving latency of a publish exceeds it, the "
       "lightgbm_tpu_freshness_slo_alarm gauge latches 1 (0 disables "
       "the alarm; the latency metric itself is always recorded)"),
    _p("loop_model_name", str, "live", (),
       desc="registry name the loop publishes refreshed generations "
            "under (Server.load_model first, Server.hot_swap after)"),
]

_SPEC_BY_NAME: Dict[str, ParamSpec] = {p.name: p for p in _PARAMS}

# alias -> canonical name (reference: src/io/config_auto.cpp:10 alias_table)
PARAM_ALIASES: Dict[str, str] = {}
for _spec in _PARAMS:
    for _a in _spec.aliases:
        PARAM_ALIASES[_a] = _spec.name


def _coerce(spec: ParamSpec, value: Any) -> Any:
    """Coerce a raw (possibly string) value to the spec's type."""
    if value is None:
        return None
    if spec.type is bool:
        if isinstance(value, str):
            return value.lower() in ("true", "1", "yes", "+", "t", "on")
        return bool(value)
    if spec.type is int:
        return int(float(value)) if isinstance(value, str) else int(value)
    if spec.type is float:
        return float(value)
    if spec.type is list:
        if isinstance(value, str):
            if not value:
                return None
            parts = [v for v in value.replace(";", ",").split(",") if v != ""]
            out = []
            for x in parts:
                try:
                    out.append(int(x))
                except ValueError:
                    try:
                        out.append(float(x))
                    except ValueError:
                        out.append(x)
            return out
        if isinstance(value, (list, tuple)):
            return list(value)
        return [value]
    if spec.type is str:
        return str(value)
    return value


class Config:
    """Resolved parameter set. Attribute access for every registered param."""

    def __init__(self, params: Optional[Dict[str, Any]] = None):
        for spec in _PARAMS:
            setattr(self, spec.name, spec.default)
        self.raw_params: Dict[str, Any] = {}
        if params:
            self.update(params)

    def update(self, params: Dict[str, Any]) -> "Config":
        canon: Dict[str, Any] = {}
        for key, value in params.items():
            name = PARAM_ALIASES.get(key, key)
            if name in canon and canon[name] != value:
                # first occurrence wins among aliases, like reference
                # Config::SetMembersFromMap keeping canonical precedence
                continue
            canon[name] = value
        for name, value in canon.items():
            spec = _SPEC_BY_NAME.get(name)
            if spec is None:
                # unknown params are kept (custom objective extras etc.)
                self.raw_params[name] = value
                continue
            coerced = _coerce(spec, value)
            if spec.check is not None and coerced is not None \
                    and not spec.check(coerced):
                raise ValueError(
                    f"Invalid value {value!r} for parameter {name!r}")
            setattr(self, name, coerced)
            self.raw_params[name] = value
        self._resolve_conflicts()
        return self

    # reference: src/io/config.cpp:261 CheckParamConflict
    def _resolve_conflicts(self) -> None:
        if self.is_parallel and self.bagging_freq > 0 and \
                self.bagging_fraction < 1.0 and self.tree_learner == "feature":
            # feature-parallel shares all rows; bagging must be synchronized
            pass
        if self.boosting == "rf":
            if self.bagging_freq <= 0 or self.bagging_fraction >= 1.0:
                self.bagging_freq = max(self.bagging_freq, 1)
                self.bagging_fraction = min(self.bagging_fraction, 0.9)
        if self.boosting == "goss":
            # GOSS replaces bagging
            self.bagging_freq = 0
            self.bagging_fraction = 1.0
        if self.max_depth > 0:
            # cap num_leaves by full tree at max_depth
            full = 1 << min(self.max_depth, 30)
            if self.num_leaves > full:
                self.num_leaves = full
        if self.checkpoint_period > 0 and not self.checkpoint_dir:
            from .utils.log import Log
            Log.warning(
                "checkpoint_period > 0 needs checkpoint_dir; "
                "checkpointing disabled")
            self.checkpoint_period = 0
        if self.collective_timeout_s > 0 and self.num_machines <= 1:
            # not an error: the same config file may serve both the
            # launcher and a local smoke run — but say clearly that the
            # watchdog only arms with real peers
            from .utils.log import Log
            Log.warning(
                "collective_timeout_s is set but num_machines <= 1; "
                "the collective watchdog only arms on multihost runs")
        if (self.observe_trace_file or self.observe_norms or
                self.observe_metrics_port > 0 or
                self.profile_spans) and not self.observe:
            # asking for an observability output implies observing
            self.observe = True
        if self.serve_max_bucket < self.serve_min_bucket:
            from .utils.log import Log
            Log.warning(
                "serve_max_bucket < serve_min_bucket; raising "
                "serve_max_bucket to %d", self.serve_min_bucket)
            self.serve_max_bucket = self.serve_min_bucket
        if self.num_machines > 1 and self.tree_learner == "serial":
            # reference config.cpp:293-299: serial learner forces
            # single-machine (theirs is silent; warn so nobody believes
            # N independent per-partition models are one model)
            from .utils.log import Log
            Log.warning(
                "num_machines > 1 requires a parallel tree_learner "
                "(data/feature/voting); forcing num_machines=1")
            self.num_machines = 1
        requested_mc_method = self.monotone_constraints_method
        if self.monotone_constraints is not None and \
                requested_mc_method in ("intermediate", "advanced"):
            # the reference downgrades these for ALL distributed modes
            # (config.cpp:381-384: local nodes lack full histograms);
            # here data/feature-parallel scans see globally merged
            # histograms, so only voting (partial aggregation) cannot
            # support the rescan
            if self.tree_learner == "voting":
                from .utils.log import Log
                Log.warning(
                    "Cannot use %r monotone constraints with the voting "
                    "tree learner, auto set to \"basic\" method.",
                    requested_mc_method)
                self.monotone_constraints_method = "basic"
            if self.feature_fraction_bynode != 1.0 and \
                    self.monotone_constraints_method != "basic":
                # reference config.cpp:386-390: by-node sampling would
                # resample on every recompute-triggered re-find
                from .utils.log import Log
                Log.warning(
                    "Cannot use %r monotone constraints with "
                    "feature_fraction_bynode != 1, auto set to \"basic\" "
                    "method.", requested_mc_method)
                self.monotone_constraints_method = "basic"
        if self.linear_tree and self.boosting == "goss":
            raise ValueError("linear_tree is not supported with goss boosting")
        if self.linear_tree:
            # reference conflicts (config.cpp:357-371): serial learner only,
            # no zero_as_missing, no L1 regression
            if self.tree_learner != "serial":
                from .utils.log import Log
                Log.warning("Linear tree learner must be serial; "
                            "tree_learner=%s ignored", self.tree_learner)
                self.tree_learner = "serial"
            if self.zero_as_missing:
                raise ValueError("zero_as_missing must be false when "
                                 "fitting linear trees")
            if self.objective in ("regression_l1", "l1", "mae",
                                  "mean_absolute_error"):
                raise ValueError("Cannot use regression_l1 objective when "
                                 "fitting linear trees")

    @property
    def is_parallel(self) -> bool:
        return self.tree_learner != "serial" or self.num_machines > 1

    @property
    def is_data_based_parallel(self) -> bool:
        return self.tree_learner in ("data", "voting")

    @property
    def max_nodes(self) -> int:
        return 2 * self.num_leaves - 1

    def metric_list(self) -> List[str]:
        if not self.metric:
            return []
        if isinstance(self.metric, (list, tuple)):
            return list(self.metric)
        return [m for m in str(self.metric).replace(";", ",").split(",") if m]

    def to_dict(self) -> Dict[str, Any]:
        return {p.name: getattr(self, p.name) for p in _PARAMS}

    def __repr__(self) -> str:
        mods = {k: v for k, v in self.to_dict().items()
                if v != _SPEC_BY_NAME[k].default}
        return f"Config({mods})"


def param_dict_to_config(params: Optional[Dict[str, Any]]) -> Config:
    return Config(params or {})


def parse_config_file(path: str) -> Dict[str, str]:
    """Parse `key=value` lines; '#' starts a comment.

    Reference: Application ctor config-file parsing (application.cpp:50-83).
    """
    out: Dict[str, str] = {}
    with open(path, "r") as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            key, value = line.split("=", 1)
            out[key.strip()] = value.strip()
    return out
