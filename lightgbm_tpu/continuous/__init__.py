"""Continuous train -> refresh -> serve loop (docs/Continuous.md).

`ContinuousTrainer` drives the full lifecycle under one durable state
machine: pull the next window of fresh rows through the streaming
spine, continue boosting from the live model, cut a generation
checkpoint, and atomically publish it into the serving registry under
live traffic. Every seam is a named fault site and every kill is
survivable — mid-ingest resumes from stream state, mid-train resumes
from the last checkpoint bundle, mid-publish leaves the old generation
serving while the torn half-built one is detected via the GENERATION
marker and discarded. Windows that crash-loop past the retry budget
are quarantined instead of wedging the loop, and data-to-serving
latency is exported as the ``lightgbm_tpu_freshness`` metric family
with an SLO alarm.
"""

from .trainer import ContinuousTrainer, CYCLE_TAG, MARKER

__all__ = ["ContinuousTrainer", "CYCLE_TAG", "MARKER"]
