"""The continuous train -> refresh -> serve driver.

State layout under ``loop_dir``::

    GENERATION               # atomic json marker: the COMMIT point of
                             # a cycle (generation, bundle, next_chunk,
                             # quarantined windows)
    gens/ckpt_%07d/          # one checkpoint bundle per PUBLISHED
                             # generation (bundle key = generation
                             # number, not tree count)
    work/CYCLE               # generation number being built
    work/ckpt/               # stream-state side files + mid-train
                             # checkpoint bundles for the cycle
    postmortems/attempt_*/   # flight-recorder flush per failed cycle

One cycle (``_run_cycle_once``)::

    ingest window -> refresh train -> cut gens bundle -> publish
        |                 |                 |               |
    streaming_ingest  histogram_build  checkpoint_io   serving_hot_swap
                                                       serving_hot_swap_commit
                                                       loop_publish

The GENERATION marker is the cycle's single commit point: everything
before it is redone deterministically from durable state on recovery
(identical bytes — stream-state resume, checkpoint resume, idempotent
re-save and re-swap), and a complete gens bundle NEWER than the marker
is by definition a torn publish, discarded by ``_recover`` before it
can ever be served. The marker is only advanced AFTER the serving swap
succeeds, so the registry is never behind the marker.

``run`` wraps each cycle in a capped-exponential crash-loop budget
(reliability/backoff.py): a window that keeps failing after
``loop_poison_retries`` full recover/rebuild attempts is quarantined —
skipped, logged, counted in the freshness metric family — instead of
wedging the loop forever.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Dict, List, Optional

from ..basic import Booster, Dataset
from ..observability import registry as _obs
from ..observability.flightrec import recorder
from ..reliability import counters, faults, pin_bundle
from ..reliability.backoff import BackoffPolicy
from ..reliability.checkpoint import (latest_checkpoint, load_checkpoint,
                                      save_checkpoint, _bundle_iter,
                                      _is_complete, _listdir)
from ..streaming import ChunkSource, WindowSource
from ..utils.log import Log, LightGBMError
from ..utils.timer import global_timer

__all__ = ["ContinuousTrainer", "MARKER", "CYCLE_TAG"]

#: the loop's commit point: a json file naming the live generation,
#: the gens bundle it was published from, and the stream cursor
MARKER = "GENERATION"
#: names the generation the work dir is building; a tag that does not
#: match marker.generation + 1 marks the work dir as stale
CYCLE_TAG = "CYCLE"
_MARKER_VERSION = 1


class ContinuousTrainer:
    """Drives train -> refresh -> serve cycles over a `ChunkSource`.

    `source` is the stream of fresh rows (windowed per cycle by
    `loop_window_chunks`), `server` the live `serving.Server` the
    generations are published into. `publish_transform`, when given,
    rewrites the model text once per generation before it is saved and
    served (it must be idempotent: a recovered cycle re-applies it to
    a model whose base trees were already transformed). `sleep` is the
    backoff clock, injectable so chaos tests do not wait wall-time.
    """

    def __init__(self, config, source: ChunkSource, server,
                 params: Optional[Dict] = None,
                 publish_transform=None, sleep=time.sleep):
        if not config.loop_dir:
            raise LightGBMError(
                "ContinuousTrainer needs loop_dir: the loop's durable "
                "state (generation marker, bundles, stream cursor) "
                "lives there")
        self.config = config
        self.source = source
        self.server = server
        self.params = dict(params or {})
        self.publish_transform = publish_transform
        self.backoff = BackoffPolicy(config.loop_backoff_ms,
                                     config.loop_backoff_max_ms,
                                     sleep=sleep)
        self.loop_dir = config.loop_dir
        self.gens_dir = os.path.join(self.loop_dir, "gens")
        self.work_dir = os.path.join(self.loop_dir, "work")
        self.work_ckpt = os.path.join(self.work_dir, "ckpt")
        self.post_dir = os.path.join(self.loop_dir, "postmortems")
        for d in (self.gens_dir, self.work_ckpt, self.post_dir):
            os.makedirs(d, exist_ok=True)
        self.marker_path = os.path.join(self.loop_dir, MARKER)
        # live state, (re)filled by _recover from the durable marker
        self.generation = 0
        self.next_chunk = 0
        self.quarantined: List[int] = []
        self._live_model_str: Optional[str] = None
        self._fault_count = 0

    # ------------------------------------------------------------------
    # durable marker + work-cycle tag
    def _read_marker(self) -> Optional[Dict]:
        try:
            with open(self.marker_path) as f:
                marker = json.load(f)
        except (OSError, ValueError):
            return None
        if marker.get("format_version") != _MARKER_VERSION:
            Log.warning("continuous: ignoring generation marker with "
                        f"format_version="
                        f"{marker.get('format_version')!r}")
            return None
        return marker

    def _write_marker(self, generation: int, bundle: Optional[str],
                      next_chunk: int, quarantined: List[int]) -> None:
        payload = {"format_version": _MARKER_VERSION,
                   "generation": int(generation),
                   "bundle": bundle,
                   "next_chunk": int(next_chunk),
                   "quarantined": [int(q) for q in quarantined]}
        tmp = self.marker_path + f".tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True)
        os.replace(tmp, self.marker_path)

    def _cycle_tag(self) -> Optional[int]:
        try:
            with open(os.path.join(self.work_dir, CYCLE_TAG)) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _write_cycle_tag(self, generation: int) -> None:
        path = os.path.join(self.work_dir, CYCLE_TAG)
        tmp = path + f".tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(f"{int(generation)}\n")
        os.replace(tmp, path)

    def _wipe_work(self) -> None:
        shutil.rmtree(self.work_dir, ignore_errors=True)
        os.makedirs(self.work_ckpt, exist_ok=True)

    # ------------------------------------------------------------------
    # recovery: runs at the top of EVERY cycle, so the in-process retry
    # path exercises exactly the code a freshly restarted process runs
    def _recover(self) -> None:
        marker = self._read_marker()
        if marker is None:
            self.generation = 0
            self.next_chunk = 0
            self.quarantined = []
            bundle_name = None
        else:
            self.generation = int(marker["generation"])
            self.next_chunk = int(marker["next_chunk"])
            self.quarantined = [int(q) for q in
                                marker.get("quarantined", [])]
            bundle_name = marker.get("bundle")
        # torn-publish sweep: a COMPLETE gens bundle newer than the
        # marker was cut by a cycle that died before its commit point —
        # it was never published durably, so it is discarded here and
        # rebuilt deterministically (identical bytes) by the next cycle
        for name in _listdir(self.gens_dir):
            it = _bundle_iter(name)
            if it is None or it <= self.generation:
                continue
            shutil.rmtree(os.path.join(self.gens_dir, name),
                          ignore_errors=True)
            counters.inc("loop_torn_publishes")
            _obs.record_freshness_torn_publish(it)
            Log.warning(
                "continuous: discarded torn generation bundle %s "
                "(newer than committed generation %d)", name,
                self.generation)
        # re-assert the pin: a kill between marker write and pin write
        # must not let keep_last pruning age out the live generation
        pin_bundle(self.gens_dir, bundle_name)
        # seed the freshness gauge with the recovered live generation —
        # a restarted process that never publishes (exhausted stream)
        # must still report the generation it serves, not 0
        if self.generation:
            _obs.record_freshness_recover(self.generation)
        # a work dir building anything but the next generation is
        # stale (left by a quarantined or already-published cycle)
        if self._cycle_tag() != self.generation + 1:
            self._wipe_work()
        self._live_model_str = None
        if bundle_name is not None:
            bundle = os.path.join(self.gens_dir, bundle_name)
            if not _is_complete(bundle):
                raise LightGBMError(
                    f"continuous: generation marker names bundle "
                    f"{bundle_name!r} but no complete bundle is there "
                    f"— loop_keep pruning and the pin file disagree?")
            self._live_model_str = load_checkpoint(bundle).model_str
            # restart semantics: (re)load the live generation into the
            # serving registry only when it is not already there — an
            # in-process retry must not churn the served entry
            name = self.config.loop_model_name
            if name not in self.server.registry:
                self.server.load_model(name,
                                       model_str=self._live_model_str)
                Log.info("continuous: restored generation %d into "
                         "serving entry %r", self.generation, name)

    # ------------------------------------------------------------------
    # one cycle: ingest -> refresh -> generation cut -> publish
    def _cycle_params(self) -> Dict:
        p = dict(self.params)
        # the same dict serves Dataset params (stream-state side files)
        # and train params (auto checkpoint callback): both kinds of
        # mid-cycle durability land under work/ckpt
        p["checkpoint_dir"] = self.work_ckpt
        if int(p.get("checkpoint_period", 0) or 0) <= 0:
            p["checkpoint_period"] = 1
        return p

    def _run_cycle_once(self) -> None:
        cfg = self.config
        gen = self.generation + 1
        self._write_cycle_tag(gen)
        t0 = time.perf_counter()
        params = self._cycle_params()
        window = WindowSource(self.source, self.next_chunk,
                              cfg.loop_window_chunks)
        ds = Dataset(window, params=params, free_raw_data=False)
        with global_timer.timeit("loop_ingest"):
            ds.construct()
        from ..engine import train
        found = latest_checkpoint(self.work_ckpt)
        if found is not None:
            # kill-mid-train recovery: resume the exact f32/RNG/bagging
            # state from the cycle's last committed bundle — the
            # finished refresh is byte-identical to an unkilled one
            booster = train(params, ds,
                            num_boost_round=cfg.loop_rounds,
                            resume_from=found)
        elif self._live_model_str is not None:
            booster = train(params, ds,
                            num_boost_round=cfg.loop_rounds,
                            init_model=Booster(
                                model_str=self._live_model_str))
        else:
            booster = train(params, ds,
                            num_boost_round=cfg.loop_rounds)
        model_str = booster.model_to_string()
        if self.publish_transform is not None:
            model_str = self.publish_transform(model_str)
        # generation cut: bundle key is the GENERATION number (not the
        # cumulative tree count — quarantined windows add no trees, and
        # the keyspace must still advance). checkpoint_io injects
        # inside save_checkpoint, making this the kill-at-cut site;
        # keep_last pruning runs here too, with the pinned live bundle
        # exempt.
        bundle = save_checkpoint(
            self.gens_dir, gen, model_str,
            state={"generation": gen,
                   "next_chunk": self.next_chunk + cfg.loop_window_chunks,
                   "cum_iteration": booster.current_iteration(),
                   "quarantined": [int(q) for q in self.quarantined]},
            arrays={}, keep_last=cfg.loop_keep)
        self._publish(gen, model_str, bundle, t0)
        self._wipe_work()
        self.generation = gen
        self.next_chunk += cfg.loop_window_chunks
        self._live_model_str = model_str

    def _publish(self, gen: int, model_str: str, bundle: str,
                 t0: float) -> None:
        """Swap the new generation into the serving registry, then
        commit it: marker advance -> pin. A kill anywhere in this
        sequence is survivable — before the marker write the bundle is
        torn (discarded + rebuilt identically by recovery), after it
        the recovery path re-pins and re-loads idempotently."""
        cfg = self.config
        name = cfg.loop_model_name
        if name in self.server.registry:
            self.server.hot_swap(name, model_str=model_str)
        else:
            self.server.load_model(name, model_str=model_str)
        # registered fault site: the new generation is serving but the
        # marker still names the old one — the torn-publish window
        faults.inject("loop_publish")
        self._write_marker(gen, os.path.basename(bundle),
                           self.next_chunk + cfg.loop_window_chunks,
                           self.quarantined)
        pin_bundle(self.gens_dir, bundle)
        _obs.record_freshness_publish(gen, time.perf_counter() - t0,
                                      cfg.loop_freshness_slo_s)
        counters.inc("loop_publishes")
        Log.info("continuous: published generation %d (window chunks "
                 "[%d:%d)) into serving entry %r", gen, self.next_chunk,
                 self.next_chunk + cfg.loop_window_chunks, name)

    # ------------------------------------------------------------------
    # poison-window quarantine
    def _quarantine(self) -> None:
        widx = self.next_chunk
        self.quarantined.append(widx)
        self._wipe_work()
        self.next_chunk += self.config.loop_window_chunks
        # same generation, same bundle: a quarantine advances only the
        # cursor — the live model is untouched
        marker = self._read_marker()
        bundle_name = marker.get("bundle") if marker else None
        self._write_marker(self.generation, bundle_name,
                           self.next_chunk, self.quarantined)
        counters.inc("loop_quarantined_windows")
        _obs.record_freshness_quarantine(widx)
        Log.warning(
            "continuous: quarantined poison window at chunk %d after "
            "%d failed attempts; loop continues at chunk %d", widx,
            self.config.loop_poison_retries, self.next_chunk)

    # ------------------------------------------------------------------
    def _window_empty(self) -> bool:
        """True when the next window holds no rows — the loop's clean
        exhaustion probe. Sized sources answer from metadata; unsized
        ones pay one restartable probe pass for the first chunk."""
        window = WindowSource(self.source, self.next_chunk,
                              self.config.loop_window_chunks)
        if window.num_rows is not None:
            return window.num_rows == 0
        it = window.chunks()
        try:
            return next(it, None) is None
        finally:
            it.close()

    def run(self, max_windows: Optional[int] = None) -> int:
        """Process windows until the source is exhausted or the window
        budget (`max_windows`, default `loop_windows`; 0 = unlimited)
        is spent. Returns the number of generations published. Both
        published and quarantined windows count against the budget."""
        cfg = self.config
        limit = max_windows if max_windows is not None \
            else (cfg.loop_windows or None)
        published = 0
        processed = 0
        attempts = 0
        while limit is None or processed < limit:
            self._recover()
            if self._window_empty():
                break
            try:
                self._run_cycle_once()
            except Exception as exc:  # noqa: BLE001 - crash-loop budget
                attempts += 1
                self._fault_count += 1
                recorder.record_exception("continuous_loop", exc)
                out_dir = os.path.join(
                    self.post_dir, f"attempt_{self._fault_count:04d}")
                os.makedirs(out_dir, exist_ok=True)
                recorder.flush("loop_fault", out_dir=out_dir,
                               extra={"generation": self.generation + 1,
                                      "window_chunk": self.next_chunk,
                                      "attempt": attempts})
                counters.inc("loop_cycle_failures")
                Log.warning(
                    "continuous: cycle for generation %d failed "
                    "(attempt %d/%d): %s: %s", self.generation + 1,
                    attempts, cfg.loop_poison_retries,
                    type(exc).__name__, exc)
                if attempts >= cfg.loop_poison_retries:
                    self._quarantine()
                    processed += 1
                    attempts = 0
                else:
                    self.backoff.wait(attempts - 1)
                continue
            published += 1
            processed += 1
            attempts = 0
        return published
