"""Dask-style distributed estimators (reference python-package/lightgbm/
dask.py:393+ DaskLGBMClassifier/Regressor/Ranker).

The reference's Dask integration exists to stitch a TCP socket mesh between
workers and run the data-parallel socket learner on each partition
(dask.py:68-135 port probing, :167-184 machines-param injection). On TPU
that whole transport layer is replaced by XLA collectives over ICI/DCN: a
single process drives all local chips through `jax.sharding`
(tree_learner=data, parallel/learner.py), and multi-host scaling uses
`jax.distributed.initialize` + the same sharded learner instead of a Dask
scheduler.

These wrappers keep the reference's API shape for drop-in compatibility:
- with dask installed, Dask collections are concatenated to the driver and
  trained on the sharded-TPU learner (the mesh replaces worker fan-out);
- without dask, constructing an estimator raises the same ImportError the
  reference raises when dask is missing (dask.py:24-30).

Cite: reference dask.py:393 (_train), :811 (_predict_part), :1060+
(estimator classes).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from .sklearn import LGBMClassifier, LGBMRanker, LGBMRegressor

__all__ = ["DaskLGBMClassifier", "DaskLGBMRegressor", "DaskLGBMRanker"]

try:  # pragma: no cover - environment dependent
    import dask.array  # noqa: F401
    import dask.dataframe  # noqa: F401
    _DASK_AVAILABLE = True
except ImportError:
    _DASK_AVAILABLE = False


def _concat_to_local(part):
    """Materialize a Dask collection on the driver.

    The reference trains per-worker on local partitions and relies on its
    socket collectives for the merge; the TPU learner shards rows over the
    device mesh instead, so data is gathered once and device-sharded
    (parallel/learner.py 'data' mode)."""
    import dask.array as da
    import dask.dataframe as dd
    if isinstance(part, da.Array):
        return part.compute()
    if isinstance(part, (dd.DataFrame, dd.Series)):
        return part.compute().to_numpy()
    return np.asarray(part)


class _DaskBase:
    _local_cls: Any = None

    def __init__(self, client: Optional[Any] = None, **kwargs):
        if not _DASK_AVAILABLE:
            raise ImportError(
                "dask is required for DaskLGBM estimators; install dask "
                "and distributed, or use the plain sklearn estimators — "
                "on TPU the device mesh already provides distributed "
                "training (tree_learner=data)")
        self._client = client
        params = dict(kwargs)
        # the TPU mesh replaces the reference's per-worker socket learner
        params.setdefault("tree_learner", "data")
        self._local = self._local_cls(**params)

    # -- fit/predict keep the reference signatures (dask.py:1060+) -----
    def fit(self, X, y, sample_weight=None, group=None, **kwargs):
        Xl = _concat_to_local(X)
        yl = _concat_to_local(y)
        sw = None if sample_weight is None else _concat_to_local(
            sample_weight)
        fit_kwargs = dict(kwargs)
        if group is not None:
            fit_kwargs["group"] = _concat_to_local(group)
        self._local.fit(Xl, yl, sample_weight=sw, **fit_kwargs)
        return self

    def _predict_impl(self, X, method, **kwargs):
        # partitions are scored on the driver against the local model (the
        # reference's per-worker _predict_part, dask.py:811, exists to
        # avoid shipping data — here the device mesh is already local).
        # Dask collections stay dask collections so .compute() keeps
        # working for callers written against the reference contract.
        import dask.array as da
        import dask.dataframe as dd
        is_dask = isinstance(X, (da.Array, dd.DataFrame, dd.Series))
        out = np.asarray(method(_concat_to_local(X), **kwargs))
        return da.from_array(out, chunks=out.shape) if is_dask else out

    def predict(self, X, **kwargs):
        return self._predict_impl(X, self._local.predict, **kwargs)

    def predict_proba(self, X, **kwargs):
        return self._predict_impl(X, self._local.predict_proba, **kwargs)

    def __getattr__(self, name):
        # delegate attributes (booster_, feature_importances_, ...) to the
        # wrapped local estimator
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._local, name)

    def to_local(self):
        """Return the underlying single-process estimator (reference
        DaskLGBMModel.to_local, dask.py:900+)."""
        return self._local


class DaskLGBMClassifier(_DaskBase):
    """Distributed classifier (reference dask.py:1060)."""
    _local_cls = LGBMClassifier


class DaskLGBMRegressor(_DaskBase):
    """Distributed regressor (reference dask.py:1230)."""
    _local_cls = LGBMRegressor


class DaskLGBMRanker(_DaskBase):
    """Distributed ranker (reference dask.py:1380)."""
    _local_cls = LGBMRanker
