"""Dask distributed estimators (reference python-package/lightgbm/
dask.py: DaskLGBMClassifier/Regressor/Ranker, 1572 LoC).

The reference's integration stitches a TCP socket mesh between Dask
workers and runs the data-parallel socket learner on each worker's
partitions (dask.py:68-135 port probing, :167-184 machines injection,
:393 _train, :811 _predict_part). Here the same orchestration drives the
TPU stack: each worker joins a `jax.distributed` rendezvous
(parallel/mesh.py setup_multihost — the Network::Init analog) and trains
on its own partitions with tree_learner=data, histograms psum'd across
all workers' devices; rank 0 returns the model, every rank holds an
identical replica.

Caveats vs the reference, stated honestly:
- a worker process can join a rendezvous only while its JAX backend is
  uninitialized (jax.distributed contract), so multi-worker fit needs
  fresh worker processes (e.g. `client.restart()` first); the reference
  has no such constraint because its sockets are its own.
- with no client, or a single worker, fit falls back to concatenating
  partitions on the driver and training on the local device mesh
  (which on TPU already provides data-parallel scaling).
"""

from __future__ import annotations

import socket
from typing import Any, Dict, List, Optional

import numpy as np

from .sklearn import LGBMClassifier, LGBMRanker, LGBMRegressor

__all__ = ["DaskLGBMClassifier", "DaskLGBMRegressor", "DaskLGBMRanker"]

try:  # pragma: no cover - environment dependent
    import dask.array  # noqa: F401
    import dask.dataframe  # noqa: F401
    _DASK_AVAILABLE = True
except ImportError:
    _DASK_AVAILABLE = False


def _concat_to_local(part):
    """Materialize a Dask collection (or pass numpy through)."""
    import dask.array as da
    import dask.dataframe as dd
    if isinstance(part, da.Array):
        return part.compute()
    if isinstance(part, (dd.DataFrame, dd.Series)):
        return part.compute().to_numpy()
    return np.asarray(part)


def _find_open_port() -> int:
    """Probe a free port on this worker (reference
    _find_random_open_port, dask.py:68)."""
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _concat_parts(parts):
    arrs = [np.asarray(p) for p in parts]
    return np.concatenate(arrs, axis=0) if len(arrs) > 1 else arrs[0]


def _train_part(model_factory, params: Dict[str, Any], rank: int,
                machines: str, num_machines: int, listen_port: int,
                parts: List, has_weight: bool, has_group: bool,
                fit_kwargs: Dict[str, Any], classes=None):
    """Per-worker training body (reference _train_part, dask.py:167-184):
    join the rendezvous, fit on the local partitions with the machines
    params injected, return the model text from rank 0. `parts` arrives
    as materialized (X, y[, w][, g]) tuples — dask dereferences the
    futures placed in the submit args on the worker."""
    import os

    os.environ["LIGHTGBM_TPU_MACHINE_RANK"] = str(rank)
    from .parallel import setup_multihost
    setup_multihost(num_machines, machines,
                    local_listen_port=listen_port)
    if params.get("tree_learner") not in ("data", "voting"):
        params = dict(params, tree_learner="data")
    params = dict(params,
                  num_machines=num_machines,
                  machines=machines,
                  local_listen_port=listen_port)
    est = model_factory(**params)
    if classes is not None:
        est._classes_override = classes  # global label encoding
    X = _concat_parts([p[0] for p in parts])
    y = _concat_parts([p[1] for p in parts])
    kw = dict(fit_kwargs)
    i = 2
    if has_weight:
        kw["sample_weight"] = _concat_parts([p[i] for p in parts])
        i += 1
    if has_group:
        kw["group"] = _concat_parts([p[i] for p in parts])
    est.fit(X, y, **kw)
    return est.booster_.model_to_string() if rank == 0 else None


def _delayed_parts(coll):
    """Aligned per-partition delayed objects of a dask collection
    (reference _split_to_parts, dask.py:55-66)."""
    import dask.array as da
    d = coll.to_delayed()
    if isinstance(coll, da.Array):
        return list(np.asarray(d).ravel())
    return list(d)


def _parts_by_worker(client, collections):
    """Future per aligned partition tuple, grouped by the worker holding
    it (reference who_has grouping, dask.py:88-135)."""
    import dask
    from distributed import wait
    part_lists = [_delayed_parts(c) for c in collections]
    n = len(part_lists[0])
    if any(len(pl) != n for pl in part_lists):
        raise ValueError(
            "X, y (and sample_weight/group) must have aligned dask "
            "partitions")
    tuples = [dask.delayed(tuple)(list(tup)) for tup in zip(*part_lists)]
    futures = client.compute(tuples)
    wait(futures)
    who = client.who_has(futures)
    out: Dict[str, List] = {}
    for fut in futures:
        w = sorted(who[fut.key])[0]
        out.setdefault(w, []).append(fut)
    return out


class _DaskBase:
    _local_cls: Any = None

    def __init__(self, client: Optional[Any] = None, **kwargs):
        if not _DASK_AVAILABLE:
            raise ImportError(
                "dask is required for DaskLGBM estimators; install dask "
                "and distributed, or use the plain sklearn estimators — "
                "on TPU the device mesh already provides distributed "
                "training (tree_learner=data)")
        self._client = client
        self._params = dict(kwargs)
        self._params.setdefault("tree_learner", "data")
        self._local = self._local_cls(**self._params)

    def _get_client(self):
        if self._client is not None:
            return self._client
        try:
            from distributed import get_client
            return get_client()
        except (ImportError, ValueError):
            return None

    # -- fit keeps the reference signature (dask.py:393 _train) --------
    def fit(self, X, y, sample_weight=None, group=None, **kwargs):
        client = self._get_client()
        workers = list(client.scheduler_info()["workers"]) \
            if client is not None else []
        if client is None or len(workers) <= 1:
            # single worker / no scheduler: the local device mesh is the
            # parallelism (rows shard over chips, parallel/learner.py)
            Xl = _concat_to_local(X)
            yl = _concat_to_local(y)
            sw = None if sample_weight is None else _concat_to_local(
                sample_weight)
            fit_kwargs = dict(kwargs)
            if group is not None:
                fit_kwargs["group"] = _concat_to_local(group)
            self._local.fit(Xl, yl, sample_weight=sw, **fit_kwargs)
            return self

        # ---- multi-worker: reference machines-injection flow ----------
        colls = [X, y] + ([sample_weight] if sample_weight is not None
                          else []) + ([group] if group is not None else [])
        by_worker = _parts_by_worker(client, colls)
        workers = sorted(by_worker)
        ports = client.run(_find_open_port, workers=workers)
        machines = ",".join(
            f"{w.split('://')[-1].rsplit(':', 1)[0]}:{ports[w]}"
            for w in workers)
        classes = None
        if isinstance(self._local, LGBMClassifier):
            # global class set from tiny per-partition uniques (no y
            # shipping): every rank must encode labels identically even
            # when its partitions miss a class
            uniq = client.gather([
                client.submit(lambda p: np.unique(np.asarray(p[1])),
                              f, pure=False)
                for parts in by_worker.values() for f in parts])
            classes = np.unique(np.concatenate(uniq))
        futures = [
            client.submit(
                _train_part, type(self._local), self._params, rank,
                machines, len(workers), ports[w], by_worker[w],
                sample_weight is not None, group is not None,
                dict(kwargs), classes, workers=[w], pure=False)
            for rank, w in enumerate(workers)]
        results = client.gather(futures)
        model_str = next(r for r in results if r is not None)
        from .basic import Booster
        self._local._Booster = Booster(model_str=model_str)
        if classes is not None:
            self._local._classes = classes
            self._local._n_classes = len(classes)
            self._local._label_map = {c: i
                                      for i, c in enumerate(classes)}
        return self

    def _predict_impl(self, X, method, **kwargs):
        # per-partition scoring (reference _predict_part, dask.py:811):
        # dask collections map the local model over their partitions so
        # no data ships to the driver
        import dask.array as da
        import dask.dataframe as dd
        if isinstance(X, da.Array):
            # probe the output rank: predict is 1-D, predict_proba /
            # pred_contrib / multiclass raw scores are 2-D
            probe = np.asarray(method(
                np.zeros((1, X.shape[1]), np.float64), **kwargs))
            fn = lambda b: np.asarray(method(b, **kwargs))
            if probe.ndim == 1:
                return X.map_blocks(
                    fn, drop_axis=list(range(1, X.ndim)),
                    dtype=probe.dtype)
            return X.map_blocks(
                fn, chunks=(X.chunks[0], (probe.shape[1],)),
                dtype=probe.dtype)
        if isinstance(X, (dd.DataFrame, dd.Series)):
            def part_fn(p):
                import pandas as pd
                out = np.asarray(method(p, **kwargs))
                if out.ndim == 1:
                    return pd.Series(out, index=p.index)
                return pd.DataFrame(out, index=p.index)
            return X.map_partitions(part_fn)
        return np.asarray(method(_concat_to_local(X), **kwargs))

    def predict(self, X, **kwargs):
        return self._predict_impl(X, self._local.predict, **kwargs)

    def predict_proba(self, X, **kwargs):
        return self._predict_impl(X, self._local.predict_proba, **kwargs)

    def __getattr__(self, name):
        # delegate attributes (booster_, feature_importances_, ...) to the
        # wrapped local estimator
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._local, name)

    def to_local(self):
        """Return the underlying single-process estimator (reference
        DaskLGBMModel.to_local, dask.py:900+)."""
        return self._local


class DaskLGBMClassifier(_DaskBase):
    """Distributed classifier (reference dask.py:1060)."""
    _local_cls = LGBMClassifier


class DaskLGBMRegressor(_DaskBase):
    """Distributed regressor (reference dask.py:1230)."""
    _local_cls = LGBMRegressor


class DaskLGBMRanker(_DaskBase):
    """Distributed ranker (reference dask.py:1380)."""
    _local_cls = LGBMRanker
