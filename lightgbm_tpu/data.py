"""Binned dataset resident in device HBM + training metadata.

Redesign of the reference data layer (include/LightGBM/dataset.h:355
`Dataset`, dataset.h:45 `Metadata`, feature_group.h:25 `FeatureGroup`):

- the reference stores column-oriented `Bin` objects (dense_bin.hpp:53) with
  optional 4-bit packing and multi-value row-wise mirrors
  (multi_val_dense_bin.hpp:20) chosen by runtime probing
  (dataset.cpp:600-702). On TPU a single row-major `[num_data, num_features]`
  uint8/uint16 matrix in HBM is the right layout: histogram build reads it
  row-wise (the probe is unnecessary), and XLA tiles it.
- trivial features (single bin) are dropped up-front like the reference's
  feature_pre_filter (dataset_loader feature filtering); the used->original
  index map is kept for model output.
- EFB bundling (feature_group.h:25): for dense narrow data it buys
  nothing on TPU (bundling saved *column passes* in the CPU design; the
  one-hot histogram contraction reads every (row, feature) cell exactly
  once either way), and the reference's memory headline is answered by
  sparse ingest (from_sparse: only the uint8 bin matrix materializes).
  For WIDE sparse data EFB would still shrink the histogram kernel's
  F axis (its flops scale with F). The TPU-native design, sketched for
  when that workload matters: bundle mutually-exclusive features into
  shared uint8 columns with bin offsets (greedy conflict-bounded, as
  the reference); build histograms on the bundled layout [S, Fb, 256];
  run the split scan SEGMENTED — per-subfeature left sums are
  prefix(t) - prefix(segment_start - 1) with static [Fb, 256]
  segment-start/feature-id/NaN-position tables (all elementwise, no
  gathers); the post-argmax (bundle, bin) -> (original feature, local
  threshold) mapping is an [S]-sized table lookup. Growth and routing
  stay in bundle space; the HostModel boundary unbundles exactly like
  used_features remapping does today.

`Metadata` carries label/weight/group/init_score and the query boundaries
used by ranking objectives (reference src/io/metadata.cpp:577).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .binning import BinMapper, find_bin_mappers
from .utils.log import Log
from .utils.file_io import open_file

__all__ = ["Metadata", "BinnedDataset"]


class Metadata:
    """Labels, weights, query boundaries, init scores (dataset.h:45)."""

    def __init__(self, num_data: int,
                 label: Optional[np.ndarray] = None,
                 weight: Optional[np.ndarray] = None,
                 group: Optional[np.ndarray] = None,
                 init_score: Optional[np.ndarray] = None):
        self.num_data = num_data
        self.label = None if label is None else \
            np.ascontiguousarray(label, dtype=np.float32).reshape(-1)
        self.weight = None if weight is None else \
            np.ascontiguousarray(weight, dtype=np.float32).reshape(-1)
        self.init_score = None if init_score is None else \
            np.ascontiguousarray(init_score, dtype=np.float64)
        # group: either sizes per query or boundaries; store boundaries
        self.query_boundaries: Optional[np.ndarray] = None
        if group is not None:
            group = np.asarray(group)
            if len(group) and group[0] == 0 and np.all(np.diff(group) >= 0):
                self.query_boundaries = group.astype(np.int64)
            else:
                self.query_boundaries = np.concatenate(
                    [[0], np.cumsum(group)]).astype(np.int64)
        self._validate()

    def _validate(self) -> None:
        if self.label is not None and len(self.label) != self.num_data:
            Log.fatal("Length of label (%d) != num_data (%d)",
                      len(self.label), self.num_data)
        if self.weight is not None and len(self.weight) != self.num_data:
            Log.fatal("Length of weight (%d) != num_data (%d)",
                      len(self.weight), self.num_data)
        if self.query_boundaries is not None and \
                self.query_boundaries[-1] != self.num_data:
            Log.fatal("Sum of query counts (%d) != num_data (%d)",
                      int(self.query_boundaries[-1]), self.num_data)

    @property
    def num_queries(self) -> int:
        if self.query_boundaries is None:
            return 0
        return len(self.query_boundaries) - 1

    def query_ids(self) -> Optional[np.ndarray]:
        """Per-row query id (for segment ops in ranking objectives)."""
        if self.query_boundaries is None:
            return None
        sizes = np.diff(self.query_boundaries)
        return np.repeat(np.arange(len(sizes), dtype=np.int32), sizes)


def _select_used_features(all_mappers, pre_filter: bool):
    """Shared dense/sparse ingestion prologue: drop trivial features
    (reference feature_pre_filter), pick the bin-matrix dtype."""
    used, used_mappers = [], []
    for f, m in enumerate(all_mappers):
        if pre_filter and m.is_trivial:
            continue
        used.append(f)
        used_mappers.append(m)
    if not used:
        Log.warning("All features are trivial (constant); nothing to learn")
    used = np.array(used, dtype=np.int32)
    max_num_bin = max([m.num_bin for m in used_mappers], default=2)
    dtype = np.uint8 if max_num_bin <= 256 else np.uint16
    return used, used_mappers, dtype


class BinnedDataset:
    """Quantized dataset: `[num_data, num_used_features]` bin matrix.

    Reference Dataset (dataset.h:355) minus the feature-group machinery;
    `construct histograms` lives in learner/histogram.py and takes the raw
    arrays, keeping this class a pure data holder.
    """

    def __init__(self, bins: np.ndarray, mappers: List[BinMapper],
                 used_features: np.ndarray, num_total_features: int,
                 metadata: Metadata,
                 feature_names: Optional[List[str]] = None,
                 raw: Optional[np.ndarray] = None):
        assert bins.shape[1] == len(used_features)
        self.bins = bins                      # [N, F_used] uint8/uint16
        # raw (un-binned) values of the used features, kept only for
        # linear trees (reference Dataset has_raw_, dataset.cpp:418-420)
        self.raw = raw                        # [N, F_used] f32 or None
        self.mappers = mappers                # per USED feature
        self.used_features = used_features    # used idx -> original idx
        self.num_total_features = num_total_features
        self.metadata = metadata
        self.feature_names = feature_names or [
            f"Column_{i}" for i in range(num_total_features)]
        # per-used-feature bin counts and flat offsets
        self.num_bins = np.array([m.num_bin for m in mappers], dtype=np.int32)
        self.feature_offsets = np.concatenate(
            [[0], np.cumsum(self.num_bins)]).astype(np.int32)
        self.total_bins = int(self.feature_offsets[-1])
        self.is_categorical = np.array(
            [m.is_categorical for m in mappers], dtype=bool)
        self.missing_types = np.array(
            [m.missing_type for m in mappers], dtype=np.int32)
        self.default_bins = np.array(
            [m.default_bin for m in mappers], dtype=np.int32)

    # ---- construction -------------------------------------------------
    @staticmethod
    def from_raw(X: np.ndarray, metadata: Metadata, max_bin: int = 255,
                 min_data_in_bin: int = 3, sample_cnt: int = 200000,
                 use_missing: bool = True, zero_as_missing: bool = False,
                 categorical_features: Optional[Sequence[int]] = None,
                 seed: int = 1, feature_names: Optional[List[str]] = None,
                 mappers: Optional[List[BinMapper]] = None,
                 feature_pre_filter: bool = True,
                 keep_raw: bool = False,
                 pre_filter_with_mappers: bool = False) -> "BinnedDataset":
        """Quantize raw features. If `mappers` given, reuse them (aligned
        valid set — reference LoadFromFileAlignWithOtherDataset,
        dataset_loader.cpp:299)."""
        X = np.asarray(X)
        if X.ndim != 2:
            raise ValueError("X must be 2-dimensional")
        num_data, num_total = X.shape
        if mappers is None:
            all_mappers = find_bin_mappers(
                X, max_bin=max_bin, min_data_in_bin=min_data_in_bin,
                sample_cnt=sample_cnt, use_missing=use_missing,
                zero_as_missing=zero_as_missing,
                categorical_features=categorical_features, seed=seed)
        else:
            if len(mappers) != num_total:
                raise ValueError(
                    f"got {len(mappers)} bin mappers for {num_total} features")
            all_mappers = mappers
        used, used_mappers, dtype = _select_used_features(
            all_mappers, feature_pre_filter and
            (mappers is None or pre_filter_with_mappers))
        from .binning import bin_columns
        from .utils.timer import global_timer
        with global_timer.timeit("dataset_quantize"):
            binned = bin_columns(X, used, used_mappers, dtype)
        raw = np.ascontiguousarray(
            X[:, used], dtype=np.float32) if keep_raw else None
        return BinnedDataset(binned, used_mappers, used, num_total, metadata,
                             feature_names, raw=raw)

    @staticmethod
    def from_sparse(X, metadata: Metadata, max_bin: int = 255,
                    min_data_in_bin: int = 3, sample_cnt: int = 200000,
                    use_missing: bool = True, zero_as_missing: bool = False,
                    categorical_features: Optional[Sequence[int]] = None,
                    seed: int = 1,
                    feature_names: Optional[List[str]] = None,
                    mappers: Optional[List[BinMapper]] = None,
                    feature_pre_filter: bool = True,
                    keep_raw: bool = False,
                    pre_filter_with_mappers: bool = False
                    ) -> "BinnedDataset":
        """Quantize a scipy CSR/CSC matrix without densifying the raw
        values: bin mappers come from per-column stored values (+ implicit
        zero counts), and only the uint8/16 bin matrix is materialized —
        the memory shape of the reference's SparseBin ingestion
        (sparse_bin.hpp:73, python-package basic.py __init_from_csr)."""
        if keep_raw:
            raise ValueError(
                "linear_tree requires dense input (leaf linear models "
                "need raw feature values)")
        X = X.tocsc()
        # canonicalize: scipy allows duplicate (row, col) entries whose
        # semantic value is the SUM; without this, fancy-index binning
        # would keep only the last duplicate while dense paths sum
        if hasattr(X, "sum_duplicates"):
            X.sum_duplicates()
        if not getattr(X, "has_sorted_indices", True):
            X.sort_indices()
        num_data, num_total = X.shape
        if mappers is None:
            from .binning import find_bin_mappers_sparse
            all_mappers = find_bin_mappers_sparse(
                X, max_bin=max_bin, min_data_in_bin=min_data_in_bin,
                sample_cnt=sample_cnt, use_missing=use_missing,
                zero_as_missing=zero_as_missing,
                categorical_features=categorical_features, seed=seed)
        else:
            if len(mappers) != num_total:
                raise ValueError(
                    f"got {len(mappers)} bin mappers for {num_total} "
                    f"features")
            all_mappers = mappers
        used, used_mappers, dtype = _select_used_features(
            all_mappers, feature_pre_filter and
            (mappers is None or pre_filter_with_mappers))
        binned = np.empty((num_data, len(used)), dtype=dtype)
        indptr, indices, vals = X.indptr, X.indices, X.data
        for j, f in enumerate(used):
            m = used_mappers[j]
            lo, hi = int(indptr[f]), int(indptr[f + 1])
            binned[:, j] = m._value_to_bin_scalar(0.0)
            if hi > lo:
                binned[indices[lo:hi], j] = m.values_to_bins(
                    np.asarray(vals[lo:hi], dtype=np.float64)).astype(dtype)
        return BinnedDataset(binned, used_mappers, used, num_total,
                             metadata, feature_names, raw=None)

    @staticmethod
    def from_chunks(chunks, metadata: Metadata, max_bin: int = 255,
                    min_data_in_bin: int = 3, sample_cnt: int = 200000,
                    use_missing: bool = True, zero_as_missing: bool = False,
                    categorical_features: Optional[Sequence[int]] = None,
                    seed: int = 1,
                    feature_names: Optional[List[str]] = None,
                    mappers: Optional[List[BinMapper]] = None,
                    feature_pre_filter: bool = True,
                    keep_raw: bool = False,
                    pre_filter_with_mappers: bool = False
                    ) -> "BinnedDataset":
        """Streamed construction from row chunks (a list of 2-D arrays
        and/or Sequence objects): the reference's ChunkedArray push path
        (utils/chunked_array.hpp, LGBM_DatasetPushRows c_api.h, python
        Sequence in basic.py). Two passes — a global row sample finds
        the bin mappers, then each chunk is quantized straight into the
        preallocated uint8/16 matrix. The dense f64 matrix never exists:
        peak host memory is one chunk + the bin matrix."""
        if keep_raw:
            raise ValueError(
                "linear_tree requires an in-memory dense matrix (leaf "
                "linear models need raw feature values)")
        lens = [len(c) if not hasattr(c, "shape") else c.shape[0]
                for c in chunks]
        offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
        num_data = int(offsets[-1])
        if num_data == 0:
            raise ValueError("no rows in chunks")
        first = np.asarray(chunks[0][0:1], dtype=np.float64)
        num_total = first.shape[1]

        def chunk_rows(ci, lo, hi):
            return np.asarray(chunks[ci][lo:hi], dtype=np.float64) \
                .reshape(hi - lo, num_total)

        if mappers is None:
            take = min(sample_cnt, num_data)
            rng = np.random.RandomState(seed)
            if num_data <= take:
                idx = np.arange(num_data)
            elif num_data > 4 * take:
                # huge streams: O(take) draw (choice(replace=False)
                # would allocate an O(num_data) permutation); duplicates
                # dropped, a slightly smaller sample is fine for binning
                idx = np.unique(rng.randint(0, num_data, size=take))
            else:
                idx = np.sort(rng.choice(num_data, size=take,
                                         replace=False))
            parts = []
            for ci in range(len(chunks)):
                sel = idx[(idx >= offsets[ci]) & (idx < offsets[ci + 1])]
                if len(sel) == 0:
                    continue
                local = sel - offsets[ci]
                # batch-walk only the windows containing samples: the
                # peak materialization stays one batch regardless of how
                # widely the sample spans a chunk
                step = getattr(chunks[ci], "batch_size", 65536) or 65536
                for lo in range(0, lens[ci], step):
                    hi = min(lo + step, lens[ci])
                    sel_b = local[(local >= lo) & (local < hi)]
                    if len(sel_b) == 0:
                        continue
                    parts.append(chunk_rows(ci, lo, hi)[sel_b - lo])
            sample = np.concatenate(parts, axis=0)
            all_mappers = find_bin_mappers(
                sample, max_bin=max_bin, min_data_in_bin=min_data_in_bin,
                sample_cnt=len(sample), use_missing=use_missing,
                zero_as_missing=zero_as_missing,
                categorical_features=categorical_features, seed=seed)
        else:
            if len(mappers) != num_total:
                raise ValueError(
                    f"got {len(mappers)} bin mappers for {num_total} "
                    f"features")
            all_mappers = mappers
        used, used_mappers, dtype = _select_used_features(
            all_mappers, feature_pre_filter and
            (mappers is None or pre_filter_with_mappers))
        binned = np.empty((num_data, len(used)), dtype=dtype)
        for ci in range(len(chunks)):
            step = getattr(chunks[ci], "batch_size", 65536) or 65536
            for lo in range(0, lens[ci], step):
                hi = min(lo + step, lens[ci])
                block = chunk_rows(ci, lo, hi)
                row0 = int(offsets[ci]) + lo
                from .binning import bin_columns
                binned[row0:row0 + (hi - lo)] = bin_columns(
                    np.asarray(block), used, used_mappers, dtype)
        return BinnedDataset(binned, used_mappers, used, num_total,
                             metadata, feature_names, raw=None)

    # ---- accessors ----------------------------------------------------
    @property
    def num_data(self) -> int:
        return self.bins.shape[0]

    @property
    def num_features(self) -> int:
        return self.bins.shape[1]

    def subset(self, row_indices: np.ndarray) -> "BinnedDataset":
        """Row subset sharing mappers (reference Dataset::CopySubrow)."""
        md = self.metadata
        sub_md = Metadata(
            len(row_indices),
            None if md.label is None else md.label[row_indices],
            None if md.weight is None else md.weight[row_indices],
            None,
            None if md.init_score is None else md.init_score[row_indices])
        return BinnedDataset(self.bins[row_indices], self.mappers,
                             self.used_features, self.num_total_features,
                             sub_md, self.feature_names,
                             raw=None if self.raw is None
                             else self.raw[row_indices])

    # ---- binary cache -------------------------------------------------
    # Reference: Dataset::SaveBinaryFile / DatasetLoader::LoadFromBinFile
    # (dataset.cpp binary token path, dataset_loader.cpp:274) — skips text
    # parsing and bin finding entirely on reload.
    _BINARY_MAGIC = "lightgbm_tpu.dataset.v1"

    def save_binary(self, filename: str) -> None:
        """Serialize the quantized matrix + bin mappers + metadata."""
        import json
        md = self.metadata
        mapper_json = json.dumps([m.to_dict() for m in self.mappers])
        payload = dict(
            magic=np.frombuffer(
                self._BINARY_MAGIC.encode(), dtype=np.uint8),
            bins=self.bins,
            used_features=self.used_features,
            num_total_features=np.int64(self.num_total_features),
            feature_names=np.array([str(s) for s in self.feature_names]),
            mappers_json=np.frombuffer(
                mapper_json.encode(), dtype=np.uint8),
        )
        if self.raw is not None:
            payload["raw"] = self.raw
        for fld in ("label", "weight", "init_score"):
            v = getattr(md, fld)
            if v is not None:
                payload["md_" + fld] = v
        if md.query_boundaries is not None:
            payload["md_query_boundaries"] = md.query_boundaries
        with open_file(filename, "wb") as fh:
            np.savez_compressed(fh, **payload)

    @staticmethod
    def is_binary_file(filename: str) -> bool:
        try:
            with open_file(filename, "rb") as fh:
                if fh.read(4) != b"PK\x03\x04":
                    return False
            with open_file(filename, "rb") as fh, np.load(fh) as z:
                if "magic" not in z:
                    return False
                return bytes(z["magic"]).decode() == \
                    BinnedDataset._BINARY_MAGIC
        except Exception:
            return False

    @staticmethod
    def load_binary(filename: str) -> "BinnedDataset":
        import json
        from .binning import BinMapper
        with open_file(filename, "rb") as fh, np.load(fh) as z:
            if bytes(z["magic"]).decode() != BinnedDataset._BINARY_MAGIC:
                raise ValueError(f"{filename} is not a lightgbm_tpu "
                                 "binary dataset")
            mappers = [BinMapper.from_dict(d) for d in
                       json.loads(bytes(z["mappers_json"]).decode())]
            bins = z["bins"]
            md = Metadata(
                int(bins.shape[0]),
                label=z["md_label"] if "md_label" in z else None,
                weight=z["md_weight"] if "md_weight" in z else None,
                group=z["md_query_boundaries"]
                if "md_query_boundaries" in z else None,
                init_score=z["md_init_score"]
                if "md_init_score" in z else None)
            return BinnedDataset(
                bins, mappers, z["used_features"],
                int(z["num_total_features"]), md,
                [str(s) for s in z["feature_names"]],
                raw=z["raw"] if "raw" in z else None)
