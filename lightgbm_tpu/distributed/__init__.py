"""Distributed training subsystem: the learner crossbar, reduce-scatter
histogram aggregation and distributed binning.

The reference's distributed story lives in three places — the
CreateTreeLearner factory (tree_learner.cpp:16-64), the parallel tree
learners (data/feature/voting_parallel_tree_learner.cpp) and the network
layer (src/network/). Here:

- ``crossbar``: the learner-factory registry (device x parallelism)
  that `boosting/gbdt.py` resolves a grower through, instead of
  assuming the serial one.
- ``hist_agg``: reduce-scatter histogram aggregation — each device owns
  a contiguous feature shard of the global histogram, finds its best
  local split, and a small allgather of [S, world] candidates merges
  them (data_parallel_tree_learner.cpp:184-233; memory-efficient array
  redistribution, arXiv:2112.01075).
- ``binning``: per-rank streaming reservoir sketches merged through the
  mapper-sync collective so bin mappers come from a global sample
  without any host materializing the dataset (Histogram Sort with
  Sampling, arXiv:1803.01237).
- ``fused``: the row-sharded fused multi-tree scan — the boosting loop
  of `boosting/fused.py` inside `shard_map`, so K sharded trees cost
  one device dispatch and compose with the pipelined executor.
- ``elastic``: the membership-epoch protocol that turns a rank-death
  abort into a mesh shrink — survivors vote through the heartbeat
  directory, commit a new epoch, and reincarnate at the smaller world
  (docs/Distributed.md "Elasticity").
"""

from .crossbar import (CROSSBAR, LearnerSpec, create_tree_learner,
                       resolve_learner)
from .elastic import (ELASTIC_RESIZE_EXIT_CODE, MembershipRecord,
                      current_epoch, epoch_agree, load_membership,
                      propose_shrink, request_join)
from .hist_agg import (build_feature_shards, check_hist_agg_fault,
                       reduce_scatter_hist)

__all__ = ["CROSSBAR", "LearnerSpec", "create_tree_learner",
           "resolve_learner", "build_feature_shards",
           "check_hist_agg_fault", "reduce_scatter_hist",
           "ELASTIC_RESIZE_EXIT_CODE", "MembershipRecord",
           "current_epoch", "epoch_agree", "load_membership",
           "propose_shrink", "request_join"]
