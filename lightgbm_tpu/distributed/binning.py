"""Distributed binning: per-rank streaming sketches -> global mappers.

The streaming spine (PR 7) already computes a per-rank reservoir sketch
during ingestion pass 1; multihost bin finding already rides ONE
allgather (`basic.py::_allgather_find_mappers`, the reference's
sample-then-allgather of dataset_loader.cpp:722-807). This module fuses
the two into the distributed-binning entry point the streamed loader
plugs in as its ``mapper_sync``: each rank contributes its reservoir
sample, the fixed-wire-shape gather unions them, and every rank freezes
IDENTICAL bin boundaries from a global sample — no host ever
materializes (or even fully samples) the dataset (Histogram Sort with
Sampling, arXiv:1803.01237).

The sample allocation stays equal-per-rank
(``bin_construct_sample_cnt // world`` rows each, exactly what
`_allgather_find_mappers` gathers): byte parity with the in-memory
multihost path is a checked invariant (tests/test_multihost.py) and the
reference allocates the same way. The collective inherits the
`collective_psum` fault site and the watchdog bracket from the
delegated gather; this module adds the `lightgbm_tpu_distributed`
sketch telemetry on top.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

__all__ = ["merge_streaming_sketch", "distributed_mapper_sync"]


def merge_streaming_sketch(sample, cfg, cat):
    """Merge this rank's pass-1 reservoir sketch into global bin
    mappers: delegates the union to the mapper-sync allgather
    (`_allgather_find_mappers` — fault site + watchdog bracket live
    there), recording the sketch volume that crossed the wire into the
    distributed metric family first."""
    from ..basic import _allgather_find_mappers
    rows = int(np.asarray(sample).shape[0]) if sample is not None else 0
    _record_sketch(rows)
    return _allgather_find_mappers(sample, cfg, cat)


def distributed_mapper_sync(cfg, cat) -> Optional[Callable]:
    """The streamed loader's multihost ``mapper_sync`` hook: a closure
    mapping this rank's sketch sample to globally-agreed bin mappers.
    None single-process — the loader then bins locally, and binning is
    "distributed" over devices only (rows shard after binning)."""
    from ..basic import _multihost_process_count
    if _multihost_process_count() <= 1:
        return None
    return lambda sample: merge_streaming_sketch(sample, cfg, cat)


def _record_sketch(rows: int) -> None:
    """lightgbm_tpu_distributed sketch telemetry; never raises."""
    try:
        from ..observability.registry import registry
        registry.record_distributed_sketch(rows)
    except Exception:       # pragma: no cover - telemetry only
        pass
