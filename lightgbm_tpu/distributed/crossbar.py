"""Learner-factory crossbar: device x parallelism -> grower.

The reference resolves its tree learner through one factory,
``TreeLearner::CreateTreeLearner`` (tree_learner.cpp:16-64): a crossbar
of device type {cpu, gpu, cuda} x learner type {serial, feature, data,
voting}. Our device column collapses to XLA (the same jitted growth
body runs on CPU/TPU), but the crossbar survives as the single registry
`boosting/gbdt.py` and the pipelined executor resolve a grower through
— with two device rows of our own: the portable scatter grower and the
MXU growth path, each crossed with the parallelism mode.

``resolve_learner`` picks the row (validating mode/device/hist_agg
combinations in ONE place instead of scattered gates);
``create_tree_learner`` builds the actual shard_map'ped grower for it.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

__all__ = ["LearnerSpec", "CROSSBAR", "resolve_learner",
           "create_tree_learner"]


@dataclasses.dataclass(frozen=True)
class LearnerSpec:
    """One crossbar cell: how tree growth is dispatched.

    Mirrors the reference's (device, learner) template instantiation
    (serial_tree_learner.cpp / *_parallel_tree_learner.cpp): `mode` is
    the parallelism column, `device` the kernel row, `hist_agg` the
    histogram merge algorithm for the row-sharded modes."""
    mode: str                 # "serial" | "data" | "feature" | "voting"
    device: str               # "scatter" (portable) | "mxu"
    hist_agg: str = "psum"    # "psum" | "reduce_scatter" (data/voting)
    rows_sharded: bool = False    # bins/grad/hess/cnt sharded over mesh
    supports_multihost: bool = False

    @property
    def is_parallel(self) -> bool:
        return self.mode != "serial"


#: the factory table (reference tree_learner.cpp:16-64). Keys are
#: (device, mode); values carry the sharding + merge contract of the
#: cell. reduce_scatter rides only the portable data/voting rows: the
#: MXU grower keeps its per-pass psum (its histogram lives inside the
#: kernel), and feature-parallel has no histogram merge at all.
CROSSBAR = {
    ("scatter", "serial"): LearnerSpec("serial", "scatter"),
    ("mxu", "serial"): LearnerSpec("serial", "mxu"),
    ("scatter", "data"): LearnerSpec(
        "data", "scatter", hist_agg="reduce_scatter", rows_sharded=True,
        supports_multihost=True),
    ("mxu", "data"): LearnerSpec(
        "data", "mxu", hist_agg="psum", rows_sharded=True,
        supports_multihost=True),
    ("scatter", "feature"): LearnerSpec("feature", "scatter"),
    ("scatter", "voting"): LearnerSpec(
        "voting", "scatter", hist_agg="reduce_scatter",
        rows_sharded=True),
}


def resolve_learner(tree_learner: str, *, device: str = "scatter",
                    hist_agg: str = "auto", num_features: int = 0,
                    top_k: int = 20, nproc: int = 1,
                    has_efb: bool = False,
                    mono_rescan: bool = False) -> LearnerSpec:
    """Resolve one crossbar cell, downgrading `hist_agg` where the
    reduce-scatter path cannot hold its contract:

    - multihost (nproc > 1): the chaos/resume guarantees are proven on
      the psum merge; gloo's all_to_all support is not, so cross-host
      runs keep psum.
    - EFB: histograms build in bundle space and expand per device; a
      feature-sharded scan would need the expansion split mid-bundle.
    - non-basic monotone methods: the whole-tree histogram cache wants
      every feature on every device.
    - voting with 2*top_k < F: the vote-selected columns are not a
      contiguous block, so ownership does not cover them; classic
      PV-Tree psum applies.

    `hist_agg="auto"` means "reduce_scatter wherever exact", explicit
    "psum"/"reduce_scatter" are honored (with the same safety
    downgrades)."""
    key = (device, tree_learner)
    if key not in CROSSBAR:
        raise ValueError(
            f"no tree learner for device={device!r} "
            f"tree_learner={tree_learner!r} (crossbar rows: "
            f"{sorted(CROSSBAR)})")
    spec = CROSSBAR[key]
    agg = spec.hist_agg
    if hist_agg != "auto":
        agg = hist_agg
    if agg == "reduce_scatter":
        blocked = (nproc > 1 or has_efb or mono_rescan
                   or device == "mxu"
                   or spec.mode not in ("data", "voting")
                   or (spec.mode == "voting"
                       and num_features > 0
                       and 2 * top_k < num_features))
        if blocked:
            agg = "psum"
    if not spec.rows_sharded:
        agg = "psum"    # no histogram merge happens at all
    return dataclasses.replace(spec, hist_agg=agg)


def create_tree_learner(spec: LearnerSpec, mesh, comm, **kwargs
                        ) -> Optional[object]:
    """Instantiate the grower for a resolved crossbar cell (the factory
    half of CreateTreeLearner). Serial cells return None — the caller
    keeps its un-shard_map'ped growth dispatch; parallel cells return
    the jitted shard_map grower from parallel/learner.py with the
    cell's device row selecting the MXU or portable body."""
    if not spec.is_parallel:
        return None
    _record_epoch_resolve(spec)
    from ..parallel.learner import make_sharded_grower
    return make_sharded_grower(mesh, comm, use_mxu=spec.device == "mxu",
                               **kwargs)


def _record_epoch_resolve(spec: LearnerSpec) -> None:
    """Elastic reincarnation re-resolves the learner through this same
    crossbar at the shrunken world; leave a flight-recorder breadcrumb
    when that happens (epoch > 0) so a postmortem shows which cell the
    resized run landed on. Never raises — forensics must not block the
    factory."""
    try:
        from .elastic import current_epoch
        epoch = current_epoch()
        if epoch > 0:
            from ..observability.flightrec import recorder
            recorder.record("resize", "crossbar_resolve", epoch=epoch,
                            mode=spec.mode, device=spec.device,
                            hist_agg=spec.hist_agg)
    except Exception:       # pragma: no cover - forensics only
        pass
