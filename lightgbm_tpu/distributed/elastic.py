"""Elastic world resize: membership epochs over the guarded collectives.

The PR-9 survivability story ends every rank-death incident the same
way: the watchdog diagnoses "rank k last seen Ns ago" and every
survivor ``os._exit(113)``s — the run dies even though the data, the
checkpoint and most of the chips are fine. This module turns that abort
into a *resize*: survivors agree on a smaller world, drain to the last
coordinated checkpoint, and finish the run.

Design constraint that shapes everything here: a rank blocked inside a
gloo/ICI collective CANNOT be interrupted from Python — the watchdog
monitor is a daemon thread and the main thread is stuck in C until the
process dies. True in-process mesh surgery is therefore impossible; the
protocol is Torch-Elastic-style **process reincarnation** instead:

1. the `CollectiveGuard` deadline fires with ``elastic_resize=true``;
   the abort path calls `propose_shrink` instead of exiting 113;
2. each fresh survivor names the dead ranks from the same stale
   heartbeats the abort diagnosis uses, and writes a *shrink proposal*
   (``resize_epoch_%04d_rank_%03d.json``) into the heartbeat directory
   — deliberately NOT a collective: the old world's collectives are
   the thing that just failed, so the vote rides the shared filesystem
   the heartbeats already prove works;
3. when every fresh survivor's proposal agrees on the member list, the
   lowest surviving rank commits ``membership_epoch_%04d.json`` — the
   new epoch, the new world size, the survivor->new-rank renumbering
   and the checkpoint bundle to resume from. Parked joiners
   (``join_*.json``) are admitted at this epoch cut and extend the
   member plan;
4. every survivor exits with `ELASTIC_RESIZE_EXIT_CODE` (75 — a
   voluntary reincarnation, distinct from the watchdog abort 113 and
   the injected rank death 86). A supervisor (`testing/chaos.py`
   ``run_elastic_training``, or any orchestrator watching exit codes)
   relaunches the survivors at the new world size with contiguous
   ranks and ``LIGHTGBM_TPU_EPOCH`` set;
5. the reincarnated processes re-init jax.distributed at W', re-resolve
   the learner through the crossbar, load the W-rank bundle through the
   reshard loader (`reliability/checkpoint.py
   load_checkpoint_resharded`), slice their contiguous row block via
   `reshard_offsets`, and resume boosting at the exact iteration.

Stale-epoch rejection: every `guarded_allgather` piggybacks
`current_epoch` on the same wire as its payload (parallel/comm.py); a
zombie rank from a previous epoch that finds its way into a collective
trips `check_epoch_agreement` on every rank instead of silently
corrupting the gather.

Observability: the ``lightgbm_tpu_membership`` family (epoch, world,
resizes, joins, reshard_wall_s — observability/registry.py) plus
flight-recorder ``resize`` events at the vote, the commit and the
crossbar re-resolve.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import asdict, dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils.log import Log, LightGBMError

__all__ = [
    "ELASTIC_RESIZE_EXIT_CODE", "MembershipRecord", "current_epoch",
    "set_epoch", "reset_epoch", "check_epoch_agreement", "epoch_agree",
    "reshard_offsets", "reshard_slice", "plan_resize", "propose_shrink",
    "request_join", "list_joiners", "load_membership",
    "sweep_stale_epoch_files",
]

#: exit status of a rank leaving voluntarily to be reincarnated at the
#: new world size — distinct from the watchdog abort (113) and the
#: injected rank death (86), so supervisors and chaos tests can tell
#: "relaunch me smaller" from "something went wrong"
ELASTIC_RESIZE_EXIT_CODE = 75

_MEMBER_PREFIX = "membership_epoch_"
_PROPOSAL_PREFIX = "resize_epoch_"
_JOIN_PREFIX = "join_"
_HB_PREFIX = "hb_rank_"


# ----------------------------------------------------------------------
# membership-epoch state: one integer per process, seeded from the
# supervisor's LIGHTGBM_TPU_EPOCH on first read so reincarnated workers
# wake up already in the committed epoch

_state_lock = threading.Lock()
_epoch: Optional[int] = None


def current_epoch() -> int:
    """This process's membership epoch (0 = the original world)."""
    global _epoch
    with _state_lock:
        if _epoch is None:
            _epoch = int(os.environ.get("LIGHTGBM_TPU_EPOCH", "0") or 0)
        return _epoch


def set_epoch(epoch: int) -> None:
    global _epoch
    with _state_lock:
        _epoch = int(epoch)


def reset_epoch() -> None:
    """Forget the cached epoch (tests): the next `current_epoch` re-seeds
    from the environment."""
    global _epoch
    with _state_lock:
        _epoch = None


def check_epoch_agreement(epochs, label: str = "collective") -> None:
    """Stale-epoch rejection: every participant of a collective must be
    in the same membership epoch, and it must be THIS process's epoch.
    A zombie from a pre-resize world that wanders into a barrier
    corrupts the gather silently; this turns it into a named error on
    every rank (rank-uniform data, so all ranks raise together)."""
    seen = sorted({int(e) for e in epochs})
    if len(seen) > 1:
        raise LightGBMError(
            f"collective '{label}': participants span membership epochs "
            f"{seen} — a rank from a stale world joined the barrier; "
            f"restart it at the committed epoch")
    if seen and seen[0] != current_epoch():
        raise LightGBMError(
            f"collective '{label}': wire epoch {seen[0]} does not match "
            f"this process's membership epoch {current_epoch()}")


def epoch_agree(label: str = "elastic_epoch_agree") -> int:
    """Startup barrier of a (re)incarnated world: every rank contributes
    its membership epoch through the guarded allgather (inheriting the
    `collective_psum` fault site and the watchdog bracket) and all must
    agree. Returns the agreed epoch."""
    from ..parallel.comm import guarded_allgather
    epochs = np.asarray(guarded_allgather(
        np.asarray([current_epoch()], dtype=np.int64),
        label=label)).reshape(-1)
    check_epoch_agreement([int(e) for e in epochs], label=label)
    return int(epochs[0])


# ----------------------------------------------------------------------
# re-shard: a W-rank bundle's global arrays sliced into W' contiguous
# row blocks

def reshard_offsets(local_rows: int, label: str = "elastic_reshard"
                    ) -> Tuple[int, int]:
    """(row offset, total rows) of this rank's contiguous block in the
    new world's global row order — an allgather of every rank's local
    row count (the re-shard collective; delegates to
    `guarded_allgather` so it carries the fault site and the watchdog
    bracket). Degenerates to (0, local_rows) on one process."""
    import jax
    from ..parallel.comm import guarded_allgather
    counts = np.asarray(guarded_allgather(
        np.asarray([int(local_rows)], dtype=np.int64),
        label=label)).reshape(-1)
    rank = jax.process_index()
    return int(counts[:rank].sum()), int(counts.sum())


def reshard_slice(arrays: Dict[str, np.ndarray], offset: int,
                  local_rows: int, total_rows: int
                  ) -> Dict[str, np.ndarray]:
    """Slice this rank's contiguous row block out of globally
    concatenated checkpoint arrays: every array whose leading dimension
    equals `total_rows` is row-partitioned state (train_score,
    bag_mask); everything else (rng_key — identical on all ranks) is
    passed through."""
    out: Dict[str, np.ndarray] = {}
    for key, val in arrays.items():
        a = np.asarray(val)
        if key != "rng_key" and a.ndim and a.shape[0] == int(total_rows):
            out[key] = a[int(offset):int(offset) + int(local_rows)]
        else:
            out[key] = a
    return out


# ----------------------------------------------------------------------
# membership files: the heartbeat directory as the shared medium

def _write_json_atomic(path: str, obj: Dict) -> None:
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _listdir(path: str) -> List[str]:
    try:
        return os.listdir(path)
    except (FileNotFoundError, NotADirectoryError):
        return []


@dataclass(frozen=True)
class MembershipRecord:
    """One committed epoch cut: who the new world is and where it
    resumes. `members` are OLD-world ranks in ascending order — a
    survivor's new rank is its index in that list; admitted joiners
    take the ranks after the survivors."""
    epoch: int
    world: int
    members: Tuple[int, ...]
    joiners: Tuple[str, ...] = ()
    reason: str = ""
    resume_bundle: str = ""

    def new_rank(self, old_rank: int) -> Optional[int]:
        try:
            return self.members.index(int(old_rank))
        except ValueError:
            return None


def _member_path(heartbeat_dir: str, epoch: int) -> str:
    return os.path.join(heartbeat_dir, f"{_MEMBER_PREFIX}{epoch:04d}.json")


def _proposal_path(heartbeat_dir: str, epoch: int, rank: int) -> str:
    return os.path.join(
        heartbeat_dir, f"{_PROPOSAL_PREFIX}{epoch:04d}_rank_{rank:03d}.json")


def load_membership(heartbeat_dir: str,
                    epoch: Optional[int] = None
                    ) -> Optional[MembershipRecord]:
    """The committed membership record for `epoch`, or the latest one
    when `epoch` is None; None when nothing has been committed."""
    best: Optional[Tuple[int, Dict]] = None
    for name in _listdir(heartbeat_dir):
        if not (name.startswith(_MEMBER_PREFIX) and name.endswith(".json")):
            continue
        try:
            ep = int(name[len(_MEMBER_PREFIX):-len(".json")])
        except ValueError:
            continue
        if epoch is not None and ep != int(epoch):
            continue
        rec = _read_json(os.path.join(heartbeat_dir, name))
        if rec is None:
            continue
        if best is None or ep > best[0]:
            best = (ep, rec)
    if best is None:
        return None
    ep, rec = best
    return MembershipRecord(
        epoch=int(rec.get("epoch", ep)),
        world=int(rec.get("world", 0)),
        members=tuple(int(m) for m in rec.get("members", ())),
        joiners=tuple(str(j) for j in rec.get("joiners", ())),
        reason=str(rec.get("reason", "")),
        resume_bundle=str(rec.get("resume_bundle", "")))


def request_join(heartbeat_dir: str, token: str,
                 now: Optional[float] = None) -> str:
    """Park a prospective rank on the heartbeat directory. The file is
    a standing request: it is folded into the member plan at the next
    epoch cut (shrink OR an explicit cycle-boundary resize) and removed
    by the supervisor once the joiner has been launched."""
    os.makedirs(heartbeat_dir, exist_ok=True)
    path = os.path.join(heartbeat_dir, f"{_JOIN_PREFIX}{token}.json")
    _write_json_atomic(path, {
        "token": str(token),
        "stamp": float(time.time() if now is None else now)})
    return path


def list_joiners(heartbeat_dir: str) -> List[str]:
    """Tokens of every parked join request, sorted (deterministic rank
    assignment: joiners take new ranks after the survivors, in token
    order)."""
    out = []
    for name in _listdir(heartbeat_dir):
        if name.startswith(_JOIN_PREFIX) and name.endswith(".json"):
            out.append(name[len(_JOIN_PREFIX):-len(".json")])
    return sorted(out)


def sweep_stale_epoch_files(heartbeat_dir: str, epoch: int,
                            world: int) -> None:
    """Restart hygiene (watchdog re-arm): a reincarnated W'-rank world
    inherits the heartbeat directory of the W-rank world it shrank
    from. Heartbeats of ranks that no longer exist would age into
    permanent "rank k last seen Ns ago" culprits, and consumed shrink
    proposals from committed epochs would confuse the next vote — both
    are swept. Committed membership records are kept: they are the
    durable history a late supervisor reads. Idempotent and safe to run
    from every rank (ENOENT races are benign)."""
    for name in _listdir(heartbeat_dir):
        path = os.path.join(heartbeat_dir, name)
        doomed = False
        if name.startswith(_HB_PREFIX):
            try:
                doomed = int(name[len(_HB_PREFIX):]) >= int(world)
            except ValueError:
                doomed = name.endswith(".tmp") or ".tmp-" in name
        elif name.startswith(_PROPOSAL_PREFIX) and name.endswith(".json"):
            try:
                ep = int(name[len(_PROPOSAL_PREFIX):].split("_", 1)[0])
            except ValueError:
                continue
            doomed = ep <= int(epoch)
        if doomed:
            try:
                os.unlink(path)
            except OSError:
                pass


# ----------------------------------------------------------------------
# the shrink vote

def plan_resize(heartbeat_dir: str, rank: int, world: int, *,
                stale_after_s: float, now: float
                ) -> Tuple[List[int], List[int], List[str]]:
    """(survivors, dead, joiners) from the heartbeat directory — the
    same stale/missing diagnosis `CollectiveGuard.diagnose` prints,
    turned into a member plan. This rank is always a survivor (it is
    alive enough to be voting)."""
    from ..reliability.watchdog import read_heartbeats
    stamps = read_heartbeats(heartbeat_dir)
    survivors: List[int] = []
    dead: List[int] = []
    for r in range(int(world)):
        if r == int(rank):
            survivors.append(r)
        elif r in stamps and (now - stamps[r]) <= stale_after_s:
            survivors.append(r)
        else:
            dead.append(r)
    return survivors, dead, list_joiners(heartbeat_dir)


def propose_shrink(heartbeat_dir: str, *, rank: int, world: int,
                   epoch: int, min_world: int = 1,
                   timeout_s: float = 30.0,
                   stale_after_s: float = 3.0, reason: str = "",
                   resume_bundle: str = "",
                   wall: Callable[[], float] = time.time,
                   sleep: Callable[[float], None] = time.sleep
                   ) -> Optional[MembershipRecord]:
    """The resize entry point (FAULT001 site ``elastic_resize``): vote
    a shrink through the heartbeat directory and return the committed
    `MembershipRecord`, or None when the vote cannot succeed — the
    caller (the watchdog abort path) then falls back to the plain
    abort, so a failed resize is never worse than today's behavior.

    Every fresh survivor writes a proposal naming the members it
    observed; when all survivor proposals agree, the lowest surviving
    rank commits the membership record and everyone else verifies it.
    Returns None when: no rank is actually dead (all heartbeats fresh —
    a wedged interconnect, not a membership failure), the surviving
    world would drop below `min_world`, the survivor sets disagree, or
    the vote times out."""
    from ..observability.flightrec import recorder
    from ..observability.registry import registry
    from ..reliability import faults
    faults.inject("elastic_resize")
    now = wall()
    survivors, dead, joiners = plan_resize(
        heartbeat_dir, rank, world, stale_after_s=stale_after_s, now=now)
    if not dead:
        Log.warning("elastic resize: no stale peer heartbeat — not a "
                    "membership failure; falling back to abort")
        return None
    new_world = len(survivors) + len(joiners)
    if new_world < int(min_world):
        Log.warning(
            "elastic resize: surviving world %d (+%d joiners) is below "
            "elastic_min_world=%d; falling back to abort",
            len(survivors), len(joiners), min_world)
        return None
    new_epoch = int(epoch) + 1
    recorder.record("resize", "propose", epoch=new_epoch, rank=int(rank),
                    members=survivors, dead=dead, joiners=joiners)
    _write_json_atomic(_proposal_path(heartbeat_dir, new_epoch, rank), {
        "epoch": new_epoch, "from_rank": int(rank), "old_world": int(world),
        "members": survivors, "joiners": joiners, "stamp": now})
    deadline = now + float(timeout_s)
    committed: Optional[MembershipRecord] = None
    while True:
        committed = load_membership(heartbeat_dir, epoch=new_epoch)
        if committed is not None:
            break
        plans = {}
        for r in survivors:
            prop = _read_json(_proposal_path(heartbeat_dir, new_epoch, r))
            if prop is not None:
                plans[r] = (tuple(int(m) for m in prop.get("members", ())),
                            tuple(str(j) for j in prop.get("joiners", ())))
        if len(plans) == len(survivors):
            if len(set(plans.values())) != 1:
                Log.warning("elastic resize: survivor proposals disagree "
                            "(%r); falling back to abort", plans)
                return None
            if int(rank) == min(survivors):
                committed = MembershipRecord(
                    epoch=new_epoch, world=new_world,
                    members=tuple(survivors), joiners=tuple(joiners),
                    reason=str(reason)[:300],
                    resume_bundle=str(resume_bundle))
                _write_json_atomic(
                    _member_path(heartbeat_dir, new_epoch),
                    asdict(committed))
                break
        if wall() >= deadline:
            Log.warning("elastic resize: vote for epoch %d timed out "
                        "after %.1fs (%d/%d proposals); falling back to "
                        "abort", new_epoch, timeout_s, len(plans),
                        len(survivors))
            return None
        sleep(0.05)
    registry.record_membership_resize(
        "shrink", committed.epoch, committed.world,
        joined=len(committed.joiners))
    recorder.record("resize", "commit", epoch=committed.epoch,
                    world=committed.world, members=list(committed.members),
                    joiners=list(committed.joiners),
                    resume_bundle=committed.resume_bundle)
    Log.warning(
        "elastic resize: epoch %d committed — world %d -> %d, members "
        "%s%s; exiting for reincarnation (exit %d)",
        committed.epoch, world, committed.world, list(committed.members),
        f" + joiners {list(committed.joiners)}" if committed.joiners
        else "", ELASTIC_RESIZE_EXIT_CODE)
    return committed
