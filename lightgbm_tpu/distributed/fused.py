"""Row-sharded fused multi-tree training: K sharded boosting iterations
per device dispatch.

`boosting/fused.py` keeps the whole boosting loop on device as a
`lax.scan` — but only for the serial MXU learner. This module is the
same reformulation for the distributed crossbar's data-parallel row:
the scan body runs INSIDE `shard_map`, so every iteration's gradients,
bagging mask, sharded tree growth (with its reduce-scatter/psum
histogram merge collectives) and score update happen on the row shard,
and the host sees one dispatch per K trees. This is what lets the
PR-5 pipelined executor double-buffer multi-device training unchanged:
`GBDT.train_many_dispatch` calls the builder's `run` through the exact
signature the serial fused path uses.

Parity contract: gradients are elementwise, the bagging mask is the
identical global draw every shard recomputes and slices, and
`grow_tree` under the exact reduce-scatter flavor is byte-identical to
serial — the per-iteration sharded path (fused_block_size=1)
reproduces serial `train_one_iter` calls bit-for-bit when rows divide
the mesh, and the byte-parity oracles run there. The fused block
itself is DETERMINISTIC (same model for every block size / pipeline
setting — what chaos resume replays), but may differ from the
per-iteration path by 1-ulp score rounding: with the whole loop in one
program, the XLA CPU backend contracts the shrinkage multiply into the
score add (an FMA, one rounding instead of two). `optimization_barrier`
is expanded away before fusion on CPU, and neither bitcast roundtrips,
`reduce_precision`, nor --xla_allow_excess_precision=false defeat the
LLVM-level contraction — so the engine's b=1 bit-parity note
(engine.py) carries this documented exception for the sharded path.

Elasticity note (distributed/elastic.py): this builder closes over a
FIXED world — `comm.num_devices`, the row pad, and the feature-shard
transpose (`bins_ft`) are all sized for the mesh at build time. A
membership resize therefore never mutates a live builder; the
reincarnated process rebuilds the whole stack (crossbar re-resolve →
`build_feature_shards` → this builder) at the new world, and the epoch
stamped on every guarded gather rejects any straggler still running a
builder from the old membership.

Objective handling: the built-in objectives close over [N] row state
(label / weight / trans_label / y_signed / ...). Baking those into the
scan as replicated constants would defeat the sharding, so every 1-D
[num_data] attribute of the objective is collected at build time,
padded, row-sharded, and rebound onto a shallow copy of the objective
inside the device function — `get_gradients` then computes on blocks.
"""

from __future__ import annotations

import copy
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.learner import shard_map

__all__ = ["build_sharded_fused_train", "objective_row_state"]


def objective_row_state(objective, num_data: int):
    """(names, arrays): every 1-D [num_data] array attribute of the
    objective — the per-row state `get_gradients` reads (label, weight,
    trans_label, y_signed, label_weight, ...). Sorted by name so the
    argument order is deterministic across builds."""
    names, arrays = [], []
    for name in sorted(vars(objective)):
        val = vars(objective)[name]
        if val is None or not hasattr(val, "ndim"):
            continue
        if getattr(val, "ndim", 0) == 1 and val.shape[0] == num_data:
            names.append(name)
            arrays.append(jnp.asarray(val))
    return names, arrays


def build_sharded_fused_train(*, mesh, comm, objective, bins,
                              bins_ft: Optional[jax.Array], num_data: int,
                              row_pad: int, feature_mask_fn, num_bins,
                              missing_is_nan, is_cat, grow_kwargs: dict,
                              shrinkage: float, extra_seed: int,
                              needs_rng: bool, bagging: Optional[dict]
                              = None):
    """Return run(score, it0, *, k, sample_keys=None) ->
    (score'[:num_data], stacked TreeArrays) — the serial
    `build_fused_train` contract, over the row-sharded mesh.

    `bins` is the already-sharded [N_pad, F] binned matrix (P(axis)),
    `bins_ft` the optional feature-shard transpose from
    `hist_agg.build_feature_shards` (P(None, axis)); `grow_kwargs` are
    the static portable-grower settings (the same ones
    `parallel.learner.make_sharded_grower` bakes). `bagging` (None =
    no sampling) carries {freq, seed, fraction, pos_fraction,
    neg_fraction, use_posneg}: the mask is the stateless global draw of
    `gbdt._bagging`, recomputed replicated in-shard and sliced to the
    block, so the fused and per-iteration paths consume identical
    masks. GOSS is not eligible here (its top-k threshold is global;
    the caller gates it out)."""
    from ..learner.grower import grow_tree

    axis = comm.axis
    n_pad = num_data + row_pad
    shrink = jnp.float32(shrinkage)
    row_names, row_arrays = objective_row_state(objective, num_data)
    row_sharded = tuple(jnp.pad(a, (0, row_pad)) for a in row_arrays)
    valid = jnp.pad(jnp.ones(num_data, jnp.float32), (0, row_pad))
    with_ft = bins_ft is not None

    if bagging is not None:
        bag_freq = int(bagging["freq"])
        bag_seed = int(bagging["seed"])
        bag_frac = float(bagging["fraction"])
        bag_pos = float(bagging["pos_fraction"])
        bag_neg = float(bagging["neg_fraction"])
        bag_posneg = bool(bagging["use_posneg"])

    def _bag_mask(it, label_blk, off, nl):
        # the mask the per-iteration path STORED at the last resample
        # boundary (gbdt._bagging), recomputed statelessly: the full
        # [num_data] draw is replicated (every shard draws identically)
        # and sliced to this shard's rows; padded rows draw u=1.0 and
        # can never enter the bag
        it_rs = it - it % bag_freq
        k2 = jax.random.fold_in(jax.random.PRNGKey(bag_seed), it_rs)
        u = jnp.pad(jax.random.uniform(k2, (num_data,)), (0, row_pad),
                    constant_values=1.0)
        u_blk = jax.lax.dynamic_slice_in_dim(u, off, nl)
        if bag_posneg:
            frac = jnp.where(label_blk > 0, bag_pos, bag_neg)
        else:
            frac = bag_frac
        return (u_blk < frac).astype(jnp.float32)

    in_specs = (P(axis), P(), P(axis)) + (P(axis),) * len(row_sharded) \
        + (P(), P(), P())
    if with_ft:
        in_specs += (P(None, axis),)
    in_specs += (P(axis, None),)

    @functools.partial(shard_map, mesh=mesh, in_specs=in_specs,
                       out_specs=(P(axis), P()), check_vma=False)
    def device_run(score, its, valid_blk, *rest):
        rest = list(rest)
        row_blks = [rest.pop(0) for _ in row_names]
        nb, minan, isc = rest.pop(0), rest.pop(0), rest.pop(0)
        bins_ft_blk = rest.pop(0) if with_ft else None
        bins_blk = rest.pop(0)
        nl = score.shape[0]
        off = jax.lax.axis_index(axis) * nl
        obj = copy.copy(objective)
        for name, blk in zip(row_names, row_blks):
            setattr(obj, name, blk)
        label_blk = getattr(obj, "label", None)

        def body(carry, it):
            grad, hess = obj.get_gradients(carry)
            grad = grad * valid_blk
            hess = hess * valid_blk
            if bagging is not None:
                mask = _bag_mask(it, label_blk, off, nl)
                grad, hess, cnt = grad * mask, hess * mask, mask
            else:
                cnt = valid_blk
            fmask = feature_mask_fn(it)
            rng = jax.random.fold_in(
                jax.random.PRNGKey(extra_seed), it) if needs_rng else None
            tree, row_node = grow_tree(
                bins_blk, grad, hess, cnt, fmask, nb, minan, isc,
                rng_key=rng, comm=comm, bins_ft=bins_ft_blk,
                **grow_kwargs)
            # ok-zeroing + shrinkage in-scan (train_one_iter's "no
            # further splits" handling, like the serial fused body).
            # The score add below may round 1 ulp off the per-iteration
            # path: in one program the backend contracts this multiply
            # into the add (FMA) — see the module docstring. The trees
            # themselves (emitted leaf values) are exact; only the
            # in-scan score carry sees the contracted rounding.
            ok = (tree.num_leaves > 1).astype(jnp.float32)
            lv = tree.leaf_value * (shrink * ok)
            tree = tree._replace(leaf_value=lv)
            return carry + lv[row_node], tree

        return jax.lax.scan(body, score, its)

    jit_run = jax.jit(device_run)
    data_sh = NamedSharding(mesh, P(axis))

    def run(score, it0, *, k: int, sample_keys=None):
        # sample_keys belongs to the GOSS contract of the serial fused
        # path; the eligibility gate keeps GOSS off this builder
        del sample_keys
        its = jnp.asarray(it0, jnp.int32) + jnp.arange(k, dtype=jnp.int32)
        if row_pad:
            score = jnp.pad(score, (0, row_pad))
        score = jax.device_put(score, data_sh)
        args = (score, its, jax.device_put(valid, data_sh))
        args += tuple(jax.device_put(a, data_sh) for a in row_sharded)
        args += (num_bins, missing_is_nan, is_cat)
        if with_ft:
            args += (bins_ft,)
        args += (bins,)
        with mesh:
            out_score, stacked = jit_run(*args)
        return out_score[:num_data], stacked

    return run
