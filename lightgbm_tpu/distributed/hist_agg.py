"""Reduce-scatter histogram aggregation (distributed/hist_agg.py).

The seed data-parallel learner merged histograms with a full `psum`:
every device materializes the whole [S, F, B, 3] global histogram and
scans every feature — the reference's plain Allreduce fallback. The
reference's real algorithm (data_parallel_tree_learner.cpp:184-233) is
a Reduce-Scatter: device d ends up owning only its feature block of the
global histogram, scans just that block for its best local split, and a
small [S, world] allgather + max-gain merge picks the winners. Memory
per device drops from O(S*F*B) to O(S*F*B / world) and the wire moves
each histogram byte once instead of world times (memory-efficient array
redistribution, arXiv:2112.01075).

Two flavors, both funneled through this module:

- **exact** (`build_feature_shards` + the `bins_ft` argument of
  `learner/grower.py::grow_tree`): a one-time all_to_all transposes the
  row-sharded binned matrix into per-device column blocks
  [N_global, F/world]. Each device then builds the histogram of ALL
  rows for ITS features — the identical scatter-adds the serial learner
  performs, restricted to a column block — so per-feature histograms,
  split gains and therefore the grown tree are byte-identical to the
  serial learner (the parity oracles in
  tests/test_distributed_learner.py). Device memory for the transpose
  equals the row shard it already holds.
- **scatter** (`reduce_scatter_hist`): a `psum_scatter` over per-device
  partial histograms. No transpose and no [N_global] gathers, but the
  blocked summation order differs from the serial accumulation, so it
  is numerically (not bitwise) equivalent — the fallback when the
  transpose is unavailable.

Fault/observability contract: the host entry point
(`build_feature_shards`) carries the `distributed_hist_agg` fault site
and a collective-watchdog bracket; `reduce_scatter_hist` is traced code
whose site fires at the growth dispatch boundary (gbdt._grow), like the
other device collectives (COLL004/FAULT001/OBS001 manifests).
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.comm import CommSpec
from ..parallel.learner import shard_map

__all__ = ["check_hist_agg_fault", "build_feature_shards",
           "reduce_scatter_hist", "feature_shard_width"]


def check_hist_agg_fault() -> None:
    """Host-side injection hook for the `distributed_hist_agg` fault
    site — fired before the all_to_all feature-shard transpose is
    dispatched (the collective itself is traced; a Python raise inside
    it would bake into the compiled program)."""
    from ..reliability import faults
    faults.inject("distributed_hist_agg")


def feature_shard_width(num_features: int, num_devices: int) -> int:
    """Features per device under the contiguous-block ownership map
    (device d owns [d*Fp, (d+1)*Fp); trailing devices may own only
    padding when F < world * ceil(F/world))."""
    return -(-num_features // max(1, num_devices))


def build_feature_shards(mesh: Mesh, comm: CommSpec,
                         bins: jax.Array) -> jax.Array:
    """One-time all_to_all transpose of the row-sharded binned matrix
    into per-device feature blocks: device d receives [N_global, Fp]
    holding ALL rows of its contiguous feature block (zero-padded to
    Fp * world columns). Runs once at `_setup_parallel`; every tree
    then histograms its own block with the serial scatter-add order,
    which is what makes the reduce-scatter path byte-exact.

    Wrapped in the `distributed_hist_agg` fault site and a
    collective-watchdog bracket, like every other host-boundary
    collective (parallel/comm.py::guarded_allgather)."""
    from ..reliability.watchdog import collective_guard

    check_hist_agg_fault()
    axis = comm.axis
    world = comm.num_devices
    f = bins.shape[1]
    fp = feature_shard_width(f, world)
    fpad = fp * world

    @functools.partial(shard_map, mesh=mesh, in_specs=(P(axis, None),),
                       out_specs=P(None, axis), check_vma=False)
    def _transpose(blk):
        # pad features INSIDE the device fn so the wire moves exactly
        # fp columns per peer; padded columns are all-zero (bin 0) and
        # are masked out of the scan by the padded slot_fmask
        blk = jnp.pad(blk, ((0, 0), (0, fpad - f)))
        return jax.lax.all_to_all(blk, axis, split_axis=1, concat_axis=0,
                                  tiled=True)

    t0 = time.perf_counter()
    with collective_guard("distributed_hist_agg"):
        bins_ft = jax.jit(_transpose)(bins)
        bins_ft.block_until_ready()
    _record_setup(world, fp, time.perf_counter() - t0)
    return bins_ft


def reduce_scatter_hist(hist: jax.Array, axis: str) -> jax.Array:
    """psum_scatter the per-device partial histograms over the feature
    dimension: input [S, Fpad, B, 3] partials, output [S, Fp, B, 3] —
    this device's fully-summed feature block of the global histogram
    (the scatter flavor; blocked sums, numerically-but-not-bitwise
    equal to the serial accumulation). Traced code: its fault site
    (`collective_psum`) fires at the growth dispatch boundary
    (gbdt._grow), like grow_tree's other collectives."""
    return jax.lax.psum_scatter(hist, axis, scatter_dimension=1,
                                tiled=True)


def _record_setup(world: int, fp: int, wall_seconds: float) -> None:
    """Feed the lightgbm_tpu_distributed metric family; never raises —
    telemetry must not fail the setup collective that carried it. When
    this transpose runs in a reincarnated world (membership epoch > 0)
    the wall is ALSO the feature-shard rebuild half of the resize cost,
    so it folds into lightgbm_tpu_membership reshard_wall_s alongside
    the row reshard the checkpoint loader timed."""
    try:
        from ..observability.registry import registry
        registry.record_distributed_setup(world, fp, wall_seconds)
        from .elastic import current_epoch
        if current_epoch() > 0:
            registry.record_membership_reshard(wall_seconds)
    except Exception:       # pragma: no cover - telemetry only
        pass
