"""Exclusive Feature Bundling, TPU-native (reference feature_group.h:25,
docs/Features.rst:36 "Optimal Split for Exclusive Feature Bundling",
dataset.cpp FindGroups/FastFeatureBundling).

Wide sparse data makes the histogram kernels pay per-feature lane padding:
a 3-bin one-hot still occupies a full 128-lane dot on the MXU, so 1000
mostly-exclusive features cost ~1000 padded columns. Bundling packs
mutually-exclusive features into shared uint8 columns (one feature's
non-default bins after another), so the histogram stage runs on
``[S, Fb, Bb]`` with Fb ≪ F — the flop/bandwidth win — and the rest of
the learner is unchanged by construction:

- the bundled histogram is EXPANDED on device back to per-original-feature
  histograms (``expand_histograms``): positions map by a static gather;
  each feature's default-bin mass is reconstructed as
  ``node_total - segment_sum`` (rows not active in a feature sit outside
  its segment). With conflict rate 0 the expansion equals the unbundled
  histogram exactly, so the existing split scan (gain forms, missing
  handling, monotone, CEGB, sampling masks) runs verbatim on original
  features;
- routing translates a chosen (original feature, threshold) into bundle
  space with static tables (segment range + local-bin lookup), keeping
  bin semantics identical (``route_bins``);
- the model/host boundary never sees bundles: trees store original
  features and thresholds.

The reference instead scans the bundled histogram per sub-feature range
(feature_histogram.hpp offsets); the expansion design was chosen so one
scan implementation serves bundled and unbundled data bit-identically.

Single-feature bundles keep their identity mapping (column == original
feature column, default bin at its original position), so dense features
pay nothing.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from .utils.log import Log

__all__ = ["EfbPlan", "build_plan", "bundle_matrix", "make_device_tables",
           "expand_histograms", "route_bins"]


class EfbPlan(NamedTuple):
    """Host-side bundling plan over USED-feature indices."""
    bundles: List[List[int]]        # per column: used-feature indices
    col_of_feat: np.ndarray         # [F] bundle column of each feature
    seg_lo: np.ndarray              # [F] first bundle-bin of f's segment
    seg_hi: np.ndarray              # [F] last bundle-bin of f's segment
    is_multi: np.ndarray            # [F] True when f shares its column
    pos_of_local: np.ndarray        # [F, bmax] bundle-bin of local bin b
    #                                 (-1: reconstructed default, -2: pad)
    local_of_pos: np.ndarray        # [Fb, Bb] local bin at column position
    col_bins: np.ndarray            # [Fb] bins used per column
    num_cols: int
    bundle_bmax: int                # Bb (max bins over columns)

    @property
    def effective(self) -> bool:
        return bool(np.any(self.is_multi))


def build_plan(bins: np.ndarray, num_bins: np.ndarray,
               default_bins: np.ndarray, is_categorical: np.ndarray,
               *, max_bundle_bins: int = 256, sample_rows: int = 20000,
               max_conflict_frac: float = 0.0,
               min_sparsity: float = 0.8) -> Optional[EfbPlan]:
    """Greedy conflict-bounded bundling (reference dataset.cpp FindGroups:
    features in decreasing non-default count order join the first bundle
    whose occupied-row overlap stays within budget and whose bin total
    fits). Returns None when nothing bundles (narrow or dense data).

    Only sufficiently sparse numeric features are bundled; dense and
    categorical features keep identity columns.
    """
    n, f = bins.shape
    if f < 8:
        return None
    rs = np.random.RandomState(13)
    rows = np.arange(n) if n <= sample_rows else \
        np.sort(rs.choice(n, sample_rows, replace=False))
    sub = np.ascontiguousarray(bins[rows].T)            # [F, S] contiguous
    nondef = sub != default_bins[:, None]               # [F, S]
    nd_cnt = nondef.sum(axis=1)
    s = len(rows)

    can_bundle = (~is_categorical) & (nd_cnt <= (1.0 - min_sparsity) * s) \
        & (num_bins >= 2)
    budget = int(max_conflict_frac * s)

    order = np.argsort(nd_cnt, kind="stable")[::-1]     # dense-first
    occ: List[np.ndarray] = []                          # per multi-bundle
    bins_used: List[int] = []
    members: List[List[int]] = []
    singleton: List[int] = []
    for fi in order:
        fi = int(fi)
        if not can_bundle[fi]:
            singleton.append(fi)
            continue
        need = int(num_bins[fi]) - 1                    # non-default bins
        placed = False
        for b in range(len(occ)):
            if bins_used[b] + need > max_bundle_bins:
                continue
            if int(np.count_nonzero(occ[b] & nondef[fi])) <= budget:
                members[b].append(fi)
                occ[b] |= nondef[fi]
                bins_used[b] += need
                placed = True
                break
        if not placed:
            members.append([fi])
            occ.append(nondef[fi].copy())
            bins_used.append(1 + need)
    # bundles that stayed alone revert to identity columns
    for b in range(len(members) - 1, -1, -1):
        if len(members[b]) == 1:
            singleton.append(members[b][0])
            del members[b], occ[b], bins_used[b]
    if not members:
        return None

    bundles = [sorted(m) for m in members] + [[fi] for fi in
                                              sorted(singleton)]
    bmax = int(num_bins.max())
    col_of_feat = np.zeros(f, np.int32)
    seg_lo = np.zeros(f, np.int32)
    seg_hi = np.zeros(f, np.int32)
    is_multi = np.zeros(f, bool)
    pos_of_local = np.full((f, bmax), -2, np.int32)
    col_bins = np.zeros(len(bundles), np.int32)
    for g, feats in enumerate(bundles):
        multi = len(feats) > 1
        pos = 1 if multi else 0                         # pos 0 = default
        for fi in feats:
            col_of_feat[fi] = g
            is_multi[fi] = multi
            nb = int(num_bins[fi])
            if multi:
                seg_lo[fi] = pos
                for b in range(nb):
                    if b == int(default_bins[fi]):
                        pos_of_local[fi, b] = -1        # reconstructed
                    else:
                        pos_of_local[fi, b] = pos
                        pos += 1
                seg_hi[fi] = pos - 1
            else:
                seg_lo[fi] = 0
                seg_hi[fi] = nb - 1
                pos_of_local[fi, :nb] = np.arange(nb)
                pos = nb
        col_bins[g] = pos
    bb = int(col_bins.max())
    local_of_pos = np.zeros((len(bundles), bb), np.int32)
    for g, feats in enumerate(bundles):
        for fi in feats:
            for b in range(int(num_bins[fi])):
                p = pos_of_local[fi, b]
                if p >= 0:
                    local_of_pos[g, p] = b
    plan = EfbPlan(bundles, col_of_feat, seg_lo, seg_hi, is_multi,
                   pos_of_local, local_of_pos, col_bins, len(bundles), bb)
    Log.info("EFB: bundled %d features into %d columns (max %d bins)",
             f, plan.num_cols, bb)
    return plan


def bundle_matrix(bins: np.ndarray, plan: EfbPlan) -> np.ndarray:
    """Re-encode the [N, F] bin matrix as [N, Fb] bundle columns."""
    n = bins.shape[0]
    dtype = np.uint8 if plan.bundle_bmax <= 256 else np.uint16
    out = np.zeros((n, plan.num_cols), dtype)
    for g, feats in enumerate(plan.bundles):
        if len(feats) == 1 and not plan.is_multi[feats[0]]:
            out[:, g] = bins[:, feats[0]].astype(dtype)
            continue
        for fi in feats:
            col = bins[:, fi].astype(np.int64)
            pos = plan.pos_of_local[fi][col]            # [N]
            active = pos >= 0
            # conflicts (simultaneously active features) resolve to the
            # later feature, within the accepted conflict budget
            out[active, g] = pos[active].astype(dtype)
    return out


class EfbDev(NamedTuple):
    """Device-side static tables. All fields are arrays so the tuple
    rides through jit as a pytree; the static ints (Fb, Bb) are derived
    from shapes, which stay concrete under tracing.

    ``loc_table[f, p]`` is the COMPLETE routing story: the original local
    bin of feature f when its bundle column holds position p (default
    bin folded in for out-of-segment positions), so a row's bin on any
    feature is one flat gather."""
    col_of_feat: object             # [F] i32
    seg_lo: object                  # [F] i32
    seg_hi: object                  # [F] i32
    flat_pos: object                # [F, bmax] i32 gather index (clipped)
    is_default_pos: object          # [F, bmax] bool
    is_valid_pos: object            # [F, bmax] bool
    loc_table: object               # [F, Bb] i32
    num_cols_arr: object            # [Fb] placeholder carrying Fb shape

    @property
    def num_cols(self) -> int:
        return self.num_cols_arr.shape[0]

    @property
    def bundle_bmax(self) -> int:
        return self.loc_table.shape[1]


def make_device_tables(plan: EfbPlan, default_bins: np.ndarray) -> EfbDev:
    import jax.numpy as jnp
    f, bmax = plan.pos_of_local.shape
    bb = plan.bundle_bmax
    flat = plan.col_of_feat[:, None] * bb + np.clip(plan.pos_of_local, 0,
                                                    bb - 1)
    loc = np.empty((f, bb), np.int32)
    for fi in range(f):
        g = plan.col_of_feat[fi]
        p = np.arange(bb)
        in_seg = (p >= plan.seg_lo[fi]) & (p <= plan.seg_hi[fi])
        loc[fi] = np.where(in_seg, plan.local_of_pos[g],
                           default_bins[fi])
    return EfbDev(
        col_of_feat=jnp.asarray(plan.col_of_feat),
        seg_lo=jnp.asarray(plan.seg_lo),
        seg_hi=jnp.asarray(plan.seg_hi),
        flat_pos=jnp.asarray(flat.astype(np.int32)),
        is_default_pos=jnp.asarray(plan.pos_of_local == -1),
        is_valid_pos=jnp.asarray(plan.pos_of_local >= 0),
        loc_table=jnp.asarray(loc),
        num_cols_arr=jnp.zeros(plan.num_cols, jnp.int8))


def expand_histograms(hist_b, efb: EfbDev):
    """[S, Fb, Bb, C] bundled histograms -> [S, F, bmax, C] per original
    feature. Linear in the histogram, so it commutes with the
    data-parallel psum. Default-bin mass is node_total - segment_sum
    (exact up to the accepted conflict budget)."""
    import jax.numpy as jnp
    s, fb, bb, c = hist_b.shape
    flat = hist_b.reshape(s, fb * bb, c)
    gath = flat[:, efb.flat_pos]                        # [S, F, bmax, C]
    csum = jnp.cumsum(hist_b, axis=2)                   # [S, Fb, Bb, C]
    # every row lands in exactly one bin of every column, so any single
    # column's total is the node total
    total = jnp.sum(hist_b[:, 0], axis=1)               # [S, C]
    hi_s = csum[:, efb.col_of_feat, efb.seg_hi]         # [S, F, C]
    lo_gate = (efb.seg_lo > 0)[None, :, None]
    lo_s = csum[:, efb.col_of_feat,
                jnp.maximum(efb.seg_lo - 1, 0)] * lo_gate
    dmass = total[:, None] - (hi_s - lo_s)              # [S, F, C]
    out = jnp.where(efb.is_valid_pos[None, :, :, None], gath, 0.0)
    out = jnp.where(efb.is_default_pos[None, :, :, None],
                    dmass[:, :, None], out)
    return out


def route_bins(bins, pf, efb: EfbDev):
    """Per-row ORIGINAL-feature local bin for rows' split feature pf.

    bins: [N, Fb] bundled matrix; pf: [N] original feature id. The
    loc_table already folds in the default bin for out-of-segment
    positions (exclusivity)."""
    import jax.numpy as jnp
    g = efb.col_of_feat[pf]                             # [N]
    binv = jnp.take_along_axis(bins, g[:, None],
                               axis=1)[:, 0].astype(jnp.int32)
    return efb.loc_table.reshape(-1)[pf * efb.bundle_bmax + binv]
