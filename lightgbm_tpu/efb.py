"""Exclusive Feature Bundling, TPU-native (reference feature_group.h:25,
docs/Features.rst:36 "Optimal Split for Exclusive Feature Bundling",
dataset.cpp FindGroups/FastFeatureBundling).

Wide sparse data makes the histogram kernels pay per-feature lane padding:
a 3-bin one-hot still occupies a full 128-lane dot on the MXU, so 1000
mostly-exclusive features cost ~1000 padded columns. Bundling packs
mutually-exclusive features into shared uint8 columns (one feature's
non-default bins after another), so the histogram stage runs on
``[S, Fb, Bb]`` with Fb ≪ F — the flop/bandwidth win — and the rest of
the learner is unchanged by construction:

- the bundled histogram is EXPANDED on device back to per-original-feature
  histograms (``expand_histograms``): positions map by a static gather;
  each feature's default-bin mass is reconstructed as
  ``node_total - segment_sum`` (rows not active in a feature sit outside
  its segment). With conflict rate 0 the expansion equals the unbundled
  histogram exactly, so the existing split scan (gain forms, missing
  handling, monotone, CEGB, sampling masks) runs verbatim on original
  features;
- routing translates a chosen (original feature, threshold) into bundle
  space with static tables (segment range + local-bin lookup), keeping
  bin semantics identical (``route_bins``);
- the model/host boundary never sees bundles: trees store original
  features and thresholds.

The reference instead scans the bundled histogram per sub-feature range
(feature_histogram.hpp offsets); the expansion design was chosen so one
scan implementation serves bundled and unbundled data bit-identically.

Single-feature bundles keep their identity mapping (column == original
feature column, default bin at its original position), so dense features
pay nothing.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from .utils.log import Log

__all__ = ["EfbPlan", "build_plan", "bundle_matrix", "make_device_tables",
           "expand_histograms", "route_bins"]


class EfbPlan(NamedTuple):
    """Host-side bundling plan over USED-feature indices."""
    bundles: List[List[int]]        # per column: used-feature indices
    col_of_feat: np.ndarray         # [F] bundle column of each feature
    seg_lo: np.ndarray              # [F] first bundle-bin of f's segment
    seg_hi: np.ndarray              # [F] last bundle-bin of f's segment
    is_multi: np.ndarray            # [F] True when f shares its column
    pos_of_local: np.ndarray        # [F, bmax] bundle-bin of local bin b
    #                                 (-1: reconstructed default, -2: pad)
    local_of_pos: np.ndarray        # [Fb, Bb] local bin at column position
    col_bins: np.ndarray            # [Fb] bins used per column
    num_cols: int
    bundle_bmax: int                # Bb (max bins over columns)

    @property
    def effective(self) -> bool:
        return bool(np.any(self.is_multi))


def build_plan(bins: np.ndarray, num_bins: np.ndarray,
               default_bins: np.ndarray, is_categorical: np.ndarray,
               *, max_bundle_bins: int = 256, sample_rows: int = 20000,
               max_conflict_frac: float = 0.0,
               min_sparsity: float = 0.8) -> Optional[EfbPlan]:
    """Greedy conflict-bounded bundling (reference dataset.cpp FindGroups:
    features in decreasing non-default count order join the first bundle
    whose occupied-row overlap stays within budget and whose bin total
    fits). Returns None when nothing bundles (narrow or dense data).

    Only sufficiently sparse numeric features are bundled; dense and
    categorical features keep identity columns.
    """
    n, f = bins.shape
    if f < 8:
        return None
    rs = np.random.RandomState(13)
    rows = np.arange(n) if n <= sample_rows else \
        np.sort(rs.choice(n, sample_rows, replace=False))
    sub = np.ascontiguousarray(bins[rows].T)            # [F, S] contiguous
    nondef = sub != default_bins[:, None]               # [F, S]
    nd_cnt = nondef.sum(axis=1)
    s = len(rows)

    can_bundle = (~is_categorical) & (nd_cnt <= (1.0 - min_sparsity) * s) \
        & (num_bins >= 2)
    budget = int(max_conflict_frac * s)

    order = np.argsort(nd_cnt, kind="stable")[::-1]     # dense-first
    occ: List[np.ndarray] = []                          # per multi-bundle
    bins_used: List[int] = []
    members: List[List[int]] = []
    singleton: List[int] = []
    for fi in order:
        fi = int(fi)
        if not can_bundle[fi]:
            singleton.append(fi)
            continue
        need = int(num_bins[fi]) - 1                    # non-default bins
        placed = False
        for b in range(len(occ)):
            if bins_used[b] + need > max_bundle_bins:
                continue
            if int(np.count_nonzero(occ[b] & nondef[fi])) <= budget:
                members[b].append(fi)
                occ[b] |= nondef[fi]
                bins_used[b] += need
                placed = True
                break
        if not placed:
            members.append([fi])
            occ.append(nondef[fi].copy())
            bins_used.append(1 + need)
    # bundles that stayed alone revert to identity columns
    for b in range(len(members) - 1, -1, -1):
        if len(members[b]) == 1:
            singleton.append(members[b][0])
            del members[b], occ[b], bins_used[b]
    if not members:
        return None

    bundles = [sorted(m) for m in members] + [[fi] for fi in
                                              sorted(singleton)]
    bmax = int(num_bins.max())
    col_of_feat = np.zeros(f, np.int32)
    seg_lo = np.zeros(f, np.int32)
    seg_hi = np.zeros(f, np.int32)
    is_multi = np.zeros(f, bool)
    pos_of_local = np.full((f, bmax), -2, np.int32)
    col_bins = np.zeros(len(bundles), np.int32)
    for g, feats in enumerate(bundles):
        multi = len(feats) > 1
        pos = 1 if multi else 0                         # pos 0 = default
        for fi in feats:
            col_of_feat[fi] = g
            is_multi[fi] = multi
            nb = int(num_bins[fi])
            if multi:
                seg_lo[fi] = pos
                for b in range(nb):
                    if b == int(default_bins[fi]):
                        pos_of_local[fi, b] = -1        # reconstructed
                    else:
                        pos_of_local[fi, b] = pos
                        pos += 1
                seg_hi[fi] = pos - 1
            else:
                seg_lo[fi] = 0
                seg_hi[fi] = nb - 1
                pos_of_local[fi, :nb] = np.arange(nb)
                pos = nb
        col_bins[g] = pos
    bb = int(col_bins.max())
    local_of_pos = np.zeros((len(bundles), bb), np.int32)
    for g, feats in enumerate(bundles):
        for fi in feats:
            for b in range(int(num_bins[fi])):
                p = pos_of_local[fi, b]
                if p >= 0:
                    local_of_pos[g, p] = b
    plan = EfbPlan(bundles, col_of_feat, seg_lo, seg_hi, is_multi,
                   pos_of_local, local_of_pos, col_bins, len(bundles), bb)
    Log.info("EFB: bundled %d features into %d columns (max %d bins)",
             f, plan.num_cols, bb)
    return plan


def bundle_matrix(bins: np.ndarray, plan: EfbPlan) -> np.ndarray:
    """Re-encode the [N, F] bin matrix as [N, Fb] bundle columns."""
    n = bins.shape[0]
    dtype = np.uint8 if plan.bundle_bmax <= 256 else np.uint16
    out = np.zeros((n, plan.num_cols), dtype)
    for g, feats in enumerate(plan.bundles):
        if len(feats) == 1 and not plan.is_multi[feats[0]]:
            out[:, g] = bins[:, feats[0]].astype(dtype)
            continue
        for fi in feats:
            col = bins[:, fi].astype(np.int64)
            pos = plan.pos_of_local[fi][col]            # [N]
            active = pos >= 0
            # conflicts (simultaneously active features) resolve to the
            # later feature, within the accepted conflict budget
            out[active, g] = pos[active].astype(dtype)
    return out


class EfbScan(NamedTuple):
    """Static tables for the SEGMENTED bundle-space split scan
    (split_bundled.py) — the reference's per-sub-feature offset scan
    over the bundled histogram (feature_histogram.hpp offsets over
    feature_group.h ranges), reformulated positionally: every bundle
    position (g, p) hosts at most ONE numeric threshold candidate, and
    its left-side sums are two csum gathers plus the reconstructed
    default mass. Scan tensors stay [S, Fb, Bb] — no expansion.

    The candidate<->position bijection: feature f with nb bins has nb-1
    non-default positions and at most nb-1 valid thresholds; threshold
    t != default sits at its own position, and t == default (which has
    no position) is hosted by the position of local bin nb-1 (never a
    threshold itself)."""
    fid: object                     # [Fb, Bb] i32 original feature (-1 pad)
    cand_t: object                  # [Fb, Bb] i32 hosted threshold (-1)
    prefix_flat: object             # [Fb, Bb] i32 csum idx, -1 = empty
    incl_def: object                # [Fb, Bb] bool add default mass left
    seg_lo_m1_flat: object          # [Fb, Bb] i32 csum idx below segment
    seg_hi_flat: object             # [Fb, Bb] i32 csum idx at segment end
    is_multi_pos: object            # [Fb, Bb] bool feature shares column
    nan_flat: object                # [Fb, Bb] i32 NaN-bin hist idx
    #                                 (-1: NaN bin IS the default bin)
    has_nan_pos: object             # [Fb, Bb] bool feature has NaN bin
    cat_feats: object               # [Fc] i32 categorical feature ids
    # ---- bundle-RANGE routing tables (histogram_mxu._route_decide's
    # efb_range mode): a numeric split (f, t) becomes pure position
    # compares on the row's bundle bin — in-segment rows go left iff
    # pos <= pos_thresh[f, t], out-of-segment rows (the feature sits at
    # its default bin) go by db_left, the NaN position goes by
    # default_left. No per-row original-bin decode at all.
    pos_thresh: object              # [F, bmax] i32 last left pos per t
    db_le_t: object                 # [F, bmax] bool default bin <= t
    nan_is_default: object          # [F] bool NaN bin IS the default
    p_nan_f: object                 # [F] i32 NaN-bin position (-1 none)


class EfbDev(NamedTuple):
    """Device-side static tables. All fields are arrays so the tuple
    rides through jit as a pytree; the static ints (Fb, Bb) are derived
    from shapes, which stay concrete under tracing.

    ``loc_table[f, p]`` is the COMPLETE routing story: the original local
    bin of feature f when its bundle column holds position p (default
    bin folded in for out-of-segment positions), so a row's bin on any
    feature is one flat gather."""
    col_of_feat: object             # [F] i32
    seg_lo: object                  # [F] i32
    seg_hi: object                  # [F] i32
    flat_pos: object                # [F, bmax] i32 gather index (clipped)
    is_default_pos: object          # [F, bmax] bool
    is_valid_pos: object            # [F, bmax] bool
    loc_table: object               # [F, Bb] i32
    num_cols_arr: object            # [Fb] placeholder carrying Fb shape
    scan: object = None             # EfbScan | None (segmented split scan)

    @property
    def num_cols(self) -> int:
        return self.num_cols_arr.shape[0]

    @property
    def bundle_bmax(self) -> int:
        return self.loc_table.shape[1]


def _make_scan_tables(plan: EfbPlan, default_bins: np.ndarray,
                      num_bins: np.ndarray, missing_is_nan: np.ndarray,
                      is_cat: np.ndarray):
    """Host construction of the EfbScan position tables (see EfbScan)."""
    import jax.numpy as jnp
    fb, bb = plan.num_cols, plan.bundle_bmax
    fid = np.full((fb, bb), -1, np.int32)
    cand_t = np.full((fb, bb), -1, np.int32)
    prefix_flat = np.full((fb, bb), -1, np.int32)
    incl_def = np.zeros((fb, bb), bool)
    seg_lo_m1 = np.full((fb, bb), -1, np.int32)
    seg_hi_f = np.zeros((fb, bb), np.int32)
    is_multi_p = np.zeros((fb, bb), bool)
    nan_flat = np.full((fb, bb), -1, np.int32)
    has_nan_p = np.zeros((fb, bb), bool)
    f = plan.col_of_feat.shape[0]
    bmax = plan.pos_of_local.shape[1]
    pos_thresh = np.zeros((f, bmax), np.int32)
    db_le_t = np.zeros((f, bmax), bool)
    nan_is_def = np.zeros(f, bool)
    p_nan_arr = np.full(f, -1, np.int32)
    for fi in range(f):
        g = int(plan.col_of_feat[fi])
        nb = int(num_bins[fi])
        db = int(default_bins[fi])
        nan = bool(missing_is_nan[fi])
        # range-routing tables: last left-side position per threshold
        pp = int(plan.seg_lo[fi]) - 1
        for t in range(bmax):
            if t < nb and plan.pos_of_local[fi, t] >= 0:
                pp = int(plan.pos_of_local[fi, t])
            pos_thresh[fi, t] = pp
            db_le_t[fi, t] = db <= t
        if nan:
            pn = int(plan.pos_of_local[fi, nb - 1])
            p_nan_arr[fi] = pn
            nan_is_def[fi] = pn < 0
        # every position of fi gets its feature id + segment/nan info
        pos_list = [int(plan.pos_of_local[fi, b]) for b in range(nb)
                    if plan.pos_of_local[fi, b] >= 0]
        p_nan = int(plan.pos_of_local[fi, nb - 1]) if nan else -1
        for p in pos_list:
            fid[g, p] = fi
            seg_lo_m1[g, p] = g * bb + plan.seg_lo[fi] - 1 \
                if plan.seg_lo[fi] > 0 else -1
            seg_hi_f[g, p] = g * bb + plan.seg_hi[fi]
            is_multi_p[g, p] = bool(plan.is_multi[fi])
            has_nan_p[g, p] = nan
            nan_flat[g, p] = g * bb + p_nan if p_nan >= 0 else -1
        if is_cat[fi]:
            continue                    # cats go through the sub-scan
        t_lim = nb - 2 - (1 if nan else 0)
        for t in range(t_lim + 1):
            if t == db and plan.is_multi[fi]:
                continue                # hosted below
            p = int(plan.pos_of_local[fi, t])
            if p < 0:
                continue
            cand_t[g, p] = t
            prefix_flat[g, p] = g * bb + p
            incl_def[g, p] = bool(plan.is_multi[fi]) and db < t
        if plan.is_multi[fi] and db <= t_lim:
            # t == default has no position; host it on local nb-1's
            # position (never a threshold: nb-1 > t_lim always)
            p_host = int(plan.pos_of_local[fi, nb - 1])
            assert p_host >= 0, "default bin must differ from last local"
            cand_t[g, p_host] = db
            prefix_flat[g, p_host] = \
                g * bb + int(plan.pos_of_local[fi, db - 1]) if db > 0 \
                else -1
            incl_def[g, p_host] = True
    cat_feats = np.nonzero(np.asarray(is_cat))[0].astype(np.int32)
    return EfbScan(
        fid=jnp.asarray(fid), cand_t=jnp.asarray(cand_t),
        prefix_flat=jnp.asarray(prefix_flat),
        incl_def=jnp.asarray(incl_def),
        seg_lo_m1_flat=jnp.asarray(seg_lo_m1),
        seg_hi_flat=jnp.asarray(seg_hi_f),
        is_multi_pos=jnp.asarray(is_multi_p),
        nan_flat=jnp.asarray(nan_flat),
        has_nan_pos=jnp.asarray(has_nan_p),
        cat_feats=jnp.asarray(cat_feats),
        pos_thresh=jnp.asarray(pos_thresh),
        db_le_t=jnp.asarray(db_le_t),
        nan_is_default=jnp.asarray(nan_is_def),
        p_nan_f=jnp.asarray(p_nan_arr))


def make_device_tables(plan: EfbPlan, default_bins: np.ndarray,
                       num_bins: Optional[np.ndarray] = None,
                       missing_is_nan: Optional[np.ndarray] = None,
                       is_cat: Optional[np.ndarray] = None) -> EfbDev:
    """Build the device tables; when the feature metadata is supplied the
    segmented-scan tables (EfbScan) are attached too."""
    import jax.numpy as jnp
    f, bmax = plan.pos_of_local.shape
    bb = plan.bundle_bmax
    flat = plan.col_of_feat[:, None] * bb + np.clip(plan.pos_of_local, 0,
                                                    bb - 1)
    loc = np.empty((f, bb), np.int32)
    for fi in range(f):
        g = plan.col_of_feat[fi]
        p = np.arange(bb)
        in_seg = (p >= plan.seg_lo[fi]) & (p <= plan.seg_hi[fi])
        loc[fi] = np.where(in_seg, plan.local_of_pos[g],
                           default_bins[fi])
    scan = None
    if num_bins is not None and missing_is_nan is not None and \
            is_cat is not None:
        scan = _make_scan_tables(plan, default_bins, num_bins,
                                 missing_is_nan, is_cat)
    return EfbDev(
        col_of_feat=jnp.asarray(plan.col_of_feat),
        seg_lo=jnp.asarray(plan.seg_lo),
        seg_hi=jnp.asarray(plan.seg_hi),
        flat_pos=jnp.asarray(flat.astype(np.int32)),
        is_default_pos=jnp.asarray(plan.pos_of_local == -1),
        is_valid_pos=jnp.asarray(plan.pos_of_local >= 0),
        loc_table=jnp.asarray(loc),
        num_cols_arr=jnp.zeros(plan.num_cols, jnp.int8),
        scan=scan)


def expand_histograms(hist_b, efb: EfbDev):
    """[S, Fb, Bb, C] bundled histograms -> [S, F, bmax, C] per original
    feature. Linear in the histogram, so it commutes with the
    data-parallel psum. Default-bin mass is node_total - segment_sum
    (exact up to the accepted conflict budget)."""
    import jax.numpy as jnp
    s, fb, bb, c = hist_b.shape
    flat = hist_b.reshape(s, fb * bb, c)
    gath = flat[:, efb.flat_pos]                        # [S, F, bmax, C]
    csum = jnp.cumsum(hist_b, axis=2)                   # [S, Fb, Bb, C]
    # every row lands in exactly one bin of every column, so any single
    # column's total is the node total
    total = jnp.sum(hist_b[:, 0], axis=1)               # [S, C]
    hi_s = csum[:, efb.col_of_feat, efb.seg_hi]         # [S, F, C]
    lo_gate = (efb.seg_lo > 0)[None, :, None]
    lo_s = csum[:, efb.col_of_feat,
                jnp.maximum(efb.seg_lo - 1, 0)] * lo_gate
    dmass = total[:, None] - (hi_s - lo_s)              # [S, F, C]
    out = jnp.where(efb.is_valid_pos[None, :, :, None], gath, 0.0)
    out = jnp.where(efb.is_default_pos[None, :, :, None],
                    dmass[:, :, None], out)
    return out


def route_bins(bins, pf, efb: EfbDev):
    """Per-row ORIGINAL-feature local bin for rows' split feature pf.

    bins: [N, Fb] bundled matrix; pf: [N] original feature id. The
    loc_table already folds in the default bin for out-of-segment
    positions (exclusivity)."""
    import jax.numpy as jnp
    g = efb.col_of_feat[pf]                             # [N]
    binv = jnp.take_along_axis(bins, g[:, None],
                               axis=1)[:, 0].astype(jnp.int32)
    return efb.loc_table.reshape(-1)[pf * efb.bundle_bmax + binv]
