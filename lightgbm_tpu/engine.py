"""train() / cv() entry points (reference python-package/lightgbm/engine.py).

Same callback protocol and return types as the reference engine.py:27 train
and :393 cv, including early stopping via EarlyStopException and
`cv_agg` aggregated results.
"""

from __future__ import annotations

import collections
import copy
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset
from .config import PARAM_ALIASES
from .utils.log import Log

__all__ = ["train", "cv", "CVBooster"]


def _resolve_num_boost_round(params: Dict[str, Any],
                             num_boost_round: int) -> int:
    for alias in ("num_iterations", "num_iteration", "n_iter", "num_tree",
                  "num_trees", "num_round", "num_rounds", "nrounds",
                  "num_boost_round", "n_estimators", "max_iter"):
        if alias in params:
            return int(params.pop(alias))
    return num_boost_round


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj=None, feval=None, init_model=None,
          feature_name="auto", categorical_feature="auto",
          keep_training_booster: bool = False,
          callbacks: Optional[List] = None,
          resume_from: Optional[str] = None) -> Booster:
    params = copy.deepcopy(params or {})
    num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    from .streaming import ChunkSource
    if isinstance(train_set, ChunkSource):
        # out-of-core source handed straight to train(): wrap it so
        # Dataset.construct routes through the two-pass streaming loader
        train_set = Dataset(train_set, params=dict(params))
    if valid_sets is not None:
        vs = valid_sets if isinstance(valid_sets, list) else [valid_sets]
        valid_sets = [Dataset(v, reference=train_set, params=dict(params))
                      if isinstance(v, ChunkSource) else v for v in vs]
    resume_state = None
    if resume_from is not None:
        # kill-and-resume (docs/Reliability.md): restore the exact
        # training state from a checkpoint bundle. Unlike init_model
        # continuation below — which re-seeds init scores through a
        # host predict and restarts the RNG stream — resume restores
        # the checkpointed f32 scores / RNG / bagging state verbatim,
        # so the finished model is byte-identical to an uninterrupted
        # run. num_boost_round stays the TOTAL iteration count.
        if init_model is not None:
            raise ValueError("resume_from and init_model are exclusive: "
                             "a checkpoint bundle already carries its model")
        from .reliability.checkpoint import (load_checkpoint,
                                             load_checkpoint_resharded,
                                             bundle_world)
        # under multihost (setup_multihost ran before train, like the
        # reference CLI) each rank loads its own shard of a coordinated
        # bundle; world validation rejects topology changes — unless
        # elastic_resize is on, where a world mismatch is exactly the
        # reincarnation path: every rank reads ALL shards of the old
        # world's bundle and re-slices its own contiguous row block at
        # restore time (docs/Distributed.md Elasticity)
        import jax
        try:
            _world = jax.process_count()
        except RuntimeError:
            _world = 1
        _elastic = bool(params.get("elastic_resize", False))
        _bundle_world = bundle_world(resume_from) if _elastic else None
        if _bundle_world is not None and _bundle_world != _world:
            resume_state = load_checkpoint_resharded(resume_from)
        elif _world > 1:
            resume_state = load_checkpoint(
                resume_from, rank=jax.process_index(), world=_world)
        else:
            resume_state = load_checkpoint(resume_from)
        init_model = None
    if fobj is not None:
        params["objective"] = "none"
    first_metric_only = bool(params.get("first_metric_only", False))

    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    base_model = None
    if init_model is not None:
        # continued training (reference: input_model seeds init scores,
        # application.cpp:91-94; the final model keeps the old trees,
        # Python Booster(model_file=...) + train). Scores are seeded with
        # the base model's raw predictions BEFORE dataset construction
        # (raw features are still present), and the base trees are merged
        # into the final model so predict/save include them.
        #
        # Bounded-divergence caveat: when a PREVIOUS train() call ended
        # in a mid-block early stop (fused block path below), that
        # booster's train_score carries the rollback's add-then-subtract
        # ULP residue — at most one f32 rounding per rolled-back tree.
        # Continuing from it trains the first new trees against
        # gradients of those scores, so a continued model can diverge
        # from a never-stopped reference by that same bounded residue;
        # seeding here via base-model PREDICTIONS (recomputed, not the
        # stored train_score) keeps the divergence to the residue itself
        # rather than compounding it.
        base_model = init_model if isinstance(init_model, Booster) else \
            Booster(model_file=init_model)

        def _seed(ds):
            if ds is None:
                return
            existing = ds.init_score
            if existing is None and ds._binned is not None:
                existing = ds._binned.metadata.init_score
            if existing is not None and \
                    not getattr(ds, "_seeded_init_score", False):
                # base trees are prepended to the final model, so an extra
                # USER init_score would double-count — refuse rather than
                # silently produce shifted predictions. Scores that _seed
                # itself wrote on a previous train() are overwritten below
                # (iterative continuation reuses the same Dataset).
                raise ValueError(
                    "cannot combine init_model with a dataset that "
                    "already has init_score")
            if ds.data is None:
                raise ValueError(
                    "init_model continuation needs raw data on the "
                    "datasets; pass free_raw_data=False or un-constructed "
                    "Datasets")
            if isinstance(ds.data, ChunkSource):
                # continued boosting over a streamed dataset (the
                # continuous loop's refresh path): the raw matrix never
                # materializes host-side, so seed init scores chunk by
                # chunk through a fresh pass of the restartable source
                # — row order matches the loader's pass-2 binning order
                parts = [base_model.predict(X, raw_score=True)
                         for X, _ in ds.data.chunks()]
                if not parts:
                    raise ValueError(
                        "init_model continuation over an exhausted "
                        "stream: the source yielded no chunks to seed "
                        "init scores from")
                init = np.concatenate(parts, axis=0)
            else:
                init = base_model.predict(ds.data, raw_score=True)
            ds.init_score = init
            ds._seeded_init_score = True
            if ds._binned is not None:
                # dataset already constructed: construct() won't re-read
                # init_score, so push it into the binned metadata directly
                ds._binned.metadata.init_score = \
                    np.asarray(init, np.float32)

        _seed(train_set)
        if valid_sets is not None:
            vs = valid_sets if isinstance(valid_sets, list) else [valid_sets]
            for vd in vs:
                if isinstance(vd, Dataset) and vd is not train_set:
                    _seed(vd)
    else:
        # a plain train() after a continued one must not inherit the seed
        # the previous call wrote into this Dataset
        def _unseed(ds):
            if ds is not None and getattr(ds, "_seeded_init_score", False):
                ds.init_score = None
                ds._seeded_init_score = False
                if ds._binned is not None:
                    ds._binned.metadata.init_score = None

        _unseed(train_set)
        if valid_sets is not None:
            vs = valid_sets if isinstance(valid_sets, list) else [valid_sets]
            for vd in vs:
                if isinstance(vd, Dataset):
                    _unseed(vd)

    booster = Booster(params=params, train_set=train_set)
    if resume_state is not None:
        # the checkpointed model's trees ride in front of the resumed
        # ones exactly like continued training, but WITHOUT init-score
        # seeding: the restored train_score already contains their
        # contribution in the exact f32 bits the killed run held
        base_model = Booster(model_str=resume_state.model_str)
    if base_model is not None:
        booster._base_model = base_model

    is_valid_contain_train = False
    train_data_name = "training"
    reduced_valid_sets = []
    name_valid_sets = []
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        if isinstance(valid_names, str):
            valid_names = [valid_names]
        for i, valid_data in enumerate(valid_sets):
            if valid_data is train_set:
                is_valid_contain_train = True
                if valid_names is not None:
                    train_data_name = valid_names[i]
                continue
            if not isinstance(valid_data, Dataset):
                raise TypeError("Training only accepts Dataset object")
            reduced_valid_sets.append(valid_data)
            name_valid_sets.append(valid_names[i] if valid_names is not None
                                   else f"valid_{i}")
    booster.train_data_name = train_data_name
    for vd, name in zip(reduced_valid_sets, name_valid_sets):
        booster.add_valid(vd, name)

    start_iter = 0
    if resume_state is not None:
        booster._restore_training_state(resume_state)
        start_iter = resume_state.iteration
        Log.info(f"resuming training from checkpoint "
                 f"{resume_state.path!r} at iteration {start_iter}")

    cbs = set(callbacks or [])
    if params.get("early_stopping_round", 0) and \
            int(params["early_stopping_round"]) > 0:
        cbs.add(callback_mod.early_stopping(
            int(params["early_stopping_round"]), first_metric_only))
    cfg = booster.config
    if getattr(cfg, "checkpoint_period", 0) > 0 and cfg.checkpoint_dir \
            and not any(getattr(cb, "is_checkpoint", False) for cb in cbs):
        cbs.add(callback_mod.checkpoint(
            cfg.checkpoint_period, cfg.checkpoint_dir, cfg.checkpoint_keep))
    if resume_state is not None:
        history = resume_state.state.get("eval_history")
        if history:
            for cb in cbs:
                if hasattr(cb, "_seed_history"):
                    cb._seed_history(history)
    callbacks_before = {cb for cb in cbs
                        if getattr(cb, "before_iteration", False)}
    callbacks_after = cbs - callbacks_before
    callbacks_before = sorted(callbacks_before,
                              key=lambda cb: getattr(cb, "order", 0))
    callbacks_after = sorted(callbacks_after,
                             key=lambda cb: getattr(cb, "order", 0))

    booster.best_iteration = -1
    # block dispatch (TPU host-boundary amortization): when nothing in
    # the loop needs a per-iteration host boundary — no before_iteration
    # callbacks, no custom fobj/feval, no training-set metrics — train
    # fused_block_size iterations per device dispatch and run the
    # per-iteration metric/callback protocol from the block's valid-score
    # trajectory (GBDT.train_many). Results are identical to b=1: every
    # iteration is still evaluated, and an early stop mid-block rolls
    # the extra trees back before propagating. (Exception: the
    # row-sharded fused path may carry 1-ulp score rounding vs b=1 —
    # see distributed/fused.py; it is deterministic for any block size.)
    block = int(getattr(booster.config, "fused_block_size", 1) or 1)
    # after-callbacks must not read model state: at inner iteration j
    # the booster already holds the whole block's trees. The library's
    # own eval-driven callbacks are marked block_safe; any custom
    # callback forces the per-iteration cadence.
    cbs_block_safe = all(getattr(cb, "block_safe", False)
                         for cb in callbacks_after)
    use_blocks = (block > 1 and fobj is None and feval is None
                  and not callbacks_before and cbs_block_safe
                  and not is_valid_contain_train
                  and getattr(booster.gbdt, "_fused_eligible",
                              lambda: False)())
    # pipelined executor (pipeline/executor.py): same block dispatch,
    # but host work (tree unpacking, scheduling, observability) overlaps
    # the next block's device compute, and valid metrics can reduce
    # in-graph. Bit-identical models either way — pipeline=false keeps
    # this loop as the parity oracle.
    use_pipeline = use_blocks and bool(getattr(cfg, "pipeline", False))

    def _eval_at(i):
        evaluation_result_list = []
        if valid_sets is not None or feval is not None:
            if is_valid_contain_train:
                evaluation_result_list.extend(booster.eval_train(feval))
            if reduced_valid_sets:
                evaluation_result_list.extend(booster.eval_valid(feval))
        for cb in callbacks_after:
            cb(callback_mod.CallbackEnv(
                model=booster, params=params, iteration=i,
                begin_iteration=start_iter, end_iteration=num_boost_round,
                evaluation_result_list=evaluation_result_list))
        return evaluation_result_list

    evaluation_result_list = []
    try:
        if use_pipeline and start_iter < num_boost_round:
            from .pipeline import run_pipelined

            def _run_cbs(i, evlist):
                for cb in callbacks_after:
                    cb(callback_mod.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=start_iter,
                        end_iteration=num_boost_round,
                        evaluation_result_list=evlist))

            es_rounds = int(params.get("early_stopping_round", 0) or 0)
            evaluation_result_list = run_pipelined(
                booster, start_iter=start_iter,
                num_boost_round=num_boost_round, base_block=block,
                run_callbacks=_run_cbs,
                has_valid=bool(reduced_valid_sets),
                stopping_rounds=es_rounds)
            i = num_boost_round   # fully trained; the loop below no-ops
        else:
            i = start_iter
        while i < num_boost_round:
            b = min(block, num_boost_round - i) if use_blocks else 1
            if b > 1:
                booster.update_batch(b)
                gb = booster.gbdt
                traj = getattr(gb, "_fused_valid_traj", None)
                if traj is not None and reduced_valid_sets:
                    # evaluate every inner iteration from the trajectory
                    # (the last point IS the final score, so valid
                    # scores end the loop in their live state)
                    for j in range(b):
                        for vi in range(len(traj)):
                            gb.valid_scores[vi] = traj[vi][j]
                        try:
                            evaluation_result_list = _eval_at(i + j)
                        except callback_mod.EarlyStopException:
                            # restore block-final scores, roll the
                            # post-stop trees back, then pin the valid
                            # scores to the exact trajectory point (the
                            # rollback's add-then-subtract would leave
                            # ULP-level residue; train_score keeps the
                            # subtractive form — the booster is normally
                            # returned at this point, and the residue is
                            # bounded by one rounding per rolled tree;
                            # a later train(init_model=this_booster)
                            # inherits that bounded divergence — see the
                            # continued-training note above)
                            for vi in range(len(traj)):
                                gb.valid_scores[vi] = traj[vi][b - 1]
                            for _ in range(b - 1 - j):
                                booster.rollback_one_iter()
                            for vi in range(len(traj)):
                                gb.valid_scores[vi] = traj[vi][j]
                            raise
                        except BaseException:
                            # any other exit (custom abort,
                            # KeyboardInterrupt): leave the booster
                            # consistent — trees hold the full block, so
                            # scores must too
                            for vi in range(len(traj)):
                                gb.valid_scores[vi] = traj[vi][b - 1]
                            raise
                elif reduced_valid_sets:
                    # belt-and-braces, believed unreachable: train_many
                    # seals a full trajectory on every completing path
                    # (fused, fault fallback, ineligible, stalled).
                    # Were it ever to fire, evaluation degrades to
                    # block-end cadence rather than reading stale
                    # intermediate valid scores.
                    evaluation_result_list = _eval_at(i + b - 1)
                else:
                    # no valid sets: no eval work, but user callbacks
                    # still fire once per iteration
                    for j in range(b):
                        evaluation_result_list = _eval_at(i + j)
                i += b
                continue
            for cb in callbacks_before:
                cb(callback_mod.CallbackEnv(
                    model=booster, params=params, iteration=i,
                    begin_iteration=start_iter, end_iteration=num_boost_round,
                    evaluation_result_list=None))
            booster.update(fobj=fobj)
            evaluation_result_list = _eval_at(i)
            i += 1
    except callback_mod.EarlyStopException as es:
        # with continued training, iteration indexing covers the merged
        # model (base trees first), matching predict(num_iteration=...).
        # On resume the loop index is already absolute over the merged
        # model, so there is no base offset to add.
        base_iters = base_model.current_iteration() \
            if base_model is not None and resume_state is None else 0
        booster.best_iteration = base_iters + es.best_iteration + 1
        evaluation_result_list = es.best_score
    except Exception as exc:
        # unhandled training failure: leave a flight-recorder bundle
        # (when a bundle directory is configured) before propagating
        from .observability.flightrec import recorder as _flightrec
        _flightrec.record_exception("engine.train", exc)
        _flightrec.flush("exception")
        raise
    if booster.best_iteration < 0:
        booster.best_iteration = booster.current_iteration()
    try:
        booster.best_score = collections.defaultdict(collections.OrderedDict)
        for data_name, eval_name, score, _ in evaluation_result_list or []:
            booster.best_score[data_name][eval_name] = score
    except Exception:
        pass
    return booster


class CVBooster:
    """Ensemble of per-fold boosters (reference engine.py:298)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def _append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params,
                  seed: int, stratified: bool, shuffle: bool):
    full_data.construct()
    num_data = full_data.num_data()
    if folds is not None:
        if not hasattr(folds, "__iter__") and not hasattr(folds, "split"):
            raise AttributeError(
                "folds should be a generator or iterator of (train_idx, "
                "test_idx) tuples or scikit-learn splitter object")
        if hasattr(folds, "split"):
            group_info = full_data.get_group()
            if group_info is not None:
                group_info = np.asarray(group_info, np.int32)
                flatted_group = np.repeat(
                    range(len(group_info)), repeats=group_info)
            else:
                flatted_group = np.zeros(num_data, np.int32)
            folds = folds.split(X=np.empty(num_data),
                                y=full_data.get_label(),
                                groups=flatted_group)
    else:
        rng = np.random.RandomState(seed)
        if stratified:
            y = np.asarray(full_data.get_label())
            order = np.argsort(y, kind="stable")
            if shuffle:
                # shuffle within class for stratification
                folds_assign = np.empty(num_data, np.int32)
                folds_assign[order] = np.arange(num_data) % nfold
                perm_in = rng.permutation  # noqa: F841
            else:
                folds_assign = np.empty(num_data, np.int32)
                folds_assign[order] = np.arange(num_data) % nfold
            folds = [(np.where(folds_assign != k)[0],
                      np.where(folds_assign == k)[0]) for k in range(nfold)]
        else:
            idx = rng.permutation(num_data) if shuffle \
                else np.arange(num_data)
            folds = [(np.concatenate([idx[:k * num_data // nfold],
                                      idx[(k + 1) * num_data // nfold:]]),
                      idx[k * num_data // nfold:
                          (k + 1) * num_data // nfold])
                     for k in range(nfold)]
    ret = []
    for train_idx, test_idx in folds:
        tr = np.sort(np.asarray(train_idx))
        te = np.sort(np.asarray(test_idx))
        train_sub = full_data.subset(tr, params)
        valid_sub = full_data.subset(te, params)
        ret.append((train_sub, valid_sub, tr, te))
    return ret


def _agg_cv_result(raw_results):
    """Collapse per-fold eval lists into cv_agg entries.

    Each fold yields (data_name, metric_name, value, higher_better)
    tuples; folds are aggregated per "data_name metric_name" key into
    ("cv_agg", key, mean, higher_better, std), preserving first-seen
    key order (the reference engine's cv display contract)."""
    by_key: Dict[str, Tuple[bool, List[float]]] = {}
    for fold in raw_results:
        for data_name, metric_name, value, higher_better, *_ in fold:
            slot = by_key.setdefault(f"{data_name} {metric_name}",
                                     (higher_better, []))
            slot[1].append(value)
    return [("cv_agg", key, float(np.mean(vals)), hb, float(np.std(vals)))
            for key, (hb, vals) in by_key.items()]


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True,
       shuffle: bool = True, metrics=None, fobj=None, feval=None,
       init_model=None, feature_name="auto", categorical_feature="auto",
       fpreproc=None, seed: int = 0, callbacks=None, eval_train_metric=False,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    params = copy.deepcopy(params or {})
    num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    init_full = None
    if init_model is not None:
        # continuation: the base model's raw predictions seed every
        # fold's init scores (reference cv: train_set._set_predictor,
        # engine.py:548-562)
        base_model = init_model if isinstance(init_model, Booster) else \
            Booster(model_file=init_model)
        if train_set.data is None or isinstance(train_set.data, str):
            raise ValueError(
                "cv(init_model=...) needs in-memory raw data on the "
                "dataset; pass free_raw_data=False with an array/frame "
                "(file-backed Datasets are not supported here)")
        existing = train_set.init_score
        if existing is None and train_set._binned is not None:
            existing = train_set._binned.metadata.init_score
        if existing is not None:
            # same contract as train(): base trees' predictions become
            # the init scores, so a user init_score would double-count
            raise ValueError(
                "cannot combine init_model with a dataset that already "
                "has init_score")
        init_full = np.asarray(
            base_model.predict(train_set.data, raw_score=True), np.float64)
    if fobj is not None:
        params["objective"] = "none"
    if metrics:
        params["metric"] = metrics
    if params.get("objective", "") in ("lambdarank", "rank_xendcg") or \
            train_set.group is not None:
        stratified = False

    results = collections.defaultdict(list)
    cvfolds = _make_n_folds(train_set, folds, nfold, params, seed,
                            stratified, shuffle)
    cvbooster = CVBooster()
    boosters = []
    for train_sub, valid_sub, tr_idx, te_idx in cvfolds:
        if init_full is not None:
            # subsets are already constructed; push into binned metadata
            # (the path Booster reads init scores from)
            for sub, idx in ((train_sub, tr_idx), (valid_sub, te_idx)):
                sub.init_score = init_full[idx]
                sub._binned.metadata.init_score = np.ascontiguousarray(
                    init_full[idx], np.float64)
        if fpreproc is not None:
            train_sub, valid_sub, params = fpreproc(
                train_sub, valid_sub, params.copy())
        bst = Booster(params=params, train_set=train_sub)
        bst.add_valid(valid_sub, "valid")
        boosters.append(bst)
        cvbooster._append(bst)

    cbs = set(callbacks or [])
    if params.get("early_stopping_round", 0) and \
            int(params["early_stopping_round"]) > 0:
        cbs.add(callback_mod.early_stopping(
            int(params["early_stopping_round"]),
            bool(params.get("first_metric_only", False))))
    callbacks_before = sorted(
        (cb for cb in cbs if getattr(cb, "before_iteration", False)),
        key=lambda cb: getattr(cb, "order", 0))
    callbacks_after = sorted(
        (cb for cb in cbs if not getattr(cb, "before_iteration", False)),
        key=lambda cb: getattr(cb, "order", 0))

    try:
        for i in range(num_boost_round):
            raw_results = []
            for bst in boosters:
                for cb in callbacks_before:
                    cb(callback_mod.CallbackEnv(
                        model=bst, params=params, iteration=i,
                        begin_iteration=0, end_iteration=num_boost_round,
                        evaluation_result_list=None))
                bst.update(fobj=fobj)
                res = bst.eval_valid(feval)
                if eval_train_metric:
                    res = bst.eval_train(feval) + res
                raw_results.append(res)
            agg = _agg_cv_result(raw_results)
            for _, key, mean, _, std in agg:
                results[key + "-mean"].append(mean)
                results[key + "-stdv"].append(std)
            for cb in callbacks_after:
                cb(callback_mod.CallbackEnv(
                    model=cvbooster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=agg))
    except callback_mod.EarlyStopException as es:
        cvbooster.best_iteration = es.best_iteration + 1
        for bst in boosters:
            bst.best_iteration = cvbooster.best_iteration
        for k in results:
            results[k] = results[k][:cvbooster.best_iteration]
    if return_cvbooster:
        results["cvbooster"] = cvbooster
    return dict(results)
