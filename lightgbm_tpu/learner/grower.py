"""Tree growth: fully-jitted best-first growth with batched frontier passes.

TPU-native redesign of the reference tree learners:

- SerialTreeLearner (serial_tree_learner.cpp:159-210) grows leaf-wise, one
  split per step, repartitioning row indices per leaf (data_partition.hpp:21).
  CUDASingleGPUTreeLearner (cuda_single_gpu_tree_learner.cpp:108-232) keeps
  that loop on host, with device kernels per phase.
- Here the WHOLE growth loop is one `lax.while_loop` on device with static
  shapes: a `row_node [N]` vector (the device-resident descendant of
  CUDADataPartition's data_index_to_leaf_index, cuda_data_partition.cu:288),
  tree arrays indexed by node id (CUDATree, cuda_tree.hpp:28), and per-pass
  histograms for every frontier node at once.

Growth policy: each pass histograms all not-yet-scanned leaves, scans their
best splits, then applies the top-`budget` splits ranked by gain where
`budget = num_leaves - current`. With `leafwise=True` only the single best
leaf splits per pass — exactly the reference's leaf-wise order
(serial_tree_learner.cpp:188-206); the default batched mode reaches the same
num_leaves in ~depth passes instead of num_leaves-1, trading exact split
order for an O(num_leaves/depth)× reduction in full-data passes — the right
trade on TPU where every pass is one fused scatter over the whole binned
matrix.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..parallel.comm import CommSpec
from .histogram import build_histograms
from .monotone import recompute_bounds
from .split import (BestSplits, SplitHyperParams, _split_gain,
                    find_best_splits, leaf_gain, leaf_output)

__all__ = ["CegbParams", "TreeArrays", "grow_tree"]


@dataclasses.dataclass(frozen=True)
class CegbParams:
    """Static CEGB settings (reference Config cegb_* params,
    cost_effective_gradient_boosting.hpp:23)."""
    tradeoff: float = 1.0
    penalty_split: float = 0.0
    has_coupled: bool = False
    has_lazy: bool = False


class TreeArrays(NamedTuple):
    """Struct-of-arrays tree, sized [max_nodes + 1] (last row = scratch).

    Device-resident counterpart of the reference Tree (include/LightGBM/
    tree.h:25) / CUDATree (cuda_tree.hpp:28). Node 0 is the root; internal
    nodes carry split info, leaves carry output values.
    """
    split_feature: jax.Array   # i32, used-feature idx; -1 for leaf
    threshold_bin: jax.Array   # i32; numerical: left iff bin <= t
    default_left: jax.Array    # bool (NaN direction)
    is_cat: jax.Array          # bool; decision: bin in cat_bitset -> left
    cat_bitset: jax.Array      # [M+1, W] uint32 bin-bitset per node
    left: jax.Array            # i32 child id
    right: jax.Array           # i32 child id
    parent: jax.Array          # i32, -1 for root
    leaf_value: jax.Array      # f32 node output
    sum_grad: jax.Array        # f32
    sum_hess: jax.Array        # f32
    count: jax.Array           # f32
    gain: jax.Array            # f32 split gain of internal nodes
    depth: jax.Array           # i32
    is_leaf: jax.Array         # bool
    num_nodes: jax.Array       # i32 scalar
    num_leaves: jax.Array      # i32 scalar


class _GrowState(NamedTuple):
    tree: TreeArrays
    row_node: jax.Array        # [N] i32
    slot_of_node: jax.Array    # [M+1] i32, -1 = not in frontier this pass
    slot_nodes: jax.Array      # [S] i32 node id per slot; M = inactive
    best: BestSplits           # per-NODE arrays [M+1]
    node_force: jax.Array      # [M+1] forced-split spec idx per node (-1=none)
    forced_ok: jax.Array       # [M+1] forced split of node is applicable
    feat_used: jax.Array       # [F] feature used by any model split (CEGB)
    row_feat_used: jax.Array   # [N, F] row charged for feature (CEGB lazy)
    cons_min: jax.Array        # [M+1] monotone lower bound per node
    cons_max: jax.Array        # [M+1] monotone upper bound per node
    path_mask: jax.Array       # [M+1, F] features used on root path (or [1,1])
    hist_cache: jax.Array      # [M+1, F, B, 3] per-node hists (intermediate/
                               # advanced monotone rescan) or [1] dummy
    pass_idx: jax.Array
    done: jax.Array


def _init_tree(max_nodes: int, root_grad, root_hess, root_count,
               root_value, bitset_words: int = 1) -> TreeArrays:
    m1 = max_nodes + 1
    zf = jnp.zeros(m1, jnp.float32)
    zi = jnp.zeros(m1, jnp.int32)
    zb = jnp.zeros(m1, bool)
    return TreeArrays(
        split_feature=jnp.full(m1, -1, jnp.int32),
        threshold_bin=zi, default_left=zb, is_cat=zb,
        cat_bitset=jnp.zeros((m1, bitset_words), jnp.uint32),
        left=jnp.full(m1, -1, jnp.int32), right=jnp.full(m1, -1, jnp.int32),
        parent=jnp.full(m1, -1, jnp.int32),
        leaf_value=zf.at[0].set(root_value),
        sum_grad=zf.at[0].set(root_grad),
        sum_hess=zf.at[0].set(root_hess),
        count=zf.at[0].set(root_count),
        gain=zf, depth=zi, is_leaf=zb.at[0].set(True),
        num_nodes=jnp.asarray(1, jnp.int32),
        num_leaves=jnp.asarray(1, jnp.int32))


def _merge_gathered_best(gathered: BestSplits) -> BestSplits:
    """Pick the max-gain split across devices per slot (the reference's
    SyncUpGlobalBestSplit max-gain reducer, parallel_tree_learner.h:191-214).
    gathered fields: [D, S]."""
    win = jnp.argmax(gathered.gain, axis=0)                   # [S]

    def pick(name, field):
        if name == "per_feature_gain":  # disjoint shards: elementwise max
            return jnp.max(field, axis=0)
        if field.ndim == 3:             # [D, S, W] bitsets
            return jnp.take_along_axis(field, win[None, :, None], axis=0)[0]
        return jnp.take_along_axis(field, win[None], axis=0)[0]

    return BestSplits(*[pick(f, getattr(gathered, f))
                        for f in BestSplits._fields])


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "max_depth", "hp", "leafwise", "bmax",
                     "feature_block", "max_passes", "comm",
                     "interaction_groups", "feature_fraction_bynode",
                     "hist_impl", "partition_impl", "cegb_cfg",
                     "monotone_method"))
def grow_tree(bins: jax.Array, grad: jax.Array, hess: jax.Array,
              cnt_weight: jax.Array, feature_mask: jax.Array,
              num_bins: jax.Array, missing_is_nan: jax.Array,
              is_cat_feat: jax.Array, *, num_leaves: int, max_depth: int,
              hp: SplitHyperParams, leafwise: bool = False, bmax: int,
              feature_block: int = 8, max_passes: int = 0,
              comm: Optional[CommSpec] = None,
              monotone: Optional[jax.Array] = None,
              interaction_groups: Optional[tuple] = None,
              feature_fraction_bynode: float = 1.0,
              rng_key: Optional[jax.Array] = None,
              hist_impl: str = "scatter",
              partition_impl: str = "auto",
              forced: Optional[Tuple[jax.Array, jax.Array, jax.Array,
                                     jax.Array]] = None,
              cegb_cfg: Optional[CegbParams] = None,
              cegb_state: Optional[Tuple[jax.Array, jax.Array, jax.Array]]
              = None, monotone_method: str = "basic", efb=None,
              bins_ft: Optional[jax.Array] = None):
    """Grow one tree. grad/hess must already include bagging/objective
    weights (zeros for out-of-bag rows); `cnt_weight` is 1.0 for in-bag rows
    and 0.0 otherwise so min_data_in_leaf counts sampled rows only.

    With `efb` (an efb.EfbDev), `bins` is the BUNDLED [N, Fb] matrix:
    histograms build in bundle space and are expanded back to original
    features before the scan, and routing translates through the bundle
    tables — every other argument stays in original-feature space
    (reference feature_group.h:25; see efb.py).

    With `comm.hist_agg == "reduce_scatter"` the data/voting histogram
    merge switches from the full psum to the reference's Reduce-Scatter
    (data_parallel_tree_learner.cpp:184-233): each device scans only its
    feature block and a small [D, S] allgather merges the winners. When
    `bins_ft` (the one-time all_to_all transpose from
    distributed/hist_agg.py::build_feature_shards, [N_global, F/world]
    per device) is supplied, the block histograms are built directly from
    all rows — byte-identical to the serial learner; without it, local
    full-width histograms fold through psum_scatter (numerically but not
    bitwise equal).

    Returns (tree, row_node) — row_node maps every row (in- and out-of-bag)
    to its leaf for learner-side score updates (reference
    score_updater.hpp:21-110 AddScore(tree_learner) path).
    """
    n = bins.shape[0]
    f = feature_mask.shape[0] if efb is not None else bins.shape[1]
    hist_bmax = efb.bundle_bmax if efb is not None else bmax
    m = 2 * num_leaves - 1             # max nodes
    s = num_leaves + 1                 # frontier slots (2k children <= S)
    if max_passes <= 0:
        max_passes = num_leaves - 1
    # intermediate/advanced monotone methods: whole-tree bound recompute
    # + all-leaves rescan from a histogram cache each iteration (the
    # vectorized equivalent of the reference's leaves_to_update refresh,
    # monotone_constraints.hpp:558-587). Bounds recomputed at pass start
    # are only sound for one split per pass — leaf-wise is required.
    mono_rescan = monotone_method != "basic" and monotone is not None
    if mono_rescan:
        if not leafwise:
            raise ValueError(
                "monotone_constraints_method=%r requires leaf-wise growth"
                % monotone_method)
        if comm is not None and comm.mode == "voting":
            raise ValueError(
                "monotone_constraints_method=%r is not supported with the "
                "voting tree learner (partial histograms cannot be "
                "cached)" % monotone_method)
        # the all-nodes histogram cache is [M+1, F, bmax, 3] f32 — on wide
        # feature sets this can dwarf HBM (F=1000, 255 leaves, 256 bins
        # ~ 1.5 GB). Warn before allocating so an OOM is attributable.
        cache_bytes = (m + 1) * f * bmax * 3 * 4
        if cache_bytes > (1 << 30):
            from ..utils.log import Log
            Log.warning(
                "monotone_constraints_method=%s allocates a %.1f GiB "
                "histogram cache ([%d nodes, %d features, %d bins]); "
                "reduce num_leaves/max_bin or use "
                "monotone_constraints_method='basic' if this OOMs."
                % (monotone_method, cache_bytes / 2**30, m + 1, f, bmax))
    k_top = num_leaves - 1             # static top-k size
    rows_sharded = comm is not None and comm.mode in ("data", "voting")
    # Reduce-scatter histogram aggregation (distributed/hist_agg.py):
    # device d owns the contiguous feature block [d*Fp, (d+1)*Fp). The
    # exact flavor needs the bins_ft transpose; voting reduces to the
    # exact data-parallel scan only when the top-2k vote selection covers
    # every feature. EFB (bundle-space histograms) and the rescanning
    # monotone methods (whole-tree full-width cache) keep the psum merge.
    rs_mode = (rows_sharded and comm.hist_agg == "reduce_scatter"
               and not mono_rescan and efb is None)
    use_rs_exact = rs_mode and bins_ft is not None and (
        comm.mode == "data" or 2 * comm.top_k >= f)
    use_rs_scatter = rs_mode and not use_rs_exact and comm.mode == "data"
    if use_rs_exact or use_rs_scatter:
        ndev = comm.num_devices
        fp = bins_ft.shape[1] if use_rs_exact else -(-f // ndev)
        fpad = fp * ndev
        myd = jax.lax.axis_index(comm.axis)
    if comm is not None and comm.mode == "feature":
        # deterministic round-robin feature shard (the reference balances by
        # total bin count, feature_parallel_tree_learner.cpp:38-57; round
        # robin gives the same expected balance for quantized features)
        my = jax.lax.axis_index(comm.axis)
        feature_mask = feature_mask * (
            (jnp.arange(f, dtype=jnp.int32) % comm.num_devices) == my
        ).astype(feature_mask.dtype)

    if use_rs_exact:
        # full-row gathers: with the feature-shard transpose this device
        # histograms ALL rows of its features, so grad/hess/cnt (loop
        # constants) gather once up front; summing the gathered arrays IS
        # the serial root reduction — no psum, no blocked-sum skew
        grad_full = jax.lax.all_gather(grad, comm.axis, tiled=True)
        hess_full = jax.lax.all_gather(hess, comm.axis, tiled=True)
        cnt_full = jax.lax.all_gather(cnt_weight, comm.axis, tiled=True)
        root_g = jnp.sum(grad_full)
        root_h = jnp.sum(hess_full)
        root_c = jnp.sum(cnt_full)
    else:
        root_g = jnp.sum(grad)
        root_h = jnp.sum(hess)
        root_c = jnp.sum(cnt_weight)
        if rows_sharded:
            # root grad/hess sums allreduced
            # (data_parallel_tree_learner.cpp:126)
            root_g = jax.lax.psum(root_g, comm.axis)
            root_h = jax.lax.psum(root_h, comm.axis)
            root_c = jax.lax.psum(root_c, comm.axis)
    root_val = leaf_output(root_g, root_h, hp.lambda_l1, hp.lambda_l2,
                           hp.max_delta_step)
    w_cat = (bmax + 31) // 32          # bitset words per node
    tree = _init_tree(m, root_g, root_h, root_c, root_val, bitset_words=w_cat)

    best0 = BestSplits(
        gain=jnp.full(m + 1, -jnp.inf, jnp.float32),
        feature=jnp.full(m + 1, -1, jnp.int32),
        threshold_bin=jnp.zeros(m + 1, jnp.int32),
        default_left=jnp.zeros(m + 1, bool),
        left_grad=jnp.zeros(m + 1, jnp.float32),
        left_hess=jnp.zeros(m + 1, jnp.float32),
        left_count=jnp.zeros(m + 1, jnp.float32),
        left_output=jnp.zeros(m + 1, jnp.float32),
        right_output=jnp.zeros(m + 1, jnp.float32),
        per_feature_gain=jnp.zeros((1, 1), jnp.float32),
        cat_bitset=jnp.zeros((m + 1, w_cat), jnp.uint32))

    use_interaction = interaction_groups is not None and \
        len(interaction_groups) > 0
    if use_interaction:
        # group masks [G, F]; allowed(node) = union of groups that contain
        # the node's full path-feature set (reference ColSampler
        # interaction-constraint filtering, col_sampler.hpp:20)
        import numpy as _np
        gm = _np.zeros((len(interaction_groups), f), _np.bool_)
        for gi, grp in enumerate(interaction_groups):
            for fi in grp:
                if 0 <= fi < f:
                    gm[gi, fi] = True
        group_masks = jnp.asarray(gm)
        path_mask0 = jnp.zeros((m + 1, f), bool)
    else:
        group_masks = None
        path_mask0 = jnp.zeros((1, 1), bool)
    use_bynode = feature_fraction_bynode < 1.0 and rng_key is not None
    k_bynode = max(1, int(round(feature_fraction_bynode * f)))

    # Forced splits (reference SerialTreeLearner::ForceSplits,
    # serial_tree_learner.cpp:459): `forced` carries a flattened spec tree
    # (feature [K], threshold bin [K], left/right child spec idx [K]); the
    # root node is bound to spec 0 and children inherit the spec's subtree
    # indices, reproducing the reference's BFS application order (forced
    # nodes outrank every gain-chosen split in the selection step).
    use_forced = forced is not None
    if use_forced:
        forced_feat, forced_bin, forced_left, forced_right = forced
        n_spec = forced_feat.shape[0]

    # CEGB (cost_effective_gradient_boosting.hpp): per-(node, feature) gain
    # penalty = tradeoff * (penalty_split * n_leaf
    #   + coupled[f] * [f unused in model]
    #   + lazy[f] * #in-bag rows in leaf not yet charged for f)
    use_cegb = cegb_cfg is not None
    if use_cegb:
        # (coupled [F], lazy [F], feat_used [F] bool, row_feat_used [N,F])
        cegb_coupled, cegb_lazy, feat_used0, row_feat_used0 = cegb_state
    else:
        feat_used0 = jnp.zeros(1, bool)
        row_feat_used0 = jnp.zeros((1, 1), bool)

    state = _GrowState(
        tree=tree,
        row_node=jnp.zeros(n, jnp.int32),
        slot_of_node=jnp.full(m + 1, -1, jnp.int32).at[0].set(0),
        slot_nodes=jnp.full(s, m, jnp.int32).at[0].set(0),
        best=best0,
        node_force=(jnp.full(m + 1, -1, jnp.int32).at[0].set(0) if use_forced
                    else jnp.full(1, -1, jnp.int32)),
        forced_ok=jnp.zeros(m + 1 if use_forced else 1, bool),
        feat_used=feat_used0,
        row_feat_used=row_feat_used0,
        cons_min=jnp.full(m + 1, -jnp.inf, jnp.float32),
        cons_max=jnp.full(m + 1, jnp.inf, jnp.float32),
        path_mask=path_mask0,
        hist_cache=(jnp.zeros((m + 1, f, bmax, 3), jnp.float32)
                    if mono_rescan else jnp.zeros(1, jnp.float32)),
        pass_idx=jnp.asarray(0, jnp.int32),
        done=jnp.asarray(False))

    def cond(st: _GrowState):
        return (~st.done) & (st.pass_idx < max_passes)

    def body(st: _GrowState) -> _GrowState:
        tree = st.tree
        # ---- 1. histograms for frontier slots ----
        row_slot = st.slot_of_node[st.row_node]            # [N]
        if use_rs_exact:
            # exact reduce-scatter: histogram ALL rows of THIS device's
            # feature block from the bins_ft transpose — the identical
            # scatter-adds the serial learner performs, restricted to a
            # column block, so the block histogram is byte-equal to the
            # serial one (per-feature accumulation is independent of how
            # columns group into blocks)
            row_slot_full = jax.lax.all_gather(row_slot, comm.axis,
                                               tiled=True)
            hist_sh = build_histograms(
                bins_ft, grad_full, hess_full, row_slot_full, cnt_full,
                num_slots=s, bmax=hist_bmax, feature_block=feature_block)
            hist = None
        elif hist_impl == "pallas":
            from .histogram_pallas import build_histograms_pallas
            hist = build_histograms_pallas(
                bins, grad, hess, cnt_weight, row_slot, num_slots=s,
                bmax=hist_bmax, partition_impl=partition_impl)
        else:
            hist = build_histograms(bins, grad, hess, row_slot, cnt_weight,
                                    num_slots=s, bmax=hist_bmax,
                                    feature_block=feature_block)
        if efb is not None:
            # bundle-space histograms -> per-original-feature histograms;
            # everything downstream (scan, forced splits, monotone cache)
            # is in original-feature space from here on. Linear, so the
            # data-parallel psum below commutes with it.
            from ..efb import expand_histograms
            hist = expand_histograms(hist, efb)
        # ---- 2. best-split scan per slot (with collectives if parallel) ----
        sn = st.slot_nodes                                  # [S] (M=dummy)
        hist_cache = st.hist_cache
        if mono_rescan:
            # cache the (globally merged) frontier histograms per node,
            # then rescan EVERY node with freshly recomputed bounds — the
            # vectorized form of the reference's refresh-and-refind of
            # affected leaves (monotone_constraints.hpp:558 Update ->
            # leaves_to_update -> serial_tree_learner re-find)
            gh = jax.lax.psum(hist, comm.axis) if (
                comm is not None and comm.mode == "data") else hist
            hist_cache = hist_cache.at[sn].set(gh)
            sn = jnp.arange(m + 1, dtype=jnp.int32)
            hist = hist_cache
            s_scan = m + 1
        else:
            s_scan = s

        # per-slot feature mask: bytree fraction x bynode sample x
        # interaction-allowed set (reference ColSampler, col_sampler.hpp:20)
        slot_fmask = jnp.broadcast_to(feature_mask[None, :], (s_scan, f))
        if use_bynode:
            # rescan slots ARE nodes: a fixed key keeps each node's
            # by-node feature sample stable across re-scans (the
            # reference samples once per leaf)
            ku = jax.random.fold_in(rng_key,
                                    1 if mono_rescan else st.pass_idx)
            u = jax.random.uniform(ku, (s_scan, f))
            u = jnp.where(feature_mask[None, :] > 0, u, jnp.inf)
            kth = jnp.sort(u, axis=1)[:, k_bynode - 1][:, None]
            slot_fmask = slot_fmask * (u <= kth)
        if use_interaction:
            pm = st.path_mask[sn]                           # [S, F]
            subset = jnp.all((~pm[:, None, :]) | group_masks[None, :, :],
                             axis=2)                        # [S, G]
            allowed = jnp.einsum("sg,gf->sf", subset.astype(jnp.float32),
                                 group_masks.astype(jnp.float32)) > 0
            allowed = allowed | pm  # path features stay available
            slot_fmask = slot_fmask * allowed
        rand_bins = None
        if hp.extra_trees and rng_key is not None:
            kr = jax.random.fold_in(jax.random.fold_in(rng_key, 7919),
                                    1 if mono_rescan else st.pass_idx)
            rand_bins = jax.random.randint(kr, (s_scan, f), 0, bmax)
        if use_cegb:
            gp = cegb_cfg.tradeoff * cegb_cfg.penalty_split * \
                tree.count[sn][:, None] * jnp.ones((s_scan, f), jnp.float32)
            if cegb_cfg.has_coupled:
                gp += cegb_cfg.tradeoff * cegb_coupled[None, :] * \
                    (~st.feat_used)[None, :].astype(jnp.float32)
            if cegb_cfg.has_lazy:
                rs = st.row_node if mono_rescan else \
                    jnp.where(row_slot < 0, s, row_slot)
                uncharged = jnp.zeros((s_scan + 1, f), jnp.float32) \
                    .at[rs].add((~st.row_feat_used).astype(jnp.float32) *
                                cnt_weight[:, None])[:s_scan]
                if rows_sharded:
                    # the on-demand cost is a sum over ALL of a node's
                    # rows; shards hold disjoint row sets, so merge like
                    # the histogram reduce (every shard must apply the
                    # identical penalty or trees diverge)
                    uncharged = jax.lax.psum(uncharged, comm.axis)
                gp += cegb_cfg.tradeoff * cegb_lazy[None, :] * uncharged
        else:
            gp = None
        if mono_rescan:
            cons_min_s, cons_max_s = recompute_bounds(
                tree, monotone, num_bins, method=monotone_method,
                missing_is_nan=missing_is_nan)
        else:
            cons_min_s, cons_max_s = st.cons_min[sn], st.cons_max[sn]
        mono_kw = dict(monotone=monotone, cons_min=cons_min_s,
                       cons_max=cons_max_s, depth=tree.depth[sn],
                       rand_bins=rand_bins, gain_penalty=gp)

        def scan_hist(h, fm):
            return find_best_splits(
                h, tree.sum_grad[sn], tree.sum_hess[sn], tree.count[sn],
                tree.leaf_value[sn], num_bins, missing_is_nan, is_cat_feat,
                fm, hp, **mono_kw)

        if comm is None or (mono_rescan and comm.mode == "data"):
            bs = scan_hist(hist, slot_fmask)  # cache already merged
        elif use_rs_exact or use_rs_scatter:
            # Reduce-Scatter scan (data_parallel_tree_learner.cpp:184-233):
            # scan ONLY this device's feature block, then merge the [D, S]
            # winners through a small allgather — the wire moves each
            # histogram byte once instead of world times. The exact
            # flavor's hist_sh is already the global block histogram; the
            # scatter flavor folds full-width partials here.
            if use_rs_exact:
                # Scan at the SERIAL operand shape: the best-split prefix
                # sum lowers to a GEMM whose rounding depends on the
                # operand width ([S,Fp,B,C] vs [S,F,B,C] pick different
                # kernel tilings), so a narrow block scan drifts from the
                # serial scan by ulps. GEMM output rows are independent of
                # each other, so embedding the block at its global column
                # offset in a zero tensor of the serial shape makes the
                # owned columns' results bit-equal to serial; the
                # ownership mask hides the zero columns, and the argmax
                # merge below ties to the lowest device = lowest feature
                # id, matching the serial first-max tie-break.
                full = jnp.zeros((hist_sh.shape[0], fpad) + hist_sh.shape[2:],
                                 hist_sh.dtype)
                full = jax.lax.dynamic_update_slice(
                    full, hist_sh, (0, myd * fp, 0, 0))
                own = ((jnp.arange(f) >= myd * fp) &
                       (jnp.arange(f) < (myd + 1) * fp))
                local = scan_hist(
                    full[:, :f],
                    slot_fmask * own[None, :].astype(slot_fmask.dtype))
            else:
                from ..distributed.hist_agg import reduce_scatter_hist
                hist_sh = reduce_scatter_hist(
                    jnp.pad(hist, ((0, 0), (0, fpad - f), (0, 0), (0, 0))),
                    comm.axis)

                def shard1(a, fill):
                    pad = jnp.full(fpad - f, fill, a.dtype)
                    return jax.lax.dynamic_slice_in_dim(
                        jnp.concatenate([a, pad]), myd * fp, fp)

                def shard2(a, fill):
                    pad = jnp.full((a.shape[0], fpad - f), fill, a.dtype)
                    return jax.lax.dynamic_slice_in_dim(
                        jnp.concatenate([a, pad], axis=1), myd * fp, fp,
                        axis=1)

                # padded tail columns scan as masked-out single-bin
                # features; block-local winner features translate back to
                # global ids before the merge
                mono_kw_sh = dict(
                    monotone=(shard1(monotone, 0) if monotone is not None
                              else None),
                    cons_min=cons_min_s, cons_max=cons_max_s,
                    depth=tree.depth[sn],
                    rand_bins=(shard2(rand_bins, 0) if rand_bins is not None
                               else None),
                    gain_penalty=(shard2(gp, 0.0) if gp is not None
                                  else None))
                local = find_best_splits(
                    hist_sh, tree.sum_grad[sn], tree.sum_hess[sn],
                    tree.count[sn], tree.leaf_value[sn],
                    shard1(num_bins, 1), shard1(missing_is_nan, False),
                    shard1(is_cat_feat, False), shard2(slot_fmask, 0), hp,
                    **mono_kw_sh)
                local = local._replace(feature=jnp.where(
                    local.feature >= 0, local.feature + myd * fp,
                    local.feature))
            gathered = BestSplits(*[
                jax.lax.all_gather(getattr(local, fld), comm.axis)
                for fld in BestSplits._fields])
            bs = _merge_gathered_best(gathered)
        elif comm.mode == "data":
            # histogram merge == the ReduceScatter of
            # data_parallel_tree_learner.cpp:184-186; psum lets every device
            # scan all features (no best-split sync round needed after)
            bs = scan_hist(jax.lax.psum(hist, comm.axis), slot_fmask)
        elif comm.mode == "feature":
            # local scan over this device's feature shard, then global
            # max-gain sync (feature_parallel_tree_learner.cpp:58-84)
            local = scan_hist(hist, slot_fmask)
            gathered = BestSplits(*[
                jax.lax.all_gather(getattr(local, fld), comm.axis)
                for fld in BestSplits._fields])
            bs = _merge_gathered_best(gathered)
        else:  # voting (PV-Tree, voting_parallel_tree_learner.cpp)
            # local scan with constraints scaled down by num_machines
            # (voting_parallel_tree_learner.cpp:62-63)
            hp_local = dataclasses.replace(
                hp,
                min_data_in_leaf=max(1, hp.min_data_in_leaf //
                                     comm.num_devices),
                min_sum_hessian_in_leaf=hp.min_sum_hessian_in_leaf /
                comm.num_devices)
            local = find_best_splits(
                hist, tree.sum_grad[sn] / comm.num_devices,
                tree.sum_hess[sn] / comm.num_devices,
                tree.count[sn] / comm.num_devices,
                tree.leaf_value[sn], num_bins, missing_is_nan, is_cat_feat,
                slot_fmask, hp_local, **mono_kw)
            k_vote = min(comm.top_k, f)
            _, vote_idx = jax.lax.top_k(local.per_feature_gain, k_vote)
            votes = jnp.zeros((s, f), jnp.float32)
            votes = jax.vmap(lambda v, i: v.at[i].add(1.0))(votes, vote_idx)
            gvotes = jax.lax.psum(votes, comm.axis)
            # global top-2k selection per slot; aggregate only those columns
            k_sel = min(2 * comm.top_k, f)
            _, sel_idx = jax.lax.top_k(gvotes, k_sel)
            sel_mask = jnp.zeros((s, f), jnp.float32)
            sel_mask = jax.vmap(
                lambda v, i: v.at[i].set(1.0))(sel_mask, sel_idx)
            hist_sel = hist * sel_mask[:, :, None, None]
            ghist = jax.lax.psum(hist_sel, comm.axis)
            bs = scan_hist(ghist, sel_mask * slot_fmask)
        if use_forced:
            # override gain-chosen splits on forced nodes with the spec's
            # (feature, threshold); stats gathered from the histogram like
            # FeatureHistogram::GatherInfoForThreshold
            # (feature_histogram.hpp:862+)
            nf_slot = st.node_force[sn]                     # [S]
            has_f = (nf_slot >= 0) & (sn < m)
            sp = jnp.clip(nf_slot, 0, n_spec - 1)
            ff = jnp.clip(forced_feat[sp], 0, f - 1)        # [S]
            fb = forced_bin[sp]
            if use_rs_exact or use_rs_scatter:
                # only the feature's owner holds its block histogram; the
                # psum of the single nonzero contribution is an exact copy
                owned = (ff >= myd * fp) & (ff < (myd + 1) * fp)
                lff = jnp.clip(ff - myd * fp, 0, fp - 1)
                hsel = jnp.take_along_axis(
                    hist_sh, lff[:, None, None, None], axis=1)[:, 0]
                hsel = hsel * owned[:, None, None].astype(hsel.dtype)
                hsel = jax.lax.psum(hsel, comm.axis)
            else:
                hsel = jnp.take_along_axis(
                    hist, ff[:, None, None, None], axis=1)[:, 0]  # [S,B,3]
                if rows_sharded and not mono_rescan:  # cache merged
                    hsel = jax.lax.psum(hsel, comm.axis)
            lmask = (jnp.arange(hsel.shape[1])[None, :] <=
                     fb[:, None]).astype(hsel.dtype)
            lg = jnp.sum(hsel[..., 0] * lmask, axis=1)
            lh = jnp.sum(hsel[..., 1] * lmask, axis=1)
            lc = jnp.sum(hsel[..., 2] * lmask, axis=1)
            pg, ph, pc = tree.sum_grad[sn], tree.sum_hess[sn], tree.count[sn]
            pout = tree.leaf_value[sn]
            rg_, rh_, rc_ = pg - lg, ph - lh, pc - lc
            l1, l2 = hp.lambda_l1, hp.lambda_l2
            shift = leaf_gain(pg, ph, l1, l2, hp.max_delta_step,
                              hp.path_smooth, pc, pout)
            fgain = _split_gain(lg, lh, lc, rg_, rh_, rc_, l1, l2, hp,
                                pout) - shift
            lout = leaf_output(lg, lh, l1, l2, hp.max_delta_step,
                               hp.path_smooth, lc, pout)
            rout = leaf_output(rg_, rh_, l1, l2, hp.max_delta_step,
                               hp.path_smooth, rc_, pout)
            valid = has_f & (lc > 0) & (rc_ > 0) & (forced_feat[sp] >= 0)
            bs = bs._replace(
                gain=jnp.where(valid, fgain, bs.gain),
                feature=jnp.where(valid, ff, bs.feature),
                threshold_bin=jnp.where(valid, fb, bs.threshold_bin),
                default_left=jnp.where(valid, False, bs.default_left),
                left_grad=jnp.where(valid, lg, bs.left_grad),
                left_hess=jnp.where(valid, lh, bs.left_hess),
                left_count=jnp.where(valid, lc, bs.left_count),
                left_output=jnp.where(valid, lout, bs.left_output),
                right_output=jnp.where(valid, rout, bs.right_output),
                cat_bitset=jnp.where(valid[:, None], jnp.uint32(0),
                                     bs.cat_bitset))
            forced_ok = st.forced_ok.at[sn].set(valid).at[m].set(False)
        else:
            forced_ok = st.forced_ok
        # scatter slot results into per-node best arrays (dummy -> row m)
        best = BestSplits(*[
            getattr(st.best, fld).at[sn].set(getattr(bs, fld))
            if fld != "per_feature_gain" else st.best.per_feature_gain
            for fld in BestSplits._fields])
        # ---- 3. choose splits: top-budget by gain ----
        eligible = tree.is_leaf & jnp.isfinite(best.gain) & (best.gain > 0)
        if use_forced:
            # forced nodes split regardless of gain sign/threshold and
            # outrank all gain-chosen candidates in the top-k selection
            eligible = tree.is_leaf & jnp.isfinite(best.gain) & \
                ((best.gain > 0) | forced_ok)
        if max_depth > 0:
            eligible &= tree.depth < max_depth
        gains = jnp.where(eligible[:m], best.gain[:m], -jnp.inf)
        if use_forced:
            gains = jnp.where(eligible[:m] & forced_ok[:m],
                              1e30 + best.gain[:m], gains)
        budget = num_leaves - tree.num_leaves
        k_allowed = jnp.minimum(jnp.asarray(1 if leafwise else k_top),
                                budget)
        top_vals, top_idx = jax.lax.top_k(gains, k_top)
        take = (jnp.arange(k_top) < k_allowed) & jnp.isfinite(top_vals)
        split_mask = jnp.zeros(m + 1, bool).at[top_idx].set(take)
        split_mask = split_mask.at[m].set(False)
        k = jnp.sum(split_mask.astype(jnp.int32))

        # ---- 4. apply splits ----
        order = jnp.cumsum(split_mask.astype(jnp.int32)) - 1   # [M+1]
        child_l = jnp.where(split_mask, tree.num_nodes + 2 * order, m)
        child_r = jnp.where(split_mask, tree.num_nodes + 2 * order + 1, m)
        nodes = jnp.arange(m + 1, dtype=jnp.int32)

        rg = tree.sum_grad - best.left_grad
        rh = tree.sum_hess - best.left_hess
        rc = tree.count - best.left_count
        feat = best.feature
        new_tree = tree._replace(
            split_feature=jnp.where(split_mask, feat, tree.split_feature),
            threshold_bin=jnp.where(split_mask, best.threshold_bin,
                                    tree.threshold_bin),
            default_left=jnp.where(split_mask, best.default_left,
                                   tree.default_left),
            is_cat=jnp.where(split_mask,
                             is_cat_feat[jnp.clip(feat, 0, f - 1)],
                             tree.is_cat),
            cat_bitset=jnp.where(split_mask[:, None], best.cat_bitset,
                                 tree.cat_bitset),
            left=jnp.where(split_mask, child_l, tree.left),
            right=jnp.where(split_mask, child_r, tree.right),
            gain=jnp.where(split_mask, best.gain, tree.gain),
            is_leaf=tree.is_leaf & ~split_mask,
            num_nodes=tree.num_nodes + 2 * k,
            num_leaves=tree.num_leaves + k)
        # children: scatter at child ids (row m is scratch)
        def scat(arr, lv, rv):
            return arr.at[child_l].set(lv).at[child_r].set(rv)
        new_tree = new_tree._replace(
            parent=scat(new_tree.parent, nodes, nodes),
            leaf_value=scat(new_tree.leaf_value, best.left_output,
                            best.right_output),
            sum_grad=scat(new_tree.sum_grad, best.left_grad, rg),
            sum_hess=scat(new_tree.sum_hess, best.left_hess, rh),
            count=scat(new_tree.count, best.left_count, rc),
            depth=scat(new_tree.depth, tree.depth + 1, tree.depth + 1),
            is_leaf=scat(new_tree.is_leaf, split_mask, split_mask),
            split_feature=scat(new_tree.split_feature,
                               jnp.full(m + 1, -1, jnp.int32),
                               jnp.full(m + 1, -1, jnp.int32)),
            left=scat(new_tree.left, jnp.full(m + 1, -1, jnp.int32),
                      jnp.full(m + 1, -1, jnp.int32)),
            right=scat(new_tree.right, jnp.full(m + 1, -1, jnp.int32),
                       jnp.full(m + 1, -1, jnp.int32)))
        # reset best-split state of new children
        new_best = best._replace(
            gain=scat(best.gain, jnp.full(m + 1, -jnp.inf, jnp.float32),
                      jnp.full(m + 1, -jnp.inf, jnp.float32)))
        if use_forced:
            # children of a forced node inherit the spec's subtree
            nf = st.node_force
            spx = jnp.clip(nf, 0, n_spec - 1)
            # inherit only when the forced split itself was applied; a node
            # that fell back to a gain-chosen split stops forcing (the
            # reference stops its BFS when a forced split is inapplicable)
            inherit = split_mask & (nf >= 0) & forced_ok
            node_force = scat(nf,
                              jnp.where(inherit, forced_left[spx], -1),
                              jnp.where(inherit, forced_right[spx], -1))
            zb_ = jnp.zeros(m + 1, bool)
            forced_ok = scat(forced_ok, zb_, zb_)
        else:
            node_force = st.node_force
        if use_cegb and cegb_cfg.has_coupled:
            feat_used = st.feat_used.at[jnp.clip(feat, 0, f - 1)].max(
                split_mask)
        else:
            feat_used = st.feat_used

        # monotone bound propagation (basic method: after a split on a
        # monotone feature, mid = (l_out + r_out)/2 caps the increasing
        # side and floors the other — monotone_constraints.hpp
        # BasicLeafConstraints::UpdateConstraints)
        if hp.has_monotone and not mono_rescan:
            mcf = monotone[jnp.clip(feat, 0, f - 1)]
            mid = (best.left_output + best.right_output) * 0.5
            pmin, pmax = st.cons_min, st.cons_max
            lmin = jnp.where(mcf < 0, jnp.maximum(pmin, mid), pmin)
            lmax = jnp.where(mcf > 0, jnp.minimum(pmax, mid), pmax)
            rmin = jnp.where(mcf > 0, jnp.maximum(pmin, mid), pmin)
            rmax = jnp.where(mcf < 0, jnp.minimum(pmax, mid), pmax)
            cons_min = scat(st.cons_min, lmin, rmin)
            cons_max = scat(st.cons_max, lmax, rmax)
        else:
            # intermediate/advanced recompute bounds from the whole tree
            # at every pass start; the incremental arrays stay unused
            cons_min, cons_max = st.cons_min, st.cons_max
        if use_interaction:
            fsel = (jnp.arange(f)[None, :] ==
                    jnp.clip(feat, 0, f - 1)[:, None]) & \
                split_mask[:, None]                        # [M+1, F]
            child_pm = st.path_mask | fsel
            path_mask = st.path_mask.at[child_l].set(child_pm) \
                .at[child_r].set(child_pm)
        else:
            path_mask = st.path_mask

        # ---- 5. frontier slots for the children ----
        slot_l = jnp.where(split_mask, 2 * order, s)
        slot_r = jnp.where(split_mask, 2 * order + 1, s)
        slot_nodes = jnp.full(s + 1, m, jnp.int32) \
            .at[slot_l].set(jnp.where(split_mask, child_l, m)) \
            .at[slot_r].set(jnp.where(split_mask, child_r, m))[:s]
        slot_of_node = jnp.full(m + 1, -1, jnp.int32) \
            .at[child_l].set(jnp.where(split_mask, slot_l, -1)) \
            .at[child_r].set(jnp.where(split_mask, slot_r, -1)) \
            .at[m].set(-1)

        # ---- 6. route rows through the new splits ----
        pnode = st.row_node
        pm = split_mask[pnode]                               # [N]
        pf = jnp.clip(feat[pnode], 0, f - 1)
        if efb is not None:
            from ..efb import route_bins
            binv = route_bins(bins, pf, efb)
        else:
            binv = jnp.take_along_axis(bins, pf[:, None], axis=1)[:, 0] \
                .astype(jnp.int32)
        thr = best.threshold_bin[pnode]
        isc = is_cat_feat[pf]
        is_nan_bin = missing_is_nan[pf] & (binv == num_bins[pf] - 1)
        bitw = best.cat_bitset[pnode, binv // 32]                  # [N]
        in_set = ((bitw >> (binv % 32).astype(jnp.uint32)) &
                  jnp.uint32(1)) == 1
        go_left = jnp.where(
            isc, in_set,
            jnp.where(is_nan_bin, best.default_left[pnode], binv <= thr))
        row_node = jnp.where(
            pm, jnp.where(go_left, child_l[pnode], child_r[pnode]), pnode)
        if use_cegb and cegb_cfg.has_lazy:
            # rows in a just-split node are now charged for its feature
            # (CalculateOndemandCosts marking, the reference's
            # is_feature_used_ per-datapoint flags)
            row_feat_used = st.row_feat_used.at[jnp.arange(n), pf].max(pm)
        else:
            row_feat_used = st.row_feat_used

        done = (k == 0) | (new_tree.num_leaves >= num_leaves)
        return _GrowState(new_tree, row_node, slot_of_node, slot_nodes,
                          new_best, node_force, forced_ok, feat_used,
                          row_feat_used, cons_min, cons_max, path_mask,
                          hist_cache, st.pass_idx + 1, done)

    final = jax.lax.while_loop(cond, body, state)
    if use_cegb:
        return final.tree, final.row_node, (final.feat_used,
                                            final.row_feat_used)
    return final.tree, final.row_node
