"""Sort/gather-free tree growth with per-pass-sized MXU histograms.

`grow_tree` (grower.py) runs every growth pass at full frontier capacity
S = num_leaves+1 inside one `lax.while_loop`. On TPU the histogram cost of
the MXU kernel scales linearly with S, and the early passes of a tree have
tiny frontiers (1, 2, 4, ... nodes). This variant unrolls the first
ceil(log2(num_leaves)) passes at doubling capacities S_p = 2^(p+1) — the
total histogram work becomes ~2x the final pass instead of ~P x — and
finishes any data-dependent leftovers (leaves that refused to split on
schedule) with a while_loop at full capacity.

Row bookkeeping never touches a sort, gather or scatter: histograms come
from histogram_mxu.build_histograms_mxu (slot-one-hot matmuls) and rows
advance through route_rows_mxu (packed node-table one-hot lookups), the
TPU reformulation of CUDADataPartition::SplitInner
(cuda_data_partition.cu:288-935).

Feature parity vs grow_tree: numerical + categorical splits, NaN routing,
monotone constraints, interaction constraints, feature_fraction_bynode,
extra_trees, forced splits (forced_splits json), CEGB (eager penalties;
lazy per-row feature penalties still fall back), and distributed growth
(the psum'd histogram merge under data/voting-parallel). The remaining
fallbacks to grow_tree are the ones gbdt._mxu_exclusions enforces:
max_bin > 256, non-basic monotone_constraints_method, CEGB with
cegb_penalty_feature_lazy, and EFB configurations the kernel cannot
route (see that method for the authoritative list).
"""

from __future__ import annotations

import functools
import math
import os
import time
import types
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.log import Log
from .grower import _init_tree, TreeArrays
from .histogram import build_histograms
from .histogram_mxu import (_round_up, build_histograms_mxu_auto, fits_v2,
                            fused_route_hist_mxu, node_sums_mxu,
                            node_values_mxu, pack_route_tables,
                            quantize_gradients, route_rows_mxu,
                            unpack_bins_4bit)
from .histogram_pallas import build_histograms_scatter
from .split import (BestSplits, SplitHyperParams, find_best_splits,
                    leaf_gain, leaf_output, _split_gain)
from .split_kernel import find_best_splits_kernel, kernel_supports

__all__ = ["grow_tree_mxu"]


def _prune_to_best_first(tree: TreeArrays, row_node: jax.Array, *,
                         num_leaves: int, m_grow: int, interpret: bool,
                         aux: Tuple = (), rank_gain=None) -> Tuple:
    """Replay the reference's strict best-first growth order
    (serial_tree_learner.cpp:159-210) over an OVERGROWN tree's recorded
    split gains, keep the winning num_leaves-1 splits, and compact.

    The grower expands ~overshoot*num_leaves leaves in batched passes
    (cheap on the MXU), so every split best-first growth would consider
    has a recorded gain; the greedy heap replay is exact whenever the
    overshoot expanded every node best-first would pick. Runs entirely
    on device: num_leaves-1 argmax steps over [nodes] vectors, then a
    cumsum renumbering. Rows are remapped to their nearest kept-leaf
    ancestor, so callers see a standard (tree, row_node) pair. `aux` is
    a tuple of (array, fill) pairs compacted alongside the tree (e.g.
    monotone constraint bounds for re-clipping recomputed leaf values);
    the compacted arrays come back as a trailing tuple."""
    m1g = m_grow + 1
    mf = 2 * num_leaves - 1
    mf1 = mf + 1
    has_split = tree.left >= 0
    # rank_gain overrides the replay ORDER only (forced splits outrank
    # every gain-chosen candidate, serial_tree_learner.cpp:459); the
    # tree keeps its true recorded gains
    gains = jnp.where(has_split,
                      tree.gain if rank_gain is None else rank_gain,
                      -jnp.inf)

    # greedy selection: pop the max-gain available node, make its
    # children available (the reference's leaf queue, with all gains
    # known up front)
    def sim(i, c):
        avail, sel = c
        j = jnp.argmax(avail)
        ok = avail[j] > -jnp.inf
        sel = sel.at[j].set(sel[j] | ok)
        avail = avail.at[j].set(-jnp.inf)
        cl = jnp.where(ok, jnp.clip(tree.left[j], 0, m_grow), m_grow)
        cr = jnp.where(ok, jnp.clip(tree.right[j], 0, m_grow), m_grow)
        avail = avail.at[cl].set(
            jnp.where(cl < m_grow, gains[cl], -jnp.inf))
        avail = avail.at[cr].set(
            jnp.where(cr < m_grow, gains[cr], -jnp.inf))
        return avail, sel

    avail0 = jnp.full(m1g, -jnp.inf, jnp.float32).at[0].set(gains[0])
    _, sel = jax.lax.fori_loop(0, num_leaves - 1, sim,
                               (avail0, jnp.zeros(m1g, bool)))

    # reachability closure by pointer doubling: a node is kept iff every
    # PROPER ancestor was selected (sel is root-connected by construction
    # of the replay, so this is the whole condition). acc[i] starts as
    # sel[parent[i]] and AND-composes up the parent chain in log2 steps
    # instead of a num_leaves-long sequential fori_loop.
    par = jnp.clip(tree.parent, 0, m_grow)
    ids = jnp.arange(m1g, dtype=jnp.int32)
    is_root = ids == 0  # unused scratch slots also carry parent -1
    ptr = jnp.where(is_root, ids, par)
    acc = jnp.where(is_root, True, sel[par])
    for _ in range(max(1, (m1g - 1).bit_length())):
        acc = acc & acc[ptr]
        ptr = ptr[ptr]
    kept = acc & (is_root | (tree.parent >= 0))
    final_leaf = kept & ~sel

    # rows sit in overgrown leaves; ascend to the nearest kept-leaf
    # ancestor — same log2 pointer doubling (final_leaf cuts every
    # root-to-leaf path, so the fixed point always exists)
    nxt = jnp.where(final_leaf | is_root, ids, par)
    for _ in range(max(1, (m1g - 1).bit_length())):
        nxt = nxt[nxt]
    remap = nxt

    # compact: renumber kept nodes densely (order-preserving, root = 0)
    new_id = jnp.cumsum(kept.astype(jnp.int32)) - 1
    dst = jnp.where(kept, jnp.clip(new_id, 0, mf), mf)

    def compact(arr, fill):
        out = jnp.full((mf1,) + arr.shape[1:], fill, arr.dtype)
        return out.at[dst].set(arr)

    def child_new(c):
        cc = jnp.clip(c, 0, m_grow)
        return jnp.where(sel & (c >= 0), new_id[cc], -1)

    parent_new = jnp.where(tree.parent >= 0, new_id[par], -1)
    pruned = TreeArrays(
        split_feature=compact(
            jnp.where(sel, tree.split_feature, -1), -1),
        threshold_bin=compact(jnp.where(sel, tree.threshold_bin, 0), 0),
        default_left=compact(sel & tree.default_left, False),
        is_cat=compact(sel & tree.is_cat, False),
        cat_bitset=compact(
            jnp.where(sel[:, None], tree.cat_bitset, 0), 0),
        left=compact(child_new(tree.left), -1),
        right=compact(child_new(tree.right), -1),
        parent=compact(parent_new, -1),
        leaf_value=compact(tree.leaf_value, 0.0),
        sum_grad=compact(tree.sum_grad, 0.0),
        sum_hess=compact(tree.sum_hess, 0.0),
        count=compact(tree.count, 0.0),
        gain=compact(jnp.where(sel, tree.gain, 0.0), 0.0),
        depth=compact(tree.depth, 0),
        is_leaf=compact(final_leaf, False),
        num_nodes=jnp.sum(kept.astype(jnp.int32)),
        num_leaves=jnp.sum(final_leaf.astype(jnp.int32)))

    # per-row lookup of the compacted kept-leaf id (exact hi/lo one-hot
    # matmul; ids < 2*num_leaves are f32-exact)
    composed = new_id[remap].astype(jnp.float32)
    row_new = node_values_mxu(row_node, composed,
                              interpret=interpret).astype(jnp.int32)
    if aux:
        return pruned, row_new, tuple(compact(a, fill) for a, fill in aux)
    return pruned, row_new


def _kernel_cap(s: int) -> int:
    """Histogram-kernel slot capacity for a pass scanning `s` slots with
    sibling subtraction: the all-fresh bulk needs s/2 (one slot per smaller
    child), plus slack for stale pairs (leaves split later than the pass
    that scanned them need both children built, 2 slots)."""
    return min(s, s // 2 + 8)


def autotune_hist_backend(bins, *, num_slots: int, bmax: int,
                          num_features: int = 0, double_prec: bool = True,
                          quantized: bool = True, const_hess: float = 0.0,
                          row_block_scatter: int = 1024):
    """One-shot on-device histogram-backend measurement (hist_backend=
    auto): build one frontier histogram at the dominant frontier width
    with the MXU one-hot kernel and the Pallas scatter kernel on the
    REAL bin matrix, time the post-compile call of each, and return
    (choice, timings_ms). Synthetic gradients/slots are used — kernel
    runtime is data-independent (dense dots, static shapes), so the
    measurement transfers to training. Runs host-side BEFORE the first
    grow_tree_mxu dispatch because the backend is a static (jit) arg;
    the result is pinned for the whole run and recorded in
    observability (boosting/gbdt.py). A backend that fails to compile
    or run times as +inf, so the other one wins."""
    n = bins.shape[0]
    g = jnp.linspace(-127.0, 127.0, n, dtype=jnp.float32)
    g = jnp.round(g) if quantized else g * 1e-2
    h = jnp.ones(n, jnp.float32)
    cnt = jnp.ones(n, jnp.float32)
    slot = (jnp.arange(n, dtype=jnp.int32) % num_slots)

    def _mxu():
        return build_histograms_mxu_auto(
            bins, g, h, cnt, slot, num_slots=num_slots, bmax=bmax,
            double_prec=double_prec, quantized=quantized,
            num_features=num_features, const_hess=const_hess)

    def _pallas():
        return build_histograms_scatter(
            bins, g, h, cnt, slot, num_slots=num_slots, bmax=bmax,
            double_prec=double_prec, quantized=quantized,
            num_features=num_features, const_hess=const_hess,
            row_block=row_block_scatter)

    timings = {}
    for name, fn in (("mxu", _mxu), ("pallas", _pallas)):
        try:
            jax.block_until_ready(fn())       # compile + warm
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            timings[name] = (time.perf_counter() - t0) * 1e3
        except Exception as exc:  # pragma: no cover - device-specific
            Log.warning("hist_backend autotune: %s backend failed (%s)",
                        name, exc)
            timings[name] = float("inf")
    choice = min(timings, key=timings.get)
    if timings[choice] == float("inf"):
        choice = "mxu"
    return choice, timings


#: index of the done flag in the growth state tuple (shared with the
#: level-pipelined driver, grower_pipeline.py)
_DONE = 9


def growth_plan(*, num_leaves: int, overshoot: float = 0.0,
                tail_split_cap: int = 0, hist_subtraction: bool = True,
                bridge_gate: float = 0.0):
    """Static growth schedule shared by the monolithic grower and the
    level-pipelined driver (grower_pipeline.py).

    Everything here derives from static config only — no array in
    sight — so the pipelined driver can size its stage-program
    sequence (init + len(schedule) passes + bridge + fixups + final)
    on the host without tracing anything. _make_grow_core consumes the
    same plan, so the two drivers cannot disagree on the schedule.

    Tuning history (docs/PerfNotes.md rounds 3-4): with overshoot the
    fixup frontier runs FULL-width (s_fix = min(LGBM_TPU_SFIX, s_max),
    default 512) — the round-3 late-tree decay (2.69 -> 2.3 trees/s)
    was narrow fixup frontiers chasing 65-200 leftover splits; the
    bridge gate (growth_bridge_gate) skips the s_max-wide bridge sweep
    once num_leaves >= gate * L_g, never gating below the actual leaf
    budget so the prune keeps its num_leaves target."""
    over = overshoot if overshoot and overshoot >= 1.0 else 0.0
    if over:
        tail_split_cap = 0
    L_g = int(math.ceil(num_leaves * over)) if over else num_leaves
    m_pad = _round_up(2 * L_g, 128)
    s_max = L_g + 1
    schedule = []
    s_p = 1
    while s_p < s_max and len(schedule) < 32:
        schedule.append(min(max(2 * s_p, 2), s_max))
        s_p *= 2
    if over:
        s_fix = min(int(os.environ.get("LGBM_TPU_SFIX", 512)), s_max)
        sk_fix = s_fix if hist_subtraction else None
    elif tail_split_cap <= 0:
        s_fix = min(64, s_max)
        sk_fix = _kernel_cap(s_fix) if hist_subtraction else None
    else:
        s_fix = min(s_max, max(16, 2 * tail_split_cap))
        sk_fix = _kernel_cap(s_fix) if hist_subtraction else None
    k_fix = max(1, s_fix // 2)
    if over and bridge_gate > 0:
        gate_leaves = max(int(bridge_gate * L_g), num_leaves)
    else:
        gate_leaves = None

    def m_cap_of(s_p):
        # pass p holds < 2*S_p node ids; slice the route tables to the
        # lane-aligned bound (sweep docstring)
        return min(m_pad, _round_up(max(2 * s_p, 2), 128))

    return types.SimpleNamespace(
        over=over, L_g=L_g, m_pad=m_pad, s_max=s_max, schedule=schedule,
        s_fix=s_fix, sk_fix=sk_fix, k_fix=k_fix, gate_leaves=gate_leaves,
        m_cap_of=m_cap_of, tail_split_cap=tail_split_cap,
        # stage-program count for the pipelined driver: init + one per
        # scheduled pass + bridge + ONE shared fixup program (traced
        # iteration arg) + final epilogue
        n_stage_programs=len(schedule) + 4,
        max_fixup_dispatch=max(0, L_g - len(schedule) - 1))


def _make_grow_core(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                    cnt_weight: jax.Array, feature_mask: jax.Array,
                    num_bins: jax.Array, missing_is_nan: jax.Array,
                    is_cat_feat: jax.Array, *, num_leaves: int,
                    max_depth: int,
                    hp: SplitHyperParams, bmax: int,
                    monotone: Optional[jax.Array] = None,
                    interaction_groups: Optional[tuple] = None,
                    feature_fraction_bynode: float = 1.0,
                    rng_key: Optional[jax.Array] = None,
                    interpret: bool = False,
                    hist_double_prec: bool = True,
                    tail_split_cap: int = 0,
                    hist_subtraction: bool = True,
                    overshoot: float = 0.0,
                    bridge_gate: float = 0.0,
                    psum_axis: Optional[str] = None,
                    quantized_grad: bool = False,
                    use_scan_kernel: bool = False,
                    packed4: bool = False,
                    const_hessian: float = 0.0,
                    hist_backend: str = "mxu",
                    partition_impl: str = "auto",
                    efb=None,
                    forced=None,
                    cegb_cfg=None,
                    cegb_state=None,
                    debug_info: bool = False,
                    quant_state=None):
    """Trace the shared growth-program pieces for one tree and return
    them as a namespace: the initial state tuple (`state0`), the
    per-pass transition closures (`one_pass`/`cond_pass`/`fixup_pass`),
    the unrolled doubling `schedule`, the fixup-capacity constants
    (`s_fix`/`sk_fix`/`k_fix`), the bridge gate (`apply_gate`) and the
    `epilogue` (flush + prune + exact refit).

    Both grow_tree_mxu (ONE monolithic jit program per tree) and the
    level-pipelined driver (grower_pipeline.py — one jit program per
    stage, dispatched asynchronously from the host) trace THIS code,
    so the two paths run the same math on the same state layout and
    stay byte-identical — the pipeline's parity oracle is structural,
    not re-implemented.

    quant_state: optional (h_grad, h_hess, hist_scale) triple from an
    earlier stage's `quant_state_out` — skips the (deterministic)
    gradient-quantization prologue so per-stage programs reuse the
    init stage's quantized gradients instead of recomputing them.

    tail_split_cap > 0 enables hybrid growth: while the leaf budget is
    loose (remaining leaves >= splittable leaves) passes split every
    eligible leaf — the regime where batched and strict best-first growth
    agree — and once the budget binds, passes commit at most
    tail_split_cap splits before re-ranking, approaching the reference's
    strict leaf-wise order (serial_tree_learner.cpp:159-210) as the cap
    shrinks. Retained gains make tail passes cheap: only the new
    children's histograms are built.

    hist_subtraction applies the reference's sibling-histogram trick
    (serial_tree_learner.cpp:311-326): kernel slots are assigned only to
    the SMALLER child of each fresh split; the larger sibling's histogram
    is parent minus smaller, with the parent row pulled from the previous
    pass's scan tensor by an exact one-hot matmul. Nodes split later than
    the pass that scanned them (stale parents) get both children built
    (2 slots), and split selection is throttled so the per-pass slot cost
    fits the kernel capacity (~s/2 instead of s slots per pass).

    packed4=True marks `bins` as 4-bit packed storage (pack_bins_4bit,
    the reference's 4-bit DenseBin, src/io/dense_bin.hpp:42): the kernels
    unpack nibbles in VMEM, so HBM holds half the bin bytes. Exact —
    identical trees to unpacked storage.

    hist_backend selects the per-pass histogram kernel: "mxu" keeps the
    one-hot matmul kernels (fused route+hist when it fits VMEM),
    "pallas" routes with route_rows_mxu(emit_counts=True) and builds
    via the slot-grouped scatter kernel (histogram_pallas — per-row
    cost independent of the frontier width), "scatter" routes the same
    way and builds with the XLA segment-sum oracle. Must be a RESOLVED
    backend, never "auto" — the one-shot autotune
    (autotune_hist_backend, driven from boosting/gbdt.py) happens
    before jit dispatch because the choice is a static argument. In the
    quantized posture all three backends produce bit-identical
    histograms (integer sums, order-independent below 2^24), hence
    byte-identical trees. EFB data ignores the selector (bundle-space
    histograms are an MXU-kernel-only formulation).

    efb (EfbDev, efb.py) marks `bins` as the BUNDLED matrix [N, Fb]:
    histograms build in bundle space ([S, Fb, Bb, 3] — the flop and
    state win on wide-sparse data) and are expanded per pass back to
    original features for the split scan; routing decodes original
    local bins through efb.loc_table inside the kernels. Same math as
    the portable grower's EFB path (grower.py), so trees match it."""
    n = bins.shape[0]
    f = int(num_bins.shape[0]) if (packed4 or efb is not None) \
        else bins.shape[1]
    nf_packed = f if packed4 else 0
    # kernel-space dims: bundle columns/bins when EFB is active
    fk = bins.shape[1] if efb is not None else f
    bk = efb.bundle_bmax if efb is not None else bmax
    loc_tbl = efb.loc_table if efb is not None else None
    # segmented EFB routes by bundle-position RANGES packed into the
    # node tables (histogram_mxu efb_range) — no per-row decode
    efb_seg = efb is not None and efb.scan is not None
    # overshoot > 1 switches to overgrow-and-prune: grow toward
    # overshoot*num_leaves leaves with unthrottled batched passes, then
    # replay the exact best-first selection over the recorded gains
    # (_prune_to_best_first). Replaces the tail throttle entirely.
    plan = growth_plan(num_leaves=num_leaves, overshoot=overshoot,
                       tail_split_cap=tail_split_cap,
                       hist_subtraction=hist_subtraction,
                       bridge_gate=bridge_gate)
    over, L_g, m_pad, s_max = plan.over, plan.L_g, plan.m_pad, plan.s_max
    tail_split_cap = plan.tail_split_cap
    m = 2 * L_g - 1
    m1 = m + 1
    k_top = L_g - 1
    w_cat = (bmax + 31) // 32
    P_all = (s_max + 1) // 2 + 2   # pair-state capacity (subtraction)

    # psum_axis != None runs this grower INSIDE shard_map as the
    # data-parallel learner: rows are sharded, per-pass histograms are
    # all-reduced over ICI (the reference's Reduce-Scatter of histograms,
    # data_parallel_tree_learner.cpp:184-186 — here a psum, with every
    # shard scanning all features), and every shard takes identical
    # split decisions, so the tree is replicated without a sync.
    def _allred(x):
        return jax.lax.psum(x, psum_axis) if psum_axis else x

    # quantized_grad: stochastically-rounded integer grad/hess feed
    # 3-channel histograms (1.67x fewer MXU flops than the 5-channel
    # double-bf16 scheme); the final leaf values are recomputed exactly
    # at the end, so quantization only perturbs the split SEARCH.
    quant = quantized_grad
    # const_hessian != 0: per-row hessians are const x cnt_weight (the
    # reference's IsConstantHessian fast path) — the kernels drop the
    # hessian channel and reconstruct it exactly as const x count, so
    # hessian sums carry NO quantization noise and every histogram dot
    # runs one channel lighter (3 -> 2 quantized, 5 -> 3 exact)
    ch = const_hessian
    root_c = _allred(jnp.sum(cnt_weight))
    if quant:
        if quant_state is not None:
            # stage programs reuse the init stage's quantized gradients
            # (deterministic, so recomputing yields the same bits — this
            # only saves the per-stage O(N) quantization work)
            h_grad, h_hess, hist_scale = quant_state
            gscale, hscale = hist_scale[0], hist_scale[1]
        else:
            qkey = rng_key if rng_key is not None \
                else jax.random.PRNGKey(0)
            qkey = jax.random.fold_in(qkey, 6271)
            # decorrelate rounding noise across trees even when no
            # per-tree key is plumbed (the sharded grower path): fold in
            # gradient bits so each iteration's noise differs — reusing
            # one u per row every tree would make its rounding error
            # systematic in the ensemble
            qkey = jax.random.fold_in(
                qkey,
                jax.lax.bitcast_convert_type(jnp.sum(grad), jnp.int32))
            h_grad, h_hess, gscale, hscale = quantize_gradients(
                grad, None if ch else hess, qkey, pmax_axis=psum_axis)
            if h_hess is None:
                h_hess = hess  # never read: the channel builder drops it
            hist_scale = jnp.stack([gscale, hscale, jnp.float32(1.0)])
        # hist-consistent root sums (exact integer sums x scale), so
        # right-child = parent - left stays internally consistent
        root_g = _allred(jnp.sum(h_grad)) * gscale
        root_h = jnp.float32(ch) * root_c if ch else \
            _allred(jnp.sum(h_hess)) * hscale
    else:
        h_grad, h_hess = grad, hess
        hist_scale = jnp.ones(3, jnp.float32)   # unused without quant
        root_g = _allred(jnp.sum(grad))
        root_h = jnp.float32(ch) * root_c if ch else \
            _allred(jnp.sum(hess))
    root_val = leaf_output(root_g, root_h, hp.lambda_l1, hp.lambda_l2,
                           hp.max_delta_step)
    tree0 = _init_tree(m, root_g, root_h, root_c, root_val,
                       bitset_words=w_cat)

    best0 = BestSplits(
        gain=jnp.full(m1, -jnp.inf, jnp.float32),
        feature=jnp.full(m1, -1, jnp.int32),
        threshold_bin=jnp.zeros(m1, jnp.int32),
        default_left=jnp.zeros(m1, bool),
        left_grad=jnp.zeros(m1, jnp.float32),
        left_hess=jnp.zeros(m1, jnp.float32),
        left_count=jnp.zeros(m1, jnp.float32),
        left_output=jnp.zeros(m1, jnp.float32),
        right_output=jnp.zeros(m1, jnp.float32),
        per_feature_gain=jnp.zeros((1, 1), jnp.float32),
        cat_bitset=jnp.zeros((m1, w_cat), jnp.uint32))

    use_interaction = interaction_groups is not None and \
        len(interaction_groups) > 0
    if use_interaction:
        import numpy as _np
        gm = _np.zeros((len(interaction_groups), f), _np.bool_)
        for gi, grp in enumerate(interaction_groups):
            for fi in grp:
                if 0 <= fi < f:
                    gm[gi, fi] = True
        group_masks = jnp.asarray(gm)
        path_mask0 = jnp.zeros((m1, f), bool)
    else:
        group_masks = None
        path_mask0 = jnp.zeros((1, 1), bool)
    use_bynode = feature_fraction_bynode < 1.0 and rng_key is not None
    k_bynode = max(1, int(round(feature_fraction_bynode * f)))

    feat_tbl = jnp.stack([num_bins.astype(jnp.float32),
                          missing_is_nan.astype(jnp.float32)], axis=1)

    # Forced splits (reference SerialTreeLearner::ForceSplits,
    # serial_tree_learner.cpp:459) and CEGB penalties
    # (cost_effective_gradient_boosting.hpp) on the MXU path — same
    # semantics as the portable grower (grower.py:266-300). The lazy
    # per-row CEGB penalty is NOT supported here (it needs an [N, F]
    # charge matrix rebuilt per pass); callers route has_lazy configs to
    # the portable grower.
    use_forced = forced is not None
    if use_forced:
        forced_feat, forced_bin, forced_left, forced_right = forced
        n_spec = forced_feat.shape[0]
    use_cegb = cegb_cfg is not None
    if use_cegb:
        if cegb_cfg.has_lazy:
            raise NotImplementedError(
                "cegb_penalty_feature_lazy runs on the portable grower")
        cegb_coupled, _cegb_lazy, feat_used0, row_feat_used0 = cegb_state
    else:
        feat_used0 = jnp.zeros(1, bool)
    node_force0 = (jnp.full(m1, -1, jnp.int32).at[0].set(0)
                   if use_forced else jnp.full(1, -1, jnp.int32))
    forced_ok0 = jnp.zeros(m1 if use_forced else 1, bool)
    was_forced0 = jnp.zeros(m1 if use_forced else 1, bool)

    def hist_cfg(s):
        # empirically tuned on v5e: wider feature chunks while the output
        # block fits comfortably in VMEM, narrower for big frontiers
        return dict(row_block=2048, fchunk=7 if s <= 64 else 4)

    def sweep(row_node, tbl_c, member_c, nslots, m_cap=None):
        """Route rows through the previous pass's packed tables and build
        the frontier histograms — fused single sweep when the histogram
        block fits VMEM, else the two-kernel fallback (wide datasets).
        Under psum_axis the local histograms are all-reduced, so the
        subtraction/scan math downstream sees global sums.

        m_cap statically slices the node tables: pass p can only hold
        node ids < 2*S_p, so early passes route against a 128-wide
        one-hot instead of the full m_pad (~8x less route work for the
        first ~6 passes of a 255-leaf tree)."""
        if m_cap is not None and m_cap < m_pad:
            tbl_c = tbl_c[:m_cap]
            member_c = member_c[:m_cap]
        if hist_backend != "mxu" and efb is None:
            # non-MXU histogram backends: route + per-slot counts in one
            # sweep (the on-device partition), then build via the
            # scatter kernel or the XLA oracle
            rn, rs, cts = route_rows_mxu(
                bins, row_node, tbl_c, member_c, feat_tbl,
                num_features=nf_packed, emit_counts=True,
                num_slots=nslots, interpret=interpret)
            if hist_backend == "pallas":
                h = build_histograms_scatter(
                    bins, h_grad, h_hess, cnt_weight, rs,
                    num_slots=nslots, bmax=bk, num_features=nf_packed,
                    quantized=quant, double_prec=hist_double_prec,
                    const_hess=ch, slot_counts=cts,
                    partition_impl=partition_impl, interpret=interpret)
            else:  # "scatter": the pure-XLA segment-sum oracle
                ub = unpack_bins_4bit(bins, f) if packed4 else bins
                h = build_histograms(ub, h_grad, h_hess, rs, cnt_weight,
                                     num_slots=nslots, bmax=bk)
                if ch:
                    # reconstruct hessian sums exactly as const x count,
                    # matching the kernel backends' channel drop
                    h = h.at[..., 1].set(h[..., 2] * jnp.float32(ch))
            if quant:
                h = h * hist_scale
            return _allred(h), rn
        # measured on v5e: small frontiers run ~15% cheaper at half
        # blocks, large ones prefer the wider block. EFB keeps rb=1024
        # in BOTH modes: expansion's original-feature route side needs
        # the VMEM headroom (a 2048 block compiled to a real 136 MB
        # OOM at 250-column bundles), and for bundle-range mode larger
        # adaptive blocks measured WORSE (0.059 vs 0.182 trees/s on the
        # low-cardinality shape, docs/PerfNotes.md round 4)
        rw = f if (efb is not None and not efb_seg) else 0
        if efb is not None:
            rb = 1024
        elif nslots <= 64:
            rb = int(os.environ.get("LGBM_TPU_RB_SMALL", 2048))
        else:
            # large frontiers: the chained per-pass microbench
            # (helpers/microbench_pass.py, v5e round 5) measured 8192
            # fastest at every sk > 64 (sk=72: 20.0 ms vs 26.9 at 4096;
            # sk=136: 34.6 vs 38.9) — fewer grid steps re-visiting the
            # VMEM-resident accumulator. Fall back block-by-block when
            # the bigger input working set would bust the VMEM budget
            # (e.g. 5-channel exact grads at wide frontiers).
            for rb in (int(os.environ.get("LGBM_TPU_RB_LARGE", 8192)),
                       4096, 2048):
                if fits_v2(nslots, fk, bk, hist_double_prec, quant,
                           route_width=rw, row_block=rb, const_hess=ch):
                    break
        if fits_v2(nslots, fk, bk, hist_double_prec, quant,
                   route_width=rw, row_block=rb, const_hess=ch):
            h, rn = fused_route_hist_mxu(
                bins, h_grad, h_hess, cnt_weight, row_node, tbl_c,
                member_c, feat_tbl, num_slots=nslots, bmax=bk,
                has_cat=hp.has_categorical, quantized=quant,
                double_prec=hist_double_prec, num_features=nf_packed,
                loc_table=None if efb_seg else loc_tbl,
                efb_range=efb_seg, row_block=rb, const_hess=ch,
                interpret=interpret)
        else:
            rn, rs = route_rows_mxu(bins, row_node, tbl_c, member_c,
                                    feat_tbl, num_features=nf_packed,
                                    loc_table=None if efb_seg
                                    else loc_tbl, efb_range=efb_seg,
                                    interpret=interpret)
            h = build_histograms_mxu_auto(
                bins, h_grad, h_hess, cnt_weight, rs, num_slots=nslots,
                bmax=bk, interpret=interpret, quantized=quant,
                double_prec=hist_double_prec, num_features=nf_packed,
                const_hess=ch,
                **hist_cfg(nslots))
        if quant:
            h = h * hist_scale  # integer sums -> gradient units
        return _allred(h), rn

    def one_pass(s, st, pass_idx, k_cap=None, sk_next=None, m_cap=None,
                 sk_self=None):
        """One growth pass at scan capacity `s` (python int). sk_next is
        the kernel-slot capacity of the NEXT pass (selection is throttled
        so committed splits' children fit it)."""
        (tree, row_node, tbl_c, member_c, slot_nodes, best, cons_min,
         cons_max, path_mask, done, parent_hist, pair_parent, pair_sleft,
         pair_kstart, node_force, forced_ok_st, feat_used,
         was_forced) = st
        sn = slot_nodes[:s]
        if sk_next is None:
            sk_next = _kernel_cap(min(2 * s, s_max)) if hist_subtraction \
                else min(2 * s, s_max)

        if hist_subtraction:
            # build only the slots assigned by the previous pass (smaller
            # siblings + both children of stale parents) ...
            sk = sk_self if sk_self is not None else _kernel_cap(s)
            kern, row_node = sweep(row_node, tbl_c, member_c, sk,
                                   m_cap=m_cap)
            # ... and reconstruct the full scan tensor [s, F, B, 3] with
            # ONE 0/+-1 selection matmul against [kernel rows ;
            # parent-pair rows]: row s (pair i = s//2, left iff s even)
            # is  +kern[ks_i]                  (smaller side)
            #     +parent_hist[i] - kern[ks_i] (larger side, fresh pair)
            #     +kern[ks_i + 1]              (other side, stale pair).
            # Replaces per-part one-hot pulls + an interleaving stack +
            # a [s_max, F, B, 3] dynamic_update_slice (measured 22.3 ms
            # -> 3.8 ms per pass at the bench shape; the parent rows are
            # carried pair-indexed in parent_hist [P_all, F*B*3], half
            # the old scan_hist state).
            npairs = (s + 1) // 2
            ks = pair_kstart[:npairs]
            pp = pair_parent[:npairs]
            sl = pair_sleft[:npairs]
            stale = pp < 0
            kern2 = kern.reshape(sk, -1)
            sides = jnp.arange(s, dtype=jnp.int32)
            pi = sides // 2
            is_small = (sides % 2 == 0) == sl[pi]
            st_i = stale[pi]
            ks_i = ks[pi]
            iota_k = jnp.arange(sk, dtype=jnp.int32)[None, :]
            hit_small = (ks_i[:, None] == iota_k).astype(jnp.float32)
            # empty pairs carry ks = -1: no column matches either way
            ks2_i = jnp.where(st_i & (ks_i >= 0), ks_i + 1, -1)
            hit_stale2 = (ks2_i[:, None] == iota_k).astype(jnp.float32)
            mk = jnp.where(is_small[:, None], hit_small,
                           jnp.where(st_i[:, None], hit_stale2,
                                     -hit_small))
            iota_p = jnp.arange(P_all, dtype=jnp.int32)[None, :]
            mp = jnp.where((~is_small & ~st_i)[:, None],
                           (pi[:, None] == iota_p).astype(jnp.float32),
                           0.0)
            hist = jax.lax.dot_general(
                jnp.concatenate([mk, mp], axis=1),
                jnp.concatenate([kern2, parent_hist], axis=0),
                dimension_numbers=(((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32) \
                .reshape(s, fk, bk, 3)
        else:
            hist, row_node = sweep(row_node, tbl_c, member_c, s,
                                   m_cap=m_cap)
        if efb is not None and efb.scan is None:
            # expansion fallback: subtraction/parent state live in
            # bundle space (above); the split scan runs on original
            # features — expand here (linear, so it commutes with the
            # psum and the sibling subtraction; efb.expand_histograms)
            from ..efb import expand_histograms
            hist_scan = expand_histograms(hist, efb)
        else:
            # unbundled, or bundled with the segmented scan (which
            # consumes the bundle-space histogram directly)
            hist_scan = hist

        slot_fmask = jnp.broadcast_to(feature_mask[None, :], (s, f))
        if use_bynode:
            ku = jax.random.fold_in(rng_key, pass_idx)
            u = jax.random.uniform(ku, (s, f))
            u = jnp.where(feature_mask[None, :] > 0, u, jnp.inf)
            kth = jnp.sort(u, axis=1)[:, k_bynode - 1][:, None]
            slot_fmask = slot_fmask * (u <= kth)
        if use_interaction:
            pm = path_mask[sn]
            subset = jnp.all((~pm[:, None, :]) | group_masks[None, :, :],
                             axis=2)
            allowed = jnp.einsum("sg,gf->sf", subset.astype(jnp.float32),
                                 group_masks.astype(jnp.float32)) > 0
            allowed = allowed | pm
            slot_fmask = slot_fmask * allowed
        rand_bins = None
        if hp.extra_trees and rng_key is not None:
            kr = jax.random.fold_in(jax.random.fold_in(rng_key, 7919),
                                    pass_idx)
            rand_bins = jax.random.randint(kr, (s, f), 0, bmax)
        if use_cegb:
            # per-(slot, feature) DeltaGain penalty (reference
            # CostEfficientGradientBoosting::DetlaGain; the portable
            # form at grower.py:375-393 minus the lazy term)
            gp = cegb_cfg.tradeoff * cegb_cfg.penalty_split * \
                tree.count[sn][:, None] * jnp.ones((s, f), jnp.float32)
            if cegb_cfg.has_coupled:
                gp += cegb_cfg.tradeoff * cegb_coupled[None, :] * \
                    (~feat_used)[None, :].astype(jnp.float32)
        else:
            gp = None

        # fused single-launch scan kernel (split_kernel.py, the
        # CUDABestSplitFinder analog). Measured ~4% SLOWER than the XLA
        # scan in-context on v5e (the scan is NOT this backend's
        # bottleneck; XLA fuses it well) — kept opt-in for backends
        # where launch overhead dominates.
        if efb is not None and efb.scan is not None:
            # segmented bundle-space scan: [S, Fb, Bb] in, original-
            # feature BestSplits out (split_bundled.py)
            from .split_bundled import find_best_splits_bundled
            bs = find_best_splits_bundled(
                hist_scan, tree.sum_grad[sn], tree.sum_hess[sn],
                tree.count[sn], tree.leaf_value[sn], num_bins,
                missing_is_nan, is_cat_feat, slot_fmask, hp, efb,
                monotone=monotone, cons_min=cons_min[sn],
                cons_max=cons_max[sn], depth=tree.depth[sn],
                rand_bins=rand_bins, gain_penalty=gp)
        elif use_scan_kernel and kernel_supports(hp) and \
                rand_bins is None and gp is None:
            bs = find_best_splits_kernel(
                hist_scan, tree.sum_grad[sn], tree.sum_hess[sn],
                tree.count[sn],
                tree.leaf_value[sn], num_bins, missing_is_nan, is_cat_feat,
                slot_fmask, hp, monotone=monotone, cons_min=cons_min[sn],
                cons_max=cons_max[sn], depth=tree.depth[sn],
                interpret=interpret)
        else:
            bs = find_best_splits(
                hist_scan, tree.sum_grad[sn], tree.sum_hess[sn],
                tree.count[sn],
                tree.leaf_value[sn], num_bins, missing_is_nan, is_cat_feat,
                slot_fmask, hp, monotone=monotone, cons_min=cons_min[sn],
                cons_max=cons_max[sn], depth=tree.depth[sn],
                rand_bins=rand_bins, gain_penalty=gp)

        if use_forced:
            # override gain-chosen splits on forced nodes with the
            # spec's (feature, threshold) — stats gathered from the scan
            # tensor like FeatureHistogram::GatherInfoForThreshold
            # (feature_histogram.hpp:862+; portable form grower.py:456).
            # The sweep already psum'd the histograms, so sums are
            # global here under data-parallel.
            nf_slot = node_force[sn]                         # [S]
            has_f = (nf_slot >= 0) & (sn < m)
            sp = jnp.clip(nf_slot, 0, n_spec - 1)
            ff = jnp.clip(forced_feat[sp], 0, f - 1)         # [S]
            fb_t = forced_bin[sp]
            if efb is not None and efb.scan is not None:
                # bundle-space: expand ONE feature per slot (the same
                # gather + default-mass reconstruction as
                # efb.expand_histograms, restricted to ff[slot])
                bbw = hist_scan.shape[2]
                flath = hist_scan.reshape(s, -1, 3)
                csum_b = jnp.cumsum(hist_scan, axis=2).reshape(s, -1, 3)
                fp = efb.flat_pos[ff]                        # [S, bmax]
                gath = jnp.take_along_axis(flath, fp[..., None], axis=1)
                total_b = jnp.sum(hist_scan[:, 0], axis=1)   # [S, 3]
                colf = efb.col_of_feat[ff]
                hi_i = colf * bbw + efb.seg_hi[ff]
                lo_gate = (efb.seg_lo[ff] > 0)[:, None]
                lo_i = colf * bbw + jnp.maximum(efb.seg_lo[ff] - 1, 0)
                hi_s = jnp.take_along_axis(
                    csum_b, hi_i[:, None, None], axis=1)[:, 0]
                lo_s = jnp.take_along_axis(
                    csum_b, lo_i[:, None, None], axis=1)[:, 0] * lo_gate
                dmass = total_b - (hi_s - lo_s)              # [S, 3]
                hsel = jnp.where(efb.is_valid_pos[ff][..., None], gath,
                                 0.0)
                hsel = jnp.where(efb.is_default_pos[ff][..., None],
                                 dmass[:, None], hsel)       # [S, bmax, 3]
            else:
                hsel = jnp.take_along_axis(
                    hist_scan, ff[:, None, None, None], axis=1)[:, 0]
            lmask = (jnp.arange(hsel.shape[1])[None, :] <=
                     fb_t[:, None]).astype(hsel.dtype)
            lg_f = jnp.sum(hsel[..., 0] * lmask, axis=1)
            lh_f = jnp.sum(hsel[..., 1] * lmask, axis=1)
            lc_f = jnp.sum(hsel[..., 2] * lmask, axis=1)
            pg, ph = tree.sum_grad[sn], tree.sum_hess[sn]
            pc, pout = tree.count[sn], tree.leaf_value[sn]
            rg_f, rh_f, rc_f = pg - lg_f, ph - lh_f, pc - lc_f
            l1_, l2_ = hp.lambda_l1, hp.lambda_l2
            shift = leaf_gain(pg, ph, l1_, l2_, hp.max_delta_step,
                              hp.path_smooth, pc, pout)
            fgain = _split_gain(lg_f, lh_f, lc_f, rg_f, rh_f, rc_f, l1_,
                                l2_, hp, pout) - shift
            lout_f = leaf_output(lg_f, lh_f, l1_, l2_, hp.max_delta_step,
                                 hp.path_smooth, lc_f, pout)
            rout_f = leaf_output(rg_f, rh_f, l1_, l2_, hp.max_delta_step,
                                 hp.path_smooth, rc_f, pout)
            valid_f = has_f & (lc_f > 0) & (rc_f > 0) & \
                (forced_feat[sp] >= 0)
            bs = bs._replace(
                gain=jnp.where(valid_f, fgain, bs.gain),
                feature=jnp.where(valid_f, ff, bs.feature),
                threshold_bin=jnp.where(valid_f, fb_t, bs.threshold_bin),
                default_left=jnp.where(valid_f, False, bs.default_left),
                left_grad=jnp.where(valid_f, lg_f, bs.left_grad),
                left_hess=jnp.where(valid_f, lh_f, bs.left_hess),
                left_count=jnp.where(valid_f, lc_f, bs.left_count),
                left_output=jnp.where(valid_f, lout_f, bs.left_output),
                right_output=jnp.where(valid_f, rout_f, bs.right_output),
                cat_bitset=jnp.where(valid_f[:, None], jnp.uint32(0),
                                     bs.cat_bitset))
            forced_ok_st = forced_ok_st.at[sn].set(valid_f) \
                .at[m].set(False)

        best = BestSplits(*[
            getattr(best, fld).at[sn].set(getattr(bs, fld))
            if fld != "per_feature_gain" else best.per_feature_gain
            for fld in BestSplits._fields])

        # ---- choose splits: top-budget by gain; children fit next pass
        eligible = tree.is_leaf & jnp.isfinite(best.gain) & (best.gain > 0)
        if use_forced:
            # forced nodes split regardless of gain sign and outrank all
            # gain-chosen candidates (serial_tree_learner.cpp:459 BFS)
            eligible = tree.is_leaf & jnp.isfinite(best.gain) & \
                ((best.gain > 0) | forced_ok_st)
        if max_depth > 0:
            eligible &= tree.depth < max_depth
        gains = jnp.where(eligible[:m], best.gain[:m], -jnp.inf)
        if use_forced:
            gains = jnp.where(eligible[:m] & forced_ok_st[:m],
                              1e30 + best.gain[:m], gains)
        budget = L_g - tree.num_leaves
        if k_cap is None:
            k_cap = min(k_top, s)  # children fill the next pass (2*s)
        k_allowed = jnp.minimum(jnp.asarray(k_cap, jnp.int32), budget)
        if tail_split_cap > 0:
            # hybrid growth: once fewer leaves remain than candidates, the
            # commit ORDER matters (a committed split's children would have
            # outranked lower candidates under best-first growth) — throttle
            # to tail_split_cap splits per pass and re-rank
            # >= : even at n_elig == budget the commit order matters (a
            # committed split's children can outrank remaining candidates)
            n_elig = jnp.sum(gains[:m] > -jnp.inf)
            k_allowed = jnp.where(
                n_elig >= budget,
                jnp.minimum(k_allowed, tail_split_cap), k_allowed)
        top_vals, top_idx = jax.lax.top_k(gains, k_top)
        take = (jnp.arange(k_top) < k_allowed) & jnp.isfinite(top_vals)
        ssn = jnp.full(m1, -1, jnp.int32).at[sn].set(
            jnp.arange(s, dtype=jnp.int32)).at[m].set(-1)
        if hist_subtraction:
            # throttle so the selected splits' children fit the next
            # pass's kernel slots: fresh parents cost 1 (smaller child
            # only), stale parents 2 (both children built)
            cand_fresh = ssn[top_idx] >= 0
            cumcost = jnp.cumsum(jnp.where(cand_fresh, 1, 2))
            take &= cumcost <= sk_next
        split_mask = jnp.zeros(m1, bool).at[top_idx].set(take)
        split_mask = split_mask.at[m].set(False)
        k = jnp.sum(split_mask.astype(jnp.int32))

        # ---- apply splits
        order = jnp.cumsum(split_mask.astype(jnp.int32)) - 1
        child_l = jnp.where(split_mask, tree.num_nodes + 2 * order, m)
        child_r = jnp.where(split_mask, tree.num_nodes + 2 * order + 1, m)
        nodes = jnp.arange(m1, dtype=jnp.int32)
        rg = tree.sum_grad - best.left_grad
        rh = tree.sum_hess - best.left_hess
        rc = tree.count - best.left_count
        feat = best.feature
        new_tree = tree._replace(
            split_feature=jnp.where(split_mask, feat, tree.split_feature),
            threshold_bin=jnp.where(split_mask, best.threshold_bin,
                                    tree.threshold_bin),
            default_left=jnp.where(split_mask, best.default_left,
                                   tree.default_left),
            is_cat=jnp.where(split_mask,
                             is_cat_feat[jnp.clip(feat, 0, f - 1)],
                             tree.is_cat),
            cat_bitset=jnp.where(split_mask[:, None], best.cat_bitset,
                                 tree.cat_bitset),
            left=jnp.where(split_mask, child_l, tree.left),
            right=jnp.where(split_mask, child_r, tree.right),
            gain=jnp.where(split_mask, best.gain, tree.gain),
            is_leaf=tree.is_leaf & ~split_mask,
            num_nodes=tree.num_nodes + 2 * k,
            num_leaves=tree.num_leaves + k)

        def scat(arr, lv, rv):
            return arr.at[child_l].set(lv).at[child_r].set(rv)
        neg1 = jnp.full(m1, -1, jnp.int32)
        new_tree = new_tree._replace(
            parent=scat(new_tree.parent, nodes, nodes),
            leaf_value=scat(new_tree.leaf_value, best.left_output,
                            best.right_output),
            sum_grad=scat(new_tree.sum_grad, best.left_grad, rg),
            sum_hess=scat(new_tree.sum_hess, best.left_hess, rh),
            count=scat(new_tree.count, best.left_count, rc),
            depth=scat(new_tree.depth, tree.depth + 1, tree.depth + 1),
            is_leaf=scat(new_tree.is_leaf, split_mask, split_mask),
            split_feature=scat(new_tree.split_feature, neg1, neg1),
            left=scat(new_tree.left, neg1, neg1),
            right=scat(new_tree.right, neg1, neg1))
        new_best = best._replace(
            gain=scat(best.gain, jnp.full(m1, -jnp.inf, jnp.float32),
                      jnp.full(m1, -jnp.inf, jnp.float32)))

        if use_forced:
            # children of an applied forced split inherit the spec's
            # subtree; a node whose forced split was inapplicable stops
            # forcing (the reference halts its BFS there)
            spx = jnp.clip(node_force, 0, n_spec - 1)
            inherit = split_mask & (node_force >= 0) & forced_ok_st
            node_force = scat(node_force,
                              jnp.where(inherit, forced_left[spx], -1),
                              jnp.where(inherit, forced_right[spx], -1))
            was_forced = was_forced | (split_mask & forced_ok_st)
            zb_ = jnp.zeros(m1, bool)
            forced_ok_st = scat(forced_ok_st, zb_, zb_)
        if use_cegb and cegb_cfg.has_coupled:
            feat_used = feat_used.at[jnp.clip(feat, 0, f - 1)].max(
                split_mask)

        if hp.has_monotone:
            mcf = monotone[jnp.clip(feat, 0, f - 1)]
            mid = (best.left_output + best.right_output) * 0.5
            pmin, pmax = cons_min, cons_max
            lmin = jnp.where(mcf < 0, jnp.maximum(pmin, mid), pmin)
            lmax = jnp.where(mcf > 0, jnp.minimum(pmax, mid), pmax)
            rmin = jnp.where(mcf > 0, jnp.maximum(pmin, mid), pmin)
            rmax = jnp.where(mcf < 0, jnp.minimum(pmax, mid), pmax)
            cons_min = scat(cons_min, lmin, rmin)
            cons_max = scat(cons_max, lmax, rmax)
        if use_interaction:
            fsel = (jnp.arange(f)[None, :] ==
                    jnp.clip(feat, 0, f - 1)[:, None]) & split_mask[:, None]
            child_pm = path_mask | fsel
            path_mask = path_mask.at[child_l].set(child_pm) \
                .at[child_r].set(child_pm)

        # ---- scan slots for the children (find_best_splits ordering)
        slot_l = jnp.where(split_mask, 2 * order, -1)
        slot_r = jnp.where(split_mask, 2 * order + 1, -1)
        slot_nodes = jnp.full(s_max + 1, m, jnp.int32) \
            .at[jnp.where(split_mask, slot_l, s_max)].set(
                jnp.where(split_mask, child_l, m)) \
            .at[jnp.where(split_mask, slot_r, s_max)].set(
                jnp.where(split_mask, child_r, m))[:s_max]

        # ---- kernel slots + pair bookkeeping for the next pass
        if hist_subtraction:
            fresh_node = ssn >= 0
            small_left = best.left_count <= rc
            cost_node = jnp.where(split_mask,
                                  jnp.where(fresh_node, 1, 2), 0)
            kstart = jnp.cumsum(cost_node) - cost_node
            route_l = jnp.where(~fresh_node | small_left, kstart, -1)
            route_r = jnp.where(~fresh_node, kstart + 1,
                                jnp.where(small_left, -1, kstart))
            pidx = jnp.where(split_mask, order, P_all)
            pair_parent = jnp.full(P_all + 1, -1, jnp.int32) \
                .at[pidx].set(jnp.where(fresh_node, ssn, -1))[:P_all]
            pair_sleft = jnp.full(P_all + 1, True) \
                .at[pidx].set(fresh_node & small_left | ~fresh_node)[:P_all]
            pair_kstart = jnp.full(P_all + 1, -1, jnp.int32) \
                .at[pidx].set(kstart)[:P_all]
            # carry the fresh pairs' parent scan rows into the next pass
            # (pair-indexed; stale pairs keep zero rows, never read)
            sel_p = (pair_parent[:, None] ==
                     jnp.arange(s, dtype=jnp.int32)[None, :]) \
                .astype(jnp.float32)
            parent_hist = jax.lax.dot_general(
                sel_p, hist.reshape(s, -1),
                dimension_numbers=(((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)
        else:
            route_l, route_r = slot_l, slot_r
        slot_of_node = jnp.full(m1, -1, jnp.int32) \
            .at[child_l].set(jnp.where(split_mask, route_l, -1)) \
            .at[child_r].set(jnp.where(split_mask, route_r, -1)) \
            .at[m].set(-1)

        # ---- pack the split tables; the NEXT pass's fused sweep routes
        # rows through them (the final flush after the loops applies the
        # last pass's tables — routing is idempotent, see
        # fused_route_hist_mxu)
        fclip = jnp.clip(feat, 0, f - 1)
        tbl_c, member_c = pack_route_tables(
            split_mask, fclip, best.threshold_bin,
            best.default_left, new_tree.is_cat, child_l, child_r,
            slot_of_node, new_tree.cat_bitset, m_pad, bmax,
            bcol=efb.col_of_feat[fclip] if efb is not None else None,
            efb=efb)

        done = (k == 0) | (new_tree.num_leaves >= L_g)
        return (new_tree, row_node, tbl_c, member_c, slot_nodes, new_best,
                cons_min, cons_max, path_mask, done, parent_hist,
                pair_parent, pair_sleft, pair_kstart, node_force,
                forced_ok_st, feat_used, was_forced)

    # initial tables: nothing split, root (node 0) sits in kernel slot 0,
    # so the first sweep is an identity route + a root histogram. Pair 0
    # of the first pass is the root, built as a "stale" pair so its
    # histogram comes straight from kernel slot 0 (no parent exists)
    tbl0, member0 = pack_route_tables(
        jnp.zeros(m1, bool), jnp.zeros(m1, jnp.int32),
        jnp.zeros(m1, jnp.int32), jnp.zeros(m1, bool),
        jnp.zeros(m1, bool), jnp.full(m1, m, jnp.int32),
        jnp.full(m1, m, jnp.int32),
        jnp.full(m1, -1, jnp.int32).at[0].set(0),
        jnp.zeros((m1, w_cat), jnp.uint32), m_pad, bmax, efb=efb)
    state = (tree0,
             jnp.zeros(n, jnp.int32),                     # row_node
             tbl0, member0,                               # route tables
             jnp.full(s_max, m, jnp.int32).at[0].set(0),  # slot_nodes
             best0,
             jnp.full(m1, -jnp.inf, jnp.float32),
             jnp.full(m1, jnp.inf, jnp.float32),
             path_mask0,
             jnp.asarray(False),
             jnp.zeros((P_all if hist_subtraction else 1,
                        fk * bk * 3 if hist_subtraction else 1),
                       jnp.float32),                       # parent_hist
             jnp.full(P_all, -1, jnp.int32),               # pair_parent
             jnp.full(P_all, True),                        # pair_sleft
             jnp.full(P_all, -1, jnp.int32).at[0].set(0),  # pair_kstart
             node_force0, forced_ok0, feat_used0, was_forced0)

    def cond_pass(s, st, pass_idx, k_cap=None, sk_next=None, m_cap=None):
        # skip whole passes once growth is done — e.g. the full-capacity
        # bridge pass after a tree that completed on schedule (a free
        # S=s_max histogram otherwise)
        return jax.lax.cond(
            st[_DONE], lambda st_: st_,
            lambda st_: one_pass(s, st_, pass_idx, k_cap, sk_next,
                                 m_cap), st)

    # ---- unrolled doubling schedule (growth_plan: shared with the
    # level-pipelined driver, which needs the stage count host-side) ----
    schedule = plan.schedule
    m_cap_of = plan.m_cap_of

    # ---- fixup loop for off-schedule leftovers ----
    # the best-first tail often splits only a couple of leaves per pass
    # (each new child is the only fresh candidate), so fixup passes run at
    # a small frontier capacity; the inactive-block skip in the histogram
    # kernel makes them cheap. One bridging pass at full capacity first:
    # it scans ALL children of the last scheduled pass (slots up to s_max)
    # while capping its own splits so the children fit the fixup frontier.
    # tail passes are per-pass-floor bound; with a hybrid-growth cap the
    # frontier only ever holds 2*cap fresh children, so shrink the fixup
    # scan capacity accordingly
    # NOTE on gates, two different animals (r3 vs r4):
    # - gating at the TARGET (stop fixups once num_leaves >= num_leaves,
    #   coverage 1.0x) was measured in r3 at +0.85 trees/s but
    #   -3.5e-3 AUC@95 — REJECTED; the replay regularly keeps
    #   fixup-grown splits, so overshoot quality needs most of the
    #   chase. The r3 answer was widening the fixup frontier instead.
    # - gating near the OVERSHOOT (growth_bridge_gate, below: skip the
    #   bridge once num_leaves >= gate*L_g, coverage ~gate*overshoot)
    #   costs only ~2.4e-4 AUC@115 for +6% — the r4 bench posture.
    # fixup capacities and the bridge gate are part of the static
    # growth_plan (see its docstring for the round-3/round-4 tuning
    # history: full-frontier s_fix, LGBM_TPU_SFIX, growth_bridge_gate)
    s_fix, sk_fix, k_fix = plan.s_fix, plan.sk_fix, plan.k_fix
    gate_leaves = plan.gate_leaves

    def apply_gate(st):
        if gate_leaves is None:
            return st
        st_l = list(st)
        st_l[_DONE] = st_l[_DONE] | (st[0].num_leaves >= gate_leaves)
        return tuple(st_l)

    def fixup_pass(st, it):
        """One fixup pass at the tail frontier capacity; `it` is the
        (traced) fixup iteration counter starting at len(schedule)+1."""
        return one_pass(s_fix, st, it + 1000, k_cap=k_fix,
                        sk_next=sk_fix, sk_self=sk_fix)

    def epilogue(state, fixup_iters):
        """Flush routing, prune to best-first, exact leaf refit; the
        grow_tree_mxu return value from a finished state tuple."""
        pre_prune_leaves = state[0].num_leaves

        # flush the routing of the last pass's splits (sweeps route at
        # the START of a pass, so the final commits have not moved rows
        # yet)
        row_node, _ = route_rows_mxu(bins, state[1], state[2], state[3],
                                     feat_tbl, num_features=nf_packed,
                                     loc_table=None if efb_seg
                                     else loc_tbl,
                                     efb_range=efb_seg,
                                     interpret=interpret)
        tree_out = state[0]
        cmin, cmax = state[6], state[7]
        if over:
            # forced splits outrank every gain-chosen split in the
            # replay order (their recorded gains stay true)
            rank = (state[0].gain + jnp.where(state[17], 1e30, 0.0)) \
                if use_forced else None
            if quant and hp.has_monotone:
                tree_out, row_node, (cmin, cmax) = _prune_to_best_first(
                    tree_out, row_node, num_leaves=num_leaves, m_grow=m,
                    interpret=interpret, rank_gain=rank,
                    aux=((cmin, -jnp.inf), (cmax, jnp.inf)))
            else:
                tree_out, row_node = _prune_to_best_first(
                    tree_out, row_node, num_leaves=num_leaves, m_grow=m,
                    interpret=interpret, rank_gain=rank)
        if quant:
            # exact leaf refit: per-leaf double-bf16 sums over the final
            # row->leaf vector, psum'd under data-parallel; quantization
            # then never reaches the fitted outputs (reference closed
            # form, feature_histogram.hpp:737
            # CalculateSplittedLeafOutput). One caveat: with
            # path_smooth > 0 the parent reference values are the
            # growth-time (quantized) ones — mirroring the reference,
            # which also smooths toward the parent's output as it stood
            # at split time, but those carry rounding noise here.
            nn = tree_out.leaf_value.shape[0]
            sums = _allred(node_sums_mxu(row_node, grad, hess,
                                         cnt_weight, num_nodes=nn,
                                         interpret=interpret))
            pout = tree_out.leaf_value[
                jnp.clip(tree_out.parent, 0, nn - 1)]
            ex_val = leaf_output(sums[:, 0], sums[:, 1], hp.lambda_l1,
                                 hp.lambda_l2, hp.max_delta_step,
                                 hp.path_smooth, sums[:, 2], pout)
            if hp.has_monotone:
                ex_val = jnp.clip(ex_val, cmin, cmax)
            lf = tree_out.is_leaf
            tree_out = tree_out._replace(
                leaf_value=jnp.where(lf, ex_val, tree_out.leaf_value),
                sum_grad=jnp.where(lf, sums[:, 0], tree_out.sum_grad),
                sum_hess=jnp.where(lf, sums[:, 1], tree_out.sum_hess),
                count=jnp.where(lf, sums[:, 2], tree_out.count))
        if debug_info:
            return tree_out, row_node, (fixup_iters, pre_prune_leaves)
        if use_cegb:
            # feature-used flags persist across trees (portable
            # contract, grower.py:674); no lazy state here, flags pass
            # through
            return tree_out, row_node, (state[16], row_feat_used0)
        return tree_out, row_node

    return types.SimpleNamespace(
        state0=state, schedule=schedule, s_max=s_max, m_pad=m_pad,
        L_g=L_g, s_fix=s_fix, sk_fix=sk_fix, k_fix=k_fix,
        gate_leaves=gate_leaves, m_cap_of=m_cap_of,
        one_pass=one_pass, cond_pass=cond_pass, apply_gate=apply_gate,
        fixup_pass=fixup_pass, epilogue=epilogue,
        quant_state_out=(h_grad, h_hess, hist_scale))


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "max_depth", "hp", "bmax",
                     "interaction_groups", "feature_fraction_bynode",
                     "interpret", "hist_double_prec", "tail_split_cap",
                     "hist_subtraction", "overshoot", "bridge_gate",
                     "psum_axis",
                     "quantized_grad", "use_scan_kernel", "packed4",
                     "const_hessian", "hist_backend", "partition_impl",
                     "cegb_cfg", "debug_info"))
def grow_tree_mxu(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                  cnt_weight: jax.Array, feature_mask: jax.Array,
                  num_bins: jax.Array, missing_is_nan: jax.Array,
                  is_cat_feat: jax.Array, *, num_leaves: int,
                  max_depth: int,
                  hp: SplitHyperParams, bmax: int,
                  monotone: Optional[jax.Array] = None,
                  interaction_groups: Optional[tuple] = None,
                  feature_fraction_bynode: float = 1.0,
                  rng_key: Optional[jax.Array] = None,
                  interpret: bool = False,
                  hist_double_prec: bool = True,
                  tail_split_cap: int = 0,
                  hist_subtraction: bool = True,
                  overshoot: float = 0.0,
                  bridge_gate: float = 0.0,
                  psum_axis: Optional[str] = None,
                  quantized_grad: bool = False,
                  use_scan_kernel: bool = False,
                  packed4: bool = False,
                  const_hessian: float = 0.0,
                  hist_backend: str = "mxu",
                  partition_impl: str = "auto",
                  efb=None,
                  forced=None,
                  cegb_cfg=None,
                  cegb_state=None,
                  debug_info: bool = False
                  ) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree; same contract as grower.grow_tree (serial mode).

    One monolithic jit program: the doubling schedule, the bridge pass
    and the data-dependent fixup while_loop all run in ONE device
    dispatch (zero host syncs per tree — the right shape for a remoted
    accelerator, docs/PerfNotes.md round 3). The level-pipelined
    driver (grower_pipeline.py, config level_pipeline=true) dispatches
    the SAME passes as separate stage programs with speculative
    host-side fixup dispatch; this function is its byte-parity oracle.
    See _make_grow_core for the full parameter semantics
    (tail_split_cap, hist_subtraction, packed4, hist_backend,
    partition_impl, efb)."""
    core = _make_grow_core(
        bins, grad, hess, cnt_weight, feature_mask, num_bins,
        missing_is_nan, is_cat_feat, num_leaves=num_leaves,
        max_depth=max_depth, hp=hp, bmax=bmax, monotone=monotone,
        interaction_groups=interaction_groups,
        feature_fraction_bynode=feature_fraction_bynode,
        rng_key=rng_key, interpret=interpret,
        hist_double_prec=hist_double_prec,
        tail_split_cap=tail_split_cap,
        hist_subtraction=hist_subtraction, overshoot=overshoot,
        bridge_gate=bridge_gate, psum_axis=psum_axis,
        quantized_grad=quantized_grad, use_scan_kernel=use_scan_kernel,
        packed4=packed4, const_hessian=const_hessian,
        hist_backend=hist_backend, partition_impl=partition_impl,
        efb=efb, forced=forced, cegb_cfg=cegb_cfg,
        cegb_state=cegb_state, debug_info=debug_info)

    state = core.state0
    for p, s_p in enumerate(core.schedule):
        state = core.cond_pass(s_p, state, jnp.asarray(p, jnp.int32),
                               m_cap=core.m_cap_of(s_p))

    state = core.apply_gate(state)
    if core.schedule:
        state = core.cond_pass(core.s_max, state, len(core.schedule),
                               k_cap=core.k_fix, sk_next=core.sk_fix)

    def cond(c):
        st, it = c
        return (~st[_DONE]) & (it < core.L_g)

    def body(c):
        st, it = c
        return core.fixup_pass(st, it), it + 1

    state, it_final = jax.lax.while_loop(
        cond, body,
        (state, jnp.asarray(len(core.schedule) + 1, jnp.int32)))
    return core.epilogue(state, it_final - (len(core.schedule) + 1))
