"""Level-pipelined tree growth: the monolithic grower's passes as
separately-dispatched stage programs with speculative fixup.

``grow_tree_mxu`` runs the doubling schedule, bridge pass and fixup
while_loop as ONE jit program — zero host syncs per tree, the right
shape for a remoted accelerator where every dispatch pays a tunnel
round-trip (docs/PerfNotes.md round 3).  This driver dispatches the
SAME passes (traced from the same ``_make_grow_core``) as separate
stage programs, which buys three things on a locally-attached device:

- level *k+1*'s histogram build is enqueued before level *k*'s results
  are host-visible (JAX async dispatch keeps the device busy; the host
  never blocks between stages),
- the data-dependent fixup while_loop becomes bounded *speculative*
  host dispatch: chunks of ``lookahead`` fixup stages are enqueued and
  a LAGGED done flag (``copy_to_host_async`` of the previous chunk's
  done bit) decides whether to stop — the host reads a value that is
  already on its way, so polling never stalls the device,
- the host regains a per-level observation point (span traces, stall
  polls, future early-exit heuristics) that the monolithic program
  hides inside the device.

Parity contract: every stage traces ``_make_grow_core`` — the same
code the monolith traces — and a speculative fixup dispatched past the
done flag is an *identity* ``lax.cond`` no-op, exactly like a skipped
``while_loop`` iteration.  Quantized gradients are computed once by the
init stage and threaded through (``quant_state``), so stochastic
rounding bits match the monolith's single quantization.  The retained
``grow_tree_mxu`` is the byte-parity oracle (tests/test_level_pipeline.py
asserts byte-equal model.txt across objectives).

Program count: ``init + len(schedule) passes + bridge + ONE fixup
program (iteration index is a traced scalar) + final`` =
``growth_plan(...).n_stage_programs`` — bounded per (shape, config),
guarded by the compile-accounting entries ``grow_stage_*``.

Ineligible configs fall back to the monolith: ``psum_axis`` (the
sharded grower runs inside shard_map — staged host dispatch would
desynchronize the collective schedule across ranks) and ``debug_info``
(its fixup-iteration count is a device-side while_loop artifact).
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .grower import TreeArrays
from .grower_mxu import _DONE, _make_grow_core, grow_tree_mxu, growth_plan

__all__ = ["LevelPipelineStats", "grow_tree_pipelined"]

# static argnames mirror grow_tree_mxu's plus the stage selector
@functools.partial(
    jax.jit,
    static_argnames=("stage", "num_leaves", "max_depth", "hp", "bmax",
                     "interaction_groups", "feature_fraction_bynode",
                     "interpret", "hist_double_prec", "tail_split_cap",
                     "hist_subtraction", "overshoot", "bridge_gate",
                     "psum_axis", "quantized_grad", "use_scan_kernel",
                     "packed4", "const_hessian", "hist_backend",
                     "partition_impl", "cegb_cfg", "debug_info"))
def _stage(bins, grad, hess, cnt_weight, feature_mask, num_bins,
           missing_is_nan, is_cat_feat, *, stage,
           state=None, quant_state=None, it=None, fixup_iters=None,
           num_leaves: int, max_depth: int, hp, bmax: int,
           monotone=None, interaction_groups=None,
           feature_fraction_bynode: float = 1.0, rng_key=None,
           interpret: bool = False, hist_double_prec: bool = True,
           tail_split_cap: int = 0, hist_subtraction: bool = True,
           overshoot: float = 0.0, bridge_gate: float = 0.0,
           psum_axis=None, quantized_grad: bool = False,
           use_scan_kernel: bool = False, packed4: bool = False,
           const_hessian: float = 0.0, hist_backend: str = "mxu",
           partition_impl: str = "auto", efb=None, forced=None,
           cegb_cfg=None, cegb_state=None, debug_info: bool = False):
    """One pipeline stage program. `stage` is "init", ("pass", p),
    "bridge", "fixup" (traced `it`) or "final" (traced `fixup_iters`);
    XLA dead-code-eliminates the parts of the shared core a given
    stage doesn't touch."""
    core = _make_grow_core(
        bins, grad, hess, cnt_weight, feature_mask, num_bins,
        missing_is_nan, is_cat_feat, num_leaves=num_leaves,
        max_depth=max_depth, hp=hp, bmax=bmax, monotone=monotone,
        interaction_groups=interaction_groups,
        feature_fraction_bynode=feature_fraction_bynode,
        rng_key=rng_key, interpret=interpret,
        hist_double_prec=hist_double_prec,
        tail_split_cap=tail_split_cap,
        hist_subtraction=hist_subtraction, overshoot=overshoot,
        bridge_gate=bridge_gate, psum_axis=psum_axis,
        quantized_grad=quantized_grad, use_scan_kernel=use_scan_kernel,
        packed4=packed4, const_hessian=const_hessian,
        hist_backend=hist_backend, partition_impl=partition_impl,
        efb=efb, forced=forced, cegb_cfg=cegb_cfg,
        cegb_state=cegb_state, debug_info=debug_info,
        quant_state=quant_state)
    if stage == "init":
        return core.state0, core.quant_state_out
    if isinstance(stage, tuple) and stage[0] == "pass":
        p = stage[1]
        s_p = core.schedule[p]
        return core.cond_pass(s_p, state, jnp.asarray(p, jnp.int32),
                              m_cap=core.m_cap_of(s_p))
    if stage == "bridge":
        st = core.apply_gate(state)
        if core.schedule:
            st = core.cond_pass(core.s_max, st, len(core.schedule),
                                k_cap=core.k_fix, sk_next=core.sk_fix)
        return st
    if stage == "fixup":
        # speculative dispatch past the done flag must be an identity
        # no-op — the exact semantics of a skipped while_loop iteration
        # in the monolith (same cond: (~done) & (it < L_g))
        return jax.lax.cond(
            (~state[_DONE]) & (it < core.L_g),
            lambda st: core.fixup_pass(st, it), lambda st: st, state)
    if stage == "final":
        return core.epilogue(state, fixup_iters)
    raise ValueError(f"unknown stage {stage!r}")


@dataclass
class LevelPipelineStats:
    """Per-tree dispatch accounting for the staged driver.

    ``fixup_speculative`` is a LOWER bound: it counts fixups known (via
    the lagged done poll) to have run as identity no-ops — fixups that
    became no-ops mid-chunk are not separately visible without an extra
    host sync, which is exactly what this driver avoids."""
    stages: int = 0                 # total stage programs dispatched
    fixup_dispatched: int = 0
    fixup_speculative: int = 0
    done_polls: int = 0
    stopped_early: bool = False
    fallback: Optional[str] = None  # set when the monolith ran instead
    lookahead: int = 0
    wall_seconds: float = 0.0
    entries: list = field(default_factory=list)  # compile-account names


def _cache_size() -> int:
    try:
        return _stage._cache_size()
    except Exception:
        return -1


def _dispatch(entry: str, stats: LevelPipelineStats, compiles, kwargs):
    """Run one stage, attributing its wall to the compile accounting
    entry `entry` iff the jit cache grew (first sighting = trace +
    compile + first dispatch, compiles.py bracketing semantics)."""
    before = _cache_size()
    t0 = time.perf_counter()
    out = _stage(**kwargs)
    if compiles is not None:
        grew = (before >= 0 and _cache_size() > before)
        compiles.record(entry, time.perf_counter() - t0 if grew else 0.0,
                        compiled=grew)
    stats.stages += 1
    stats.entries.append(entry)
    return out


def grow_tree_pipelined(bins, grad, hess, cnt_weight, feature_mask,
                        num_bins, missing_is_nan, is_cat_feat, *,
                        lookahead: int = 4, iteration: int = 0,
                        stats: Optional[LevelPipelineStats] = None,
                        **kw) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree via staged level-pipelined dispatch; same contract
    (arguments and return value, bit-for-bit) as ``grow_tree_mxu``.

    `lookahead` fixup stages are enqueued per chunk before the host
    consults the previous chunk's (already-in-flight) done flag.
    `stats`, when supplied, receives the dispatch accounting; the
    observability registry's ``level_pipeline`` family is updated
    either way when observability is enabled."""
    if kw.get("psum_axis") is not None or kw.get("debug_info", False):
        # ineligible (module docstring) — the oracle IS the answer
        out = grow_tree_mxu(bins, grad, hess, cnt_weight, feature_mask,
                            num_bins, missing_is_nan, is_cat_feat, **kw)
        if stats is not None:
            stats.fallback = ("psum_axis"
                              if kw.get("psum_axis") is not None
                              else "debug_info")
        return out

    from ..observability import registry as _obs

    st_acc = stats if stats is not None else LevelPipelineStats()
    st_acc.lookahead = lookahead = max(1, int(lookahead))
    compiles = _obs.compiles
    plan = growth_plan(
        num_leaves=kw["num_leaves"],
        overshoot=kw.get("overshoot", 0.0),
        tail_split_cap=kw.get("tail_split_cap", 0),
        hist_subtraction=kw.get("hist_subtraction", True),
        bridge_gate=kw.get("bridge_gate", 0.0))
    common = dict(bins=bins, grad=grad, hess=hess,
                  cnt_weight=cnt_weight, feature_mask=feature_mask,
                  num_bins=num_bins, missing_is_nan=missing_is_nan,
                  is_cat_feat=is_cat_feat, **kw)

    t0 = time.time()
    w0 = time.perf_counter()
    state, quant_state = _dispatch(
        "grow_stage_init", st_acc, compiles,
        dict(common, stage="init"))
    common["quant_state"] = quant_state
    for p in range(len(plan.schedule)):
        state = _dispatch(
            f"grow_stage_pass_{p}", st_acc, compiles,
            dict(common, stage=("pass", p), state=state))
    state = _dispatch(
        "grow_stage_bridge", st_acc, compiles,
        dict(common, stage="bridge", state=state))

    # ---- speculative fixup: chunks of `lookahead`, lagged done poll ----
    max_fix = plan.max_fixup_dispatch
    it = len(plan.schedule) + 1
    prev_done = None
    while st_acc.fixup_dispatched < max_fix:
        chunk = min(lookahead, max_fix - st_acc.fixup_dispatched)
        for _ in range(chunk):
            state = _dispatch(
                "grow_stage_fixup", st_acc, compiles,
                dict(common, stage="fixup", state=state,
                     it=jnp.asarray(it, jnp.int32)))
            it += 1
            st_acc.fixup_dispatched += 1
        done_ref = state[_DONE]
        try:
            done_ref.copy_to_host_async()
        except AttributeError:
            pass
        if prev_done is not None:
            st_acc.done_polls += 1
            if bool(prev_done):   # lagged read — likely already landed
                st_acc.fixup_speculative += chunk
                st_acc.stopped_early = True
                break
        prev_done = done_ref

    out = _dispatch(
        "grow_stage_final", st_acc, compiles,
        dict(common, stage="final", state=state,
             # only consumed under debug_info, which falls back above —
             # the monolith's value would be the executed (not
             # dispatched) fixup count
             fixup_iters=jnp.asarray(st_acc.fixup_dispatched, jnp.int32)))
    st_acc.wall_seconds = time.perf_counter() - w0
    _obs.record_level_pipeline(
        iteration, t0, st_acc.wall_seconds, st_acc.stages,
        st_acc.fixup_dispatched, st_acc.fixup_speculative,
        st_acc.stopped_early)
    return out
