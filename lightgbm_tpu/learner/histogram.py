"""Histogram construction: the hottest op of the framework.

Redesign of the reference histogram path (Bin::ConstructHistogram
dense_bin.hpp:143-160, row-wise MultiValBinWrapper train_share_states.h:37-80,
and the CUDA shared-memory kernels cuda_histogram_constructor.cu:18-307):
instead of per-leaf gathers over index ranges, ONE fused pass over all rows
scatter-adds (grad, hess, count) keyed by (frontier_slot, feature, bin).
Rows whose node is not being histogrammed this pass are routed to a trash
slot — shapes stay static, no data-dependent row gathers.

Layout: hist[s, f, b, c] with rectangular bin axis padded to `bmax`
(per-feature valid-bin masking happens in the split scan). Accumulation in
float32; channel 2 carries exact data counts (the reference tracks counts
outside the histogram; keeping them in-band costs 1/3 more HBM but makes
min_data_in_leaf exact on device).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["build_histograms"]


@functools.partial(jax.jit, static_argnames=("num_slots", "bmax",
                                             "feature_block"))
def build_histograms(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                     row_slot: jax.Array, cnt: jax.Array = None, *,
                     num_slots: int, bmax: int,
                     feature_block: int = 8) -> jax.Array:
    """Build per-slot histograms.

    Args:
      bins: [N, F] integer bin matrix (uint8/uint16/int32).
      grad, hess: [N] float32 gradients/hessians (bagging weights already
        folded in).
      row_slot: [N] int32 slot of each row's node; -1 routes to trash.
      num_slots: static number of live slots S.
      bmax: static padded bin count per feature.
      feature_block: features scatter-added per scan step (bounds the
        transient [N*block] index buffer).

    Returns:
      hist: [S, F, bmax, 3] float32 (sum_grad, sum_hess, count).
    """
    n, f = bins.shape
    slot = row_slot.astype(jnp.int32)
    if cnt is None:
        cnt = jnp.ones_like(grad)
    data = jnp.stack([grad, hess, cnt], axis=-1)  # [N, 3]

    fb = min(feature_block, f)
    num_blocks = (f + fb - 1) // fb
    pad_f = num_blocks * fb
    if pad_f != f:
        bins = jnp.pad(bins, ((0, 0), (0, pad_f - f)))
    bins_i = bins.astype(jnp.int32)

    # Each scan step scatter-adds one block of `fb` features; every feature
    # in the block owns its own [S, bmax] plane: id = (slot*fb + j)*bmax + bin.
    num_seg = (num_slots * fb + 1) * bmax
    trash = num_slots * fb * bmax
    blocks = jnp.arange(pad_f, dtype=jnp.int32).reshape(num_blocks, fb)

    def block_step(_, fb_idx):
        cols = jnp.take(bins_i, fb_idx, axis=1)           # [N, fb]
        j = jnp.arange(fb, dtype=jnp.int32)[None, :]
        ids = (slot[:, None] * fb + j) * bmax + cols
        valid = (fb_idx[None, :] < f) & (slot[:, None] >= 0) & \
                (slot[:, None] < num_slots)
        ids = jnp.where(valid, ids, trash)
        vals = jnp.broadcast_to(data[:, None, :], (n, fb, 3))
        seg = jax.ops.segment_sum(
            vals.reshape(n * fb, 3), ids.reshape(n * fb),
            num_segments=num_seg)
        return None, seg[:num_slots * fb * bmax].reshape(
            num_slots, fb, bmax, 3)

    _, hists = jax.lax.scan(block_step, None, blocks)
    # hists: [num_blocks, S, fb, bmax, 3] -> [S, num_blocks*fb, bmax, 3]
    hist = jnp.transpose(hists, (1, 0, 2, 3, 4)).reshape(
        num_slots, pad_f, bmax, 3)
    return hist[:, :f]
